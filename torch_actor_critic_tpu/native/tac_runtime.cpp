// tac_runtime: native synchronization core for the host-side runtime.
//
// The reference's host runtime is OpenMPI (process launch + collectives,
// ref sac/mpi.py); on TPU the gradient path rides XLA collectives over
// ICI instead (parallel/dp.py), but the *host* side still needs a fast
// process-parallel substrate to feed the chip: MuJoCo/dm_control physics
// is single-threaded C called from Python (SURVEY.md §7 hard part (e)).
//
// This library provides the low-latency cross-process synchronization
// layer under envs/vec_env.py's ParallelEnvPool: futex wait/wake on
// int32 words living in POSIX shared memory, so a step dispatch to N
// env worker processes costs N futex wakes (~1us each) and one
// futex-parked wait-all — no pipes, no pickling, no GIL handoff on the
// hot path. Observations/actions cross process boundaries through the
// same shared-memory block, written in place as rows of the batched
// arrays the trainer consumes (zero Python-level gathers).
//
// Futexes are SHARED (no FUTEX_PRIVATE_FLAG): the words live in shm
// mapped by multiple processes.
//
// All waits take a timeout; a worker that died mid-step surfaces as a
// timeout the pool turns into a diagnosed RuntimeError — the failure
// detection the reference lacks (its per-step comm.recv deadlocks
// forever on a dead rank, ref sac/algorithm.py:262-271; SURVEY.md §5).

#include <cerrno>
#include <cstdint>
#include <ctime>

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

long sys_futex(volatile int32_t* uaddr, int op, int32_t val,
               const struct timespec* timeout) {
  return syscall(SYS_futex, const_cast<int32_t*>(uaddr), op, val, timeout,
                 nullptr, 0);
}

int64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return int64_t(ts.tv_sec) * 1000000000 + ts.tv_nsec;
}

// Wait until *addr != old_val or the (absolute, CLOCK_MONOTONIC ns)
// deadline passes. deadline_ns < 0 waits forever. 0 = changed, -1 = timeout.
int wait_ne_deadline(volatile int32_t* addr, int32_t old_val,
                     int64_t deadline_ns) {
  while (__atomic_load_n(addr, __ATOMIC_SEQ_CST) == old_val) {
    struct timespec rel;
    struct timespec* relp = nullptr;
    if (deadline_ns >= 0) {
      int64_t remaining = deadline_ns - now_ns();
      if (remaining <= 0) return -1;
      rel.tv_sec = remaining / 1000000000;
      rel.tv_nsec = remaining % 1000000000;
      relp = &rel;
    }
    long r = sys_futex(addr, FUTEX_WAIT, old_val, relp);
    if (r == -1 && errno == ETIMEDOUT) return -1;
    // EAGAIN: value already changed; EINTR: signal — re-check either way.
  }
  return 0;
}

}  // namespace

extern "C" {

// Atomically store val into *addr and wake every futex waiter on it.
void tac_store_wake(volatile int32_t* addr, int32_t val) {
  __atomic_store_n(addr, val, __ATOMIC_SEQ_CST);
  sys_futex(addr, FUTEX_WAKE, INT32_MAX, nullptr);
}

int32_t tac_load(volatile int32_t* addr) {
  return __atomic_load_n(addr, __ATOMIC_SEQ_CST);
}

// Park until *addr != old_val. timeout_ms < 0 waits forever.
// Returns 0 on change, -1 on timeout.
int tac_wait_ne(volatile int32_t* addr, int32_t old_val, int64_t timeout_ms) {
  int64_t deadline = timeout_ms < 0 ? -1 : now_ns() + timeout_ms * 1000000;
  return wait_ne_deadline(addr, old_val, deadline);
}

// Park until words[i*stride] == targets[i*stride] for every i in [0, n).
// One shared deadline across the whole barrier. Returns 0, or -(i+1) for
// the first worker that had not acked at the deadline (its index is the
// diagnosis the pool reports).
int tac_wait_all_eq(volatile int32_t* words, volatile int32_t* targets,
                    int32_t n, int64_t stride, int64_t timeout_ms) {
  int64_t deadline = timeout_ms < 0 ? -1 : now_ns() + timeout_ms * 1000000;
  for (int32_t i = 0; i < n; ++i) {
    volatile int32_t* w = words + i * stride;
    int32_t want = __atomic_load_n(targets + i * stride, __ATOMIC_SEQ_CST);
    for (;;) {
      int32_t got = __atomic_load_n(w, __ATOMIC_SEQ_CST);
      if (got == want) break;
      if (wait_ne_deadline(w, got, deadline) != 0) return -(i + 1);
    }
  }
  return 0;
}

}  // extern "C"
