"""ctypes loader for the native runtime (``libtacrt.so``).

The library is tiny (one translation unit, no dependencies) so if a
prebuilt ``.so`` is absent we attempt a direct ``g++`` build into the
package directory — one-time, ~1s. Set ``TAC_NATIVE_LIB`` to use a
specific build (e.g. the ASan variant from ``make asan``).

``load_runtime`` returns ``None`` when the library is unavailable
(no compiler, non-Linux); callers fall back to pure-Python paths.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sys
import tempfile
import threading
from pathlib import Path

logger = logging.getLogger(__name__)

_NATIVE_DIR = Path(__file__).parent
_LOCK = threading.Lock()
_CACHE: dict = {}

SOURCES = [_NATIVE_DIR / "tac_runtime.cpp"]


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.tac_store_wake.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.tac_store_wake.restype = None
    lib.tac_load.argtypes = [ctypes.c_void_p]
    lib.tac_load.restype = ctypes.c_int32
    lib.tac_wait_ne.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64]
    lib.tac_wait_ne.restype = ctypes.c_int
    lib.tac_wait_all_eq.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.tac_wait_all_eq.restype = ctypes.c_int
    return lib


def _build(out: Path) -> bool:
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2",
        "-Wall",
        "-fPIC",
        "-std=c++17",
        "-shared",
        "-o",
        str(out),
        *[str(s) for s in SOURCES],
    ]
    try:
        # Build to a temp file then rename: concurrent builders (e.g.
        # spawned env workers racing the parent) each land a complete .so.
        with tempfile.NamedTemporaryFile(
            dir=out.parent, suffix=".so.tmp", delete=False
        ) as tmp:
            tmp_path = Path(tmp.name)
        cmd[cmd.index(str(out))] = str(tmp_path)
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp_path, out)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logger.debug("native build failed: %s", e)
        if "tmp_path" in locals():
            tmp_path.unlink(missing_ok=True)
        return False


def load_runtime(build_if_missing: bool = True) -> ctypes.CDLL | None:
    """Load (building if needed) the native runtime, or ``None``."""
    if not sys.platform.startswith("linux"):
        return None
    with _LOCK:
        if "lib" in _CACHE:
            return _CACHE["lib"]
        path = os.environ.get("TAC_NATIVE_LIB")
        candidates = [Path(path)] if path else [_NATIVE_DIR / "libtacrt.so"]
        for cand in candidates:
            if cand.exists():
                try:
                    _CACHE["lib"] = _declare(ctypes.CDLL(str(cand)))
                    return _CACHE["lib"]
                except OSError as e:
                    logger.warning("failed to load %s: %s", cand, e)
        if build_if_missing and path is None:
            out = _NATIVE_DIR / "libtacrt.so"
            if _build(out):
                try:
                    _CACHE["lib"] = _declare(ctypes.CDLL(str(out)))
                    return _CACHE["lib"]
                except OSError as e:  # pragma: no cover
                    logger.warning("failed to load built %s: %s", out, e)
        _CACHE["lib"] = None
        return None
