"""Image augmentation for pixel RL: DrQ random shift.

Vanilla SAC from pixels is notoriously unstable/sample-inefficient;
random-shift augmentation of the replayed frames is the standard,
minimal fix (Kostrikov et al., "Image Augmentation Is All You Need"
[DrQ] — PAPERS.md): pad the frame by a few pixels (edge-replicate) and
crop back at a random offset, independently per example and per use.
The reference has no augmentation (or pixel-learning evidence) at all;
this is a gated extension (``SACConfig.frame_augment``, default
``"none"`` = parity).

Everything here is jit-compatible (static shapes, ``dynamic_slice``
crops) and runs inside the fused update burst — augmentation happens
on device at sample time, so the replay buffer keeps storing each
frame once, unaugmented.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from torch_actor_critic_tpu.core.types import Batch, MultiObservation

__all__ = ["random_shift", "augment_batch", "shift_offsets"]


def shift_offsets(key: jax.Array, n: int, pad: int = 4) -> jax.Array:
    """The DrQ shift draw: ``(n, 2)`` per-example crop offsets, uniform
    over ``[0, 2*pad]``. The ONE definition shared by
    :func:`random_shift` (pad-then-crop) and the fused pixel pipeline
    (:mod:`torch_actor_critic_tpu.ops.pixels`, clipped-index gather),
    so the two spellings of the augmentation draw identical shifts from
    identical keys."""
    return jax.random.randint(key, (n, 2), 0, 2 * pad + 1)


def random_shift(frames: jax.Array, key: jax.Array, pad: int = 4) -> jax.Array:
    """DrQ random-shift: edge-pad by ``pad`` px, crop at a per-example
    uniform offset in ``[0, 2*pad]``. Works on ``(..., B, H, W, C)``
    frames of any dtype (uint8 replay frames stay uint8 — shifting
    moves bytes, no arithmetic).
    """
    *lead, h, w, c = frames.shape
    flat = frames.reshape((-1, h, w, c))
    padded = jnp.pad(
        flat, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="edge"
    )
    offsets = shift_offsets(key, flat.shape[0], pad)

    def crop(img, off):
        return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, c))

    return jax.vmap(crop)(padded, offsets).reshape(frames.shape)


def augment_batch(
    batch: Batch, key: jax.Array, mode: str, pad: int = 4
) -> Batch:
    """Apply the configured augmentation to a sampled visual batch.

    ``mode="none"`` (parity) returns the batch untouched — including
    for flat/sequence observations, where there is nothing to augment.
    ``mode="shift"`` random-shifts ``states.frame`` and
    ``next_states.frame`` with independent offsets (DrQ's K=M=1
    scheme). Called inside the jitted update, so the augmentation is
    re-drawn every gradient step as DrQ prescribes.
    """
    if mode == "none" or not isinstance(batch.states, MultiObservation):
        return batch
    if mode != "shift":
        raise ValueError(f"unknown frame_augment mode {mode!r}")
    k_s, k_n = jax.random.split(key)
    return batch.replace(
        states=batch.states.replace(
            frame=random_shift(batch.states.frame, k_s, pad)
        ),
        next_states=batch.next_states.replace(
            frame=random_shift(batch.next_states.frame, k_n, pad)
        ),
    )
