"""Scaled-dot-product attention: blockwise (XLA) and flash (Pallas TPU).

The reference has no attention anywhere — its models are feedforward
MLPs/CNNs over fixed-width observation vectors (SURVEY.md §5
"Long-context: absent by construction"). This module is the compute
core of the framework's long-context *extension*: sequence policies
(:mod:`torch_actor_critic_tpu.models.sequence`) and ring-attention
context parallelism (:mod:`torch_actor_critic_tpu.parallel.context`)
both reduce to the online-softmax block update defined here.

Three implementations of the same math, one contract:

- :func:`reference_attention` — materializes the full ``(Tq, Tk)``
  score matrix. O(T^2) memory; ground truth for tests.
- :func:`blockwise_attention` — FlashAttention-style online softmax
  over K/V blocks via ``lax.scan``: O(block) memory, differentiable,
  runs on any backend. This is the training-path default.
- :func:`flash_attention` — a Pallas TPU kernel of the same loop:
  grid ``(batch·heads, q-blocks, k-blocks)`` so VMEM only ever holds
  one ``(block, head_dim)`` tile of each operand (long sequences
  stream from HBM through the BlockSpec pipeline), MXU matmuls with
  f32 accumulators in VMEM scratch. Wrapped in a ``custom_vjp`` whose
  backward is *also* Pallas (FlashAttention-2 style: forward saves the
  per-row logsumexp; dQ and dK/dV kernels recompute probability tiles
  from it), so training gets the kernel in both directions. Head dims
  are zero-padded to the 128-lane width transparently.

All take ``(batch, heads, seq, head_dim)`` arrays. ``q_offset`` /
``k_offset`` are *global* position offsets of the local q/k chunks —
the hook that lets ring attention apply a correct causal mask when the
sequence axis is sharded across devices.
"""

from __future__ import annotations

import functools
import math
import typing as t

import jax
import jax.numpy as jnp

NEG_INF = float("-inf")


def reference_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
) -> jax.Array:
    """Plain softmax(QK^T/sqrt(d))V with the full score matrix."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(q.shape[-1])
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    # Rows with no visible key (possible when k_offset > q position, as
    # happens for future chunks in ring attention) would softmax to NaN;
    # zero them instead to match the online-softmax convention.
    all_masked = jnp.all(scores == NEG_INF, axis=-1, keepdims=True)
    weights = jax.nn.softmax(jnp.where(all_masked, 0.0, scores), axis=-1)
    weights = jnp.where(all_masked, 0.0, weights)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def online_block_update(
    q: jax.Array,
    k_blk: jax.Array,
    v_blk: jax.Array,
    m: jax.Array,
    l: jax.Array,
    acc: jax.Array,
    causal: bool = False,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
    k_end: jax.Array | int | None = None,
    scale: float | None = None,
) -> t.Tuple[jax.Array, jax.Array, jax.Array]:
    """One online-softmax accumulation step against a K/V block.

    Carries ``(m, l, acc)`` — running row max, normalizer, and
    unnormalized output — in float32. ``k_end`` (a *global* position
    bound) masks a pad tail; ``causal`` masks in global coordinates via
    the offsets. Safe when the block is entirely masked (contributes
    nothing). The single update body shared by the scan path here, the
    cross-device ring in ``parallel/context.py``, and mirrored by the
    Pallas kernel.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k_blk, preferred_element_type=jnp.float32
    ) * scale
    if causal or k_end is not None:
        tq, tk = scores.shape[-2], scores.shape[-1]
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        valid = True
        if k_end is not None:
            valid = k_pos < k_end
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
            valid = valid & (q_pos >= k_pos)
        scores = jnp.where(valid, scores, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    # exp(-inf - -inf) = NaN; a fully-masked row keeps m_new == -inf and
    # must contribute exp(...) = 0.
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
    l = l * alpha + jnp.sum(p, axis=-1)
    acc = acc * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return m_new, l, acc


def finalize_online(m: jax.Array, l: jax.Array, acc: jax.Array) -> jax.Array:
    """Normalize the online-softmax accumulator; all-masked rows → 0."""
    return acc / jnp.where(l == 0.0, 1.0, l)[..., None]


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    q_offset: jax.Array | int = 0,
    k_offset: jax.Array | int = 0,
    block_k: int = 256,
) -> jax.Array:
    """Online-softmax attention scanning over K/V blocks.

    Never materializes the ``(Tq, Tk)`` matrix: peak memory is
    O(Tq · block_k) per (batch, head). Differentiable (plain jnp under
    ``lax.scan``), so it is the training-path implementation.
    """
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_k = min(block_k, tk)
    if tk % block_k:  # pad K/V to a block multiple; pad tail masked out
        pad = block_k - tk % block_k
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    n_blocks = k.shape[2] // block_k
    k_blocks = k.reshape(b, h, n_blocks, block_k, d).transpose(2, 0, 1, 3, 4)
    v_blocks = v.reshape(b, h, n_blocks, block_k, d).transpose(2, 0, 1, 3, 4)

    qf = q.astype(jnp.float32)
    init = (
        jnp.full((b, h, tq), NEG_INF, jnp.float32),
        jnp.zeros((b, h, tq), jnp.float32),
        jnp.zeros((b, h, tq, d), jnp.float32),
    )
    padded = k.shape[2] != tk

    def body(carry, blk):
        j, k_blk, v_blk = blk
        m, l, acc = carry
        m, l, acc = online_block_update(
            qf, k_blk, v_blk, m, l, acc,
            causal=causal,
            q_offset=q_offset,
            k_offset=k_offset + j * block_k,
            k_end=k_offset + tk if padded else None,
        )
        return (m, l, acc), None

    idx = jnp.arange(n_blocks)
    (m, l, acc), _ = jax.lax.scan(body, init, (idx, k_blocks, v_blocks))
    return finalize_online(m, l, acc).astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas TPU flash-attention kernel
# --------------------------------------------------------------------------

_LANE = 128  # TPU lane width: last tile dim, and scratch column count


def _compiler_params(pltpu):
    """``pltpu.CompilerParams`` across the 0.4->0.5 rename (older jax
    spells it ``TPUCompilerParams``; same constructor surface)."""
    return getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _acc_dot(a: jax.Array, b: jax.Array, dims) -> jax.Array:
    """``dot_general`` with f32 accumulation on MXU-native operands.

    Operands keep their storage dtype (bf16 stays bf16 — the MXU's fast
    mixed-precision path; upcasting to f32 first would force the ~4x
    slower f32 systolic passes). When exactly one side is an f32
    intermediate (the probability/ds tiles) and the other is sub-f32,
    the intermediate is cast DOWN to match — FlashAttention's standard
    TPU scheme; bf16 probabilities are inside the softmax's own error
    budget. f32-in/f32-out math is bit-identical to a plain f32 dot.
    """
    if a.dtype != b.dtype:
        if a.dtype == jnp.float32:
            a = a.astype(b.dtype)
        else:
            b = b.astype(a.dtype)
    return jax.lax.dot_general(
        a, b, (dims, ((), ())), preferred_element_type=jnp.float32
    )


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *rest,
    block_q: int, block_k: int, scale: float, causal: bool,
    save_lse: bool = False,
):
    """One ``(batch·head, q-block, k-block)`` program.

    The k-block grid dimension is innermost, so for a fixed q block the
    programs run j = 0..nk-1 in order, carrying the online-softmax state
    in VMEM scratch (``m``/``l`` use column 0 of a (block_q, LANE)
    tile); the final k step normalizes into ``o_ref``. Same update math
    as :func:`online_block_update`.
    """
    from jax.experimental import pallas as pl  # deferred: TPU-only path

    if save_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        lse_ref = None
        m_ref, l_ref, acc_ref = rest

    iq = pl.program_id(1)
    j = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Under causality, K blocks strictly past this q block's diagonal
    # contribute nothing; skip their compute entirely.
    needed = True if not causal else j * block_k <= (iq + 1) * block_q - 1

    @pl.when(needed)
    def _update():
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        scores = _acc_dot(q, k_blk, ((1,), (1,))) * scale
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        m = m_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        # No isneginf guards in-kernel (unlike online_block_update,
        # whose ring-attention callers CAN see fully-masked rows): the
        # causal k-skip still runs j=0, where every row sees key 0, so
        # m_new is finite from the first visited block on. Masked
        # scores are -inf -> exp(-inf - finite) = 0, and the j=0
        # alpha = exp(-inf - finite) = 0 wipes the zero-init state.
        # The softmax tail is VPU-bound; each removed elementwise pass
        # over the (block_q, block_k) tile is measurable throughput.
        p = jnp.exp(scores - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_ref[:, 0] = l_ref[:, 0] * alpha + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + _acc_dot(
            p, v_blk, ((1,), (0,))
        )
        m_ref[:, 0] = m_new

    @pl.when(j == n_kb - 1)
    def _finalize():
        l = l_ref[:, 0]
        o_ref[0] = (
            acc_ref[:] / jnp.where(l == 0.0, 1.0, l)[:, None]
        ).astype(o_ref.dtype)
        if save_lse:
            # Per-row logsumexp — the only forward residual the flash
            # backward needs besides (q, k, v, o). INVARIANT: no
            # in-kernel row is ever fully masked (causal rows always
            # see key 0; there is no q/k offset on the Pallas path),
            # so l > 0 and lse is finite here — the l == 0 guard below
            # is defensive only, and the backward relies on finite lse
            # (it has no isneginf path; extending this kernel to
            # ring-attention offsets would need those guards back).
            # Stored broadcast across a 128-lane axis: Mosaic requires
            # (8, 128)-tileable output blocks, so a (1, block_q) row
            # vector is not lowerable — same layout as the reference
            # TPU kernel's l/m residuals (jax pallas ops flash_attention,
            # MIN_BLOCK_SIZE lanes).
            lse = jnp.where(
                l == 0.0, NEG_INF, m_ref[:, 0] + jnp.log(jnp.where(l == 0.0, 1.0, l))
            )
            lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _pad_head_dim(
    *arrays: jax.Array, lanes: int = _LANE
) -> t.Tuple[jax.Array, ...]:
    """Zero-pad the trailing (head) axis to a multiple of ``lanes``.

    ``lanes=128`` is the native lane width. ``lanes=64`` keeps a d=64
    head at its true width: the MXU still runs at most 50% on a 64-wide
    contraction either way (the 128x128 systolic array bound — see
    SCALING.md's attention roofline), but the q/k/v/o tiles carry half
    the HBM traffic and VMEM footprint of the zero-padded layout.

    ADOPTION GATE: ``lanes=64`` is validated in Pallas interpret mode
    only; Mosaic may reject or de-optimize sub-128-lane tiles on real
    hardware. 128 stays the default (and the only recommended value)
    until an on-chip sweep artifact in ``runs/tpu/`` shows 64 both
    lowering and winning.
    """
    d = arrays[0].shape[-1]
    if d % lanes == 0:
        return arrays
    pad = lanes - d % lanes
    return tuple(
        jnp.pad(x, ((0, 0),) * (x.ndim - 1) + ((0, pad),)) for x in arrays
    )


# Auto block-size cap: the chip's block sweep (runs/tpu/
# bench_20260731T034827Z.json, attention.block_sweep) measured fwd+bwd
# at [4, 8, 2048, 64] bf16 monotonically improving up to (512, 512) —
# 16.9 TFLOP/s vs the 6.1 the old (128, 128) default recorded in the
# same artifact, a 2.8x — so auto picks the largest block in
# {128, 256, 512} that tiles the sequence.
_AUTO_BLOCK_CAP = 512


def _auto_block(t: int, cap: int = _AUTO_BLOCK_CAP) -> int | None:
    """Largest block in {512, 256, 128} <= ``cap`` dividing ``t``
    (``t`` itself when ``t <= 128`` — the single-block case the old
    128 default already allowed). ``None`` when no such block exists:
    the shape set accepted here is exactly the old fixed-128 default's
    (so no shape silently moves from the XLA path onto never-validated
    degenerate Pallas tiles), only the chosen block can be larger."""
    if t <= 128:
        return t
    for b in (512, 256, 128):
        if b <= cap and t % b == 0:
            return b
    return None


def _check_blocks(tq: int, tk: int, block_q: int | None, block_k: int | None):
    block_q = _auto_block(tq) if block_q is None else min(block_q, tq)
    block_k = _auto_block(tk) if block_k is None else min(block_k, tk)
    if block_q is None or block_k is None or tq % block_q or tk % block_k:
        raise ValueError(
            f"flash_attention: Tq={tq} must divide by block_q={block_q} and "
            f"Tk={tk} by block_k={block_k} (None = no 128/256/512 block "
            "tiles the length); use attention(impl='xla') or "
            "blockwise_attention for ragged lengths."
        )
    return block_q, block_k


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int,
    block_k: int,
    interpret: bool,
    save_lse: bool = False,
    pad_lanes: int = _LANE,
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not interpret and jax.default_backend() != "tpu":
        # Without this, a compiled Pallas call on a CPU/GPU process dies
        # much later in lowering with a cryptic Mosaic error (the
        # trace-time 'auto' dispatch footgun, see attention()'s CAUTION
        # note). Trace-time default_backend is the right check: the
        # kernel choice is also made at trace time.
        raise RuntimeError(
            "flash_attention compiles Pallas TPU kernels but this "
            f"process's default backend is {jax.default_backend()!r}; "
            "use attention(..., impl='xla') (or inject "
            "models.sequence.xla_attention into sequence models), or "
            "pass interpret=True for CPU testing."
        )
    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q, block_k = _check_blocks(tq, tk, block_q, block_k)
    if not (q.dtype == k.dtype == v.dtype):
        # _acc_dot's downcast rule is only safe for the kernels' own f32
        # intermediates; a mixed-dtype *input* would be silently rounded.
        raise ValueError(
            "flash_attention requires q/k/v to share one dtype, got "
            f"{q.dtype}/{k.dtype}/{v.dtype}; cast the operands first."
        )
    # The softmax scale uses the *logical* head dim; zero-pad the head
    # axis to the lane width (dot products are unchanged by zero columns,
    # padded output columns are sliced away).
    scale = 1.0 / math.sqrt(d)
    q, k, v = _pad_head_dim(q, k, v, lanes=pad_lanes)
    dp = q.shape[-1]
    qr = q.reshape(b * h, tq, dp)
    kr = k.reshape(b * h, tk, dp)
    vr = v.reshape(b * h, tk, dp)
    out_shape = [jax.ShapeDtypeStruct((b * h, tq, dp), q.dtype)]
    out_specs = [
        pl.BlockSpec((1, block_q, dp), lambda bh, iq, j: (bh, iq, 0),
                     memory_space=pltpu.VMEM),
    ]
    if save_lse:
        out_shape.append(
            jax.ShapeDtypeStruct((b * h, tq, _LANE), jnp.float32)
        )
        out_specs.append(
            pl.BlockSpec((1, block_q, _LANE), lambda bh, iq, j: (bh, iq, 0),
                         memory_space=pltpu.VMEM)
        )
    outs = pl.pallas_call(
        functools.partial(
            _flash_kernel,
            block_q=block_q, block_k=block_k, scale=scale, causal=causal,
            save_lse=save_lse,
        ),
        out_shape=out_shape,
        grid=(b * h, tq // block_q, tk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, iq, j: (bh, iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dp), lambda bh, iq, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dp), lambda bh, iq, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # m (col 0)
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # l (col 0)
            pltpu.VMEM((block_q, dp), jnp.float32),     # acc
        ],
        # bh and q-block programs are independent; the k sweep carries
        # the online-softmax scratch and must stay sequential.
        compiler_params=_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, kr, vr)
    out = outs[0].reshape(b, h, tq, dp)[..., :d]
    if save_lse:
        return out, outs[1][:, :, 0].reshape(b, h, tq)
    return out


def _attn_probs(q, k, lse, scale, causal, iq, jk, block_q, block_k):
    """Recompute the (block_q, block_k) probability tile from saved lse.

    ``p[r, c] = exp(s[r, c] - lse[r])`` — exactly the forward's softmax
    weights, recovered without re-running the online max/normalizer scan.
    Shared by both backward kernels.
    """
    s = _acc_dot(q, k, ((1,), (1,))) * scale
    if causal:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    # lse is finite for every row inside the kernel (each causal row
    # sees at least key 0 — see the forward's guard-removal note), and
    # masked scores are -inf -> exp(-inf - finite) = 0 with no NaN
    # path, so no isneginf passes are needed on the VPU-bound tail.
    return jnp.exp(s - lse[:, None])


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, block_q: int, block_k: int, scale: float, causal: bool,
):
    """dQ: grid ``(batch·head, q-block, k-block)``, k innermost.

    ``ds = p · (dO Vᵀ − Δ)``, ``dq += ds K · scale`` accumulated in VMEM
    scratch over the k sweep, written once on the final k step. Δ is the
    precomputed ``rowsum(dO ∘ O)`` (standard FlashAttention-2 backward).
    """
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    j = pl.program_id(2)
    n_kb = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    needed = True if not causal else j * block_k <= (iq + 1) * block_q - 1

    @pl.when(needed)
    def _update():
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        do = do_ref[0]
        p = _attn_probs(
            q, k_blk, lse_ref[0][:, 0], scale, causal, iq, j, block_q, block_k
        )
        dpv = _acc_dot(do, v_blk, ((1,), (1,)))
        ds = p * (dpv - delta_ref[0][:, 0][:, None])
        dq_acc[:] += _acc_dot(ds, k_blk, ((1,), (0,))) * scale

    @pl.when(j == n_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, block_q: int, block_k: int, scale: float, causal: bool,
):
    """dK/dV: grid ``(batch·head, k-block, q-block)``, q innermost.

    ``dv += pᵀ dO``; ``dk += dsᵀ Q · scale`` — both accumulated in VMEM
    scratch over the q sweep for a fixed k block.
    """
    from jax.experimental import pallas as pl

    jk = pl.program_id(1)
    i = pl.program_id(2)
    n_qb = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    # Under causality, q blocks strictly before this k block's start see
    # none of it; skip them.
    needed = True if not causal else (i + 1) * block_q - 1 >= jk * block_k

    @pl.when(needed)
    def _update():
        q = q_ref[0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        do = do_ref[0]
        p = _attn_probs(
            q, k_blk, lse_ref[0][:, 0], scale, causal, i, jk, block_q, block_k
        )
        dv_acc[:] += _acc_dot(p, do, ((0,), (0,)))
        dpv = _acc_dot(do, v_blk, ((1,), (1,)))
        ds = p * (dpv - delta_ref[0][:, 0][:, None])
        dk_acc[:] += _acc_dot(ds, q, ((0,), (0,))) * scale

    @pl.when(i == n_qb - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, o, lse, g, causal, block_q, block_k, interpret,
    pad_lanes: int = _LANE,
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    block_q, block_k = _check_blocks(tq, tk, block_q, block_k)
    scale = 1.0 / math.sqrt(d)
    # The forward enforced a single q/k/v dtype; the cotangent can still
    # arrive wider (e.g. an f32 loss over a bf16 output) — align it so
    # _acc_dot never downcasts a genuine input unasked.
    g = g.astype(q.dtype)
    # Δ = rowsum(dO ∘ O): cheap elementwise reduce, fused by XLA; padded
    # head columns of o/g are zero so padding doesn't perturb it.
    delta = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).reshape(b * h, tq)
    q, k, v, g = _pad_head_dim(q, k, v, g, lanes=pad_lanes)
    dp = q.shape[-1]
    qr = q.reshape(b * h, tq, dp)
    kr = k.reshape(b * h, tk, dp)
    vr = v.reshape(b * h, tk, dp)
    gr = g.reshape(b * h, tq, dp)
    # Row stats enter the kernels broadcast across a 128-lane axis —
    # (1, block_q) blocks are not (8, 128)-tileable on TPU (see the
    # matching note in the forward's lse output).
    lse_r = jnp.broadcast_to(
        lse.reshape(b * h, tq)[:, :, None], (b * h, tq, _LANE)
    )
    delta = jnp.broadcast_to(delta[:, :, None], (b * h, tq, _LANE))

    qspec = pl.BlockSpec((1, block_q, dp), lambda bh, x, y: (bh, x, 0),
                         memory_space=pltpu.VMEM)
    kspec_dq = pl.BlockSpec((1, block_k, dp), lambda bh, iq, j: (bh, j, 0),
                            memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, block_q, _LANE), lambda bh, x, y: (bh, x, 0),
                           memory_space=pltpu.VMEM)
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            block_q=block_q, block_k=block_k, scale=scale, causal=causal,
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, dp), q.dtype),
        grid=(b * h, tq // block_q, tk // block_k),
        in_specs=[qspec, kspec_dq, kspec_dq, qspec, rowspec, rowspec],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, dp), jnp.float32)],
        compiler_params=_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, kr, vr, gr, lse_r, delta)

    # dK/dV sweep: the grid's second axis is the k block, q innermost.
    qspec_kv = pl.BlockSpec((1, block_q, dp), lambda bh, jk, i: (bh, i, 0),
                            memory_space=pltpu.VMEM)
    kspec_kv = pl.BlockSpec((1, block_k, dp), lambda bh, jk, i: (bh, jk, 0),
                            memory_space=pltpu.VMEM)
    rowspec_kv = pl.BlockSpec((1, block_q, _LANE), lambda bh, jk, i: (bh, i, 0),
                              memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            block_q=block_q, block_k=block_k, scale=scale, causal=causal,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, dp), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, dp), v.dtype),
        ],
        grid=(b * h, tk // block_k, tq // block_q),
        in_specs=[qspec_kv, kspec_kv, kspec_kv, qspec_kv, rowspec_kv,
                  rowspec_kv],
        out_specs=[kspec_kv, kspec_kv],
        scratch_shapes=[
            pltpu.VMEM((block_k, dp), jnp.float32),
            pltpu.VMEM((block_k, dp), jnp.float32),
        ],
        compiler_params=_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, kr, vr, gr, lse_r, delta)

    dq = dq.reshape(b * h, tq, dp)[..., :d].reshape(b, h, tq, d)
    dk = dk.reshape(b * h, tk, dp)[..., :d].reshape(b, h, tk, d)
    dv = dv.reshape(b * h, tk, dp)[..., :d].reshape(b, h, tk, d)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool = False,
    pad_lanes: int = _LANE,
):
    """Pallas TPU flash attention, forward *and* backward kernels.

    The forward is the online-softmax streaming kernel; under
    ``jax.grad`` it additionally saves the per-row logsumexp, and the
    backward runs two Pallas kernels (dQ over k-blocks; dK/dV over
    q-blocks) that recompute probability tiles from the saved lse — the
    FlashAttention-2 scheme, O(block²) VMEM, no (Tq, Tk) matrix ever
    materialized in either direction.

    ``block_q``/``block_k`` default to auto: the largest block in
    {128, 256, 512} that tiles the sequence length — 512 is the chip's
    block-sweep optimum (2.8x the old 128-block default fwd+bwd bf16,
    see ``_AUTO_BLOCK_CAP``). Explicit values require ``Tq % block_q == 0``
    and ``Tk % block_k == 0`` (raises ``ValueError`` otherwise); any
    head dim works (zero-padded to the 128-lane width internally).
    ``interpret=True`` runs the kernels in the Pallas interpreter
    (CPU-testable; used by the test suite).
    """
    return _flash_forward(
        q, k, v, causal, block_q, block_k, interpret, pad_lanes=pad_lanes
    )


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, pad_lanes):
    out, lse = _flash_forward(
        q, k, v, causal, block_q, block_k, interpret, save_lse=True,
        pad_lanes=pad_lanes,
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, pad_lanes, res, g):
    q, k, v, o, lse = res
    return _flash_backward(
        q, k, v, o, lse, g, causal, block_q, block_k, interpret,
        pad_lanes=pad_lanes,
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    impl: str = "auto",
    block_q: int | None = None,
    block_k: int | None = None,
) -> jax.Array:
    """Dispatch: ``'pallas'`` kernel on TPU-compatible shapes,
    ``'xla'`` blockwise scan otherwise; ``'auto'`` picks by the process
    default backend.

    CAUTION: ``'auto'`` bakes the choice in at trace time, so a
    function compiled for a *non-default* backend (e.g. the trainer's
    host-CPU actor mirror while TPU is default) must not rely on it —
    pass an explicit ``impl`` or, for the sequence models, inject
    ``models.sequence.xla_attention``. (``lax.platform_dependent`` is
    not an option: XLA still lowers the dead Pallas branch on CPU and
    ``pallas_call`` has no CPU lowering outside interpret mode.)
    Tracing the Pallas path on a non-TPU-default process raises a clear
    ``RuntimeError`` at trace time (tests/test_attention.py pins this)
    instead of a cryptic Mosaic lowering error.
    """
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        # TPU tiles are (8, 128) for f32: besides block divisibility,
        # require sublane-aligned sequence lengths (T % 8 == 0) or the
        # kernel would compile sublane-unaligned tiles that are only
        # ever exercised in interpret mode. Auto blocks (None) accept
        # exactly the shape set the old fixed-128 default did (see
        # _auto_block); a None result routes to XLA like a ragged
        # length always has.
        bq = _auto_block(q.shape[2]) if block_q is None else block_q
        bk = _auto_block(k.shape[2]) if block_k is None else block_k
        shapes_ok = (
            bq is not None
            and bk is not None
            and q.shape[2] % 8 == 0
            and k.shape[2] % 8 == 0
            and q.shape[2] % min(bq, q.shape[2]) == 0
            and k.shape[2] % min(bk, k.shape[2]) == 0
        )
        impl = "pallas" if (on_tpu and shapes_ok) else "xla"
    if impl == "pallas":
        return flash_attention(q, k, v, causal, block_q, block_k)
    return blockwise_attention(
        q, k, v, causal, block_k=128 if block_k is None else block_k
    )
