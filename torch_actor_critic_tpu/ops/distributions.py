"""Squashed-Gaussian primitives.

The exact math of the reference actor head (ref
``networks/linear.py:39-51``): clip log-std to ``[-20, 2]``,
reparameterized sample ``u = mu + sigma * eps``, squash
``a = tanh(u) * act_limit``, and the numerically-stable tanh log-prob
correction ``logp -= sum(2 * (log 2 - u - softplus(-2u)))`` (the
log-derivative of tanh rewritten via softplus; same identity OpenAI
spinningup uses). Kept as free functions so the MLP and CNN actors — and
any future policy head — share one implementation instead of the
reference's copy in each module (ref ``networks/convolutional.py:105-120``).

Note the reference (and spinningup) do *not* include the ``act_limit``
scale in the log-prob correction; we reproduce that behavior exactly for
parity (``act_limit`` is 1.0 for all standard MuJoCo envs, so the
constant only matters for the reference's nonstandard default of 10,
ref ``networks/linear.py:22``).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

LOG_STD_MIN = -20.0
LOG_STD_MAX = 2.0

_LOG_SQRT_2PI = 0.5 * math.log(2.0 * math.pi)


def gaussian_log_prob(u: jax.Array, mu: jax.Array, log_std: jax.Array) -> jax.Array:
    """Diagonal-Gaussian log-density, summed over the trailing axis.

    Matches ``Normal(mu, std).log_prob(u).sum(-1)``
    (ref ``networks/linear.py:50``).
    """
    std = jnp.exp(log_std)
    z = (u - mu) / std
    return jnp.sum(-0.5 * z * z - log_std - _LOG_SQRT_2PI, axis=-1)


def tanh_log_prob_correction(u: jax.Array) -> jax.Array:
    """``sum(2 * (log 2 - u - softplus(-2u)))`` over the trailing axis.

    The stable form of ``sum(log(1 - tanh(u)^2))``
    (ref ``networks/linear.py:51``).
    """
    return jnp.sum(2.0 * (math.log(2.0) - u - jax.nn.softplus(-2.0 * u)), axis=-1)


def squashed_gaussian_sample(
    key: jax.Array | None,
    mu: jax.Array,
    log_std: jax.Array,
    act_limit: float,
    deterministic: bool = False,
    with_logprob: bool = True,
):
    """Sample (or take the mode of) a tanh-squashed Gaussian policy.

    Returns ``(action, log_prob)``; ``log_prob`` is ``None`` when
    ``with_logprob`` is False. ``deterministic``/``with_logprob`` mirror
    the reference forward flags (ref ``networks/linear.py:32,43-51``).
    Pure function of an explicit PRNG ``key`` — the TPU-native
    replacement for torch's global-RNG ``rsample()``.
    """
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    if deterministic:
        u = mu
    else:
        if key is None:
            raise ValueError(
                "squashed_gaussian_sample: a PRNG `key` is required when "
                "deterministic=False (stochastic sampling)."
            )
        u = mu + jnp.exp(log_std) * jax.random.normal(key, mu.shape, mu.dtype)
    action = jnp.tanh(u) * act_limit

    logprob = None
    if with_logprob:
        logprob = gaussian_log_prob(u, mu, log_std) - tanh_log_prob_correction(u)
    return action, logprob
