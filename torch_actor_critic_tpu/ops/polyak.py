"""Polyak (exponential moving average) target-network update.

Functional equivalent of the reference's in-place
``targ = polyak * targ + (1 - polyak) * src`` loop over parameters
(ref ``sac/algorithm.py:77-81``). One ``tree_map``; XLA fuses it into
the surrounding update step so the whole thing is a single multiply-add
over each parameter buffer — no per-tensor Python loop, no ``no_grad``
bookkeeping.
"""

from __future__ import annotations

import typing as t

import jax


def polyak_update(source: t.Any, target: t.Any, polyak: float) -> t.Any:
    """Return ``polyak * target + (1 - polyak) * source``, leaf-wise."""
    return jax.tree_util.tree_map(
        lambda s, tgt: polyak * tgt + (1.0 - polyak) * s, source, target
    )
