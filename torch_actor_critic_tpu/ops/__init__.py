from torch_actor_critic_tpu.ops.distributions import (  # noqa: F401
    gaussian_log_prob,
    squashed_gaussian_sample,
    tanh_log_prob_correction,
)
from torch_actor_critic_tpu.ops.polyak import polyak_update  # noqa: F401
# NOTE: the `attention` dispatch *function* is deliberately not re-exported
# here — it would shadow the `ops.attention` submodule attribute.
from torch_actor_critic_tpu.ops.attention import (  # noqa: F401
    blockwise_attention,
    flash_attention,
    reference_attention,
)
from torch_actor_critic_tpu.ops.pixels import (  # noqa: F401
    fused_frame_gather,
    gather_frames_reference,
)
