"""Fused replay-sample -> decode -> augment -> cast pixel pipeline.

The TPU bench (BENCH_r03-r05) pins the visual workload at ~0.02 MFU
while the same chip sustains 0.70 on synthetic bf16 matmuls — and the
bench's own large-batch bf16 visual probe reaches 0.18, so the headroom
is real. Part of the gap is the pixel hot path: every gradient step
gathers a uint8 frame batch from the HBM ring (``buffer/replay.py``),
round-trips it through pad/crop augmentation (``ops/augment.py``) and
then materializes it as **float32** inside the CNN trunk
(``models/visual.py`` decodes ``frame.astype(float32) / 255``) — a
4x-width HBM write/read per forward, repeated across the four conv
towers of a SAC step.

This module fuses the whole chain into one kernel so the sampled frame
batch reaches the MXU in its compute dtype without ever existing as
f32 in HBM:

    replay-gather (+ frame stacking) -> uint8 decode -> DrQ random
    shift -> normalize -> cast to compute dtype

Three implementations of the same math, one contract — exactly the
``ops/attention.py`` scheme:

- :func:`gather_frames_reference` — pure jnp (gather + clipped-index
  shift + cast). Ground truth for tests; the training-path default on
  non-TPU backends.
- :func:`_gather_frames_pallas` — a Pallas TPU kernel: grid
  ``(batch, stack)``, the replay row selected per program via
  scalar-prefetch index maps (the ring never streams — one frame block
  of VMEM per program), the DrQ shift expressed as two one-hot
  **matmul-gathers** (MXU-friendly selection; exact for uint8 values,
  which are integers <= 255 and therefore exactly representable in
  f32 *and* bf16), decode/normalize fused into the epilogue, output
  written directly in the compute dtype. ADOPTION GATE: validated in
  interpret mode (CPU CI); Mosaic may reject the uint8 VMEM blocks or
  the in-kernel transpose on some generations — the ``impl`` dispatch
  keeps the XLA path one flag away until a chip artifact in
  ``runs/tpu/`` shows the kernel lowering and winning.
- :func:`fused_frame_gather` — the dispatch: ``'pallas'`` on a
  TPU-default backend, ``'xla'`` otherwise; ``interpret=True`` runs
  the kernel in the Pallas interpreter for CPU tests. Tracing the
  Pallas path on a non-TPU process raises at trace time (the
  ``flash_attention`` footgun guard).

Bit contract (pinned by tests/test_pixels.py): all three paths agree
BITWISE for every (out_dtype, normalize, augment, frame_stack)
combination, and the f32/no-augment output equals what the legacy path
computes inside the model (gather -> ``astype(float32)`` ->
``/ 255``), so switching ``pixel_pipeline="fused"`` at f32 changes
nothing but where the decode runs. Decode order is
``uint8 -> out_dtype -> (/255)``: integers <= 255 are exact in bf16,
so no f32 intermediate is needed for exact decoding, and the jaxpr of
the fused sample provably contains no f32 frame-batch tensor.

Frame stacking (``frame_stack > 1``) gathers the ``S`` ring rows
``idx - S + 1 .. idx`` (modular) and concatenates them on channels —
the gather-in-kernel formulation of a host-side frame stacker. NOTE:
the ring is a transition buffer, so stacked rows are consecutive
*pushes*; callers own the episode-boundary semantics (the built-in
envs bake temporal context into channels instead — see
``envs/pixel_pendulum.py`` — which is why training wires
``frame_stack=1`` today).
"""

from __future__ import annotations

import functools
import typing as t

import jax
import jax.numpy as jnp

from torch_actor_critic_tpu.ops.augment import shift_offsets

__all__ = [
    "fused_frame_gather",
    "gather_frames_reference",
    "stack_rows",
]


def stack_rows(
    idx: jax.Array, frame_stack: int, capacity: int
) -> jax.Array:
    """Ring rows backing a stacked gather: ``(B, S)`` int32, oldest
    first, newest (``idx`` itself) last, modular on the ring."""
    if frame_stack < 1:
        raise ValueError(f"frame_stack must be >= 1, got {frame_stack}")
    offsets = jnp.arange(frame_stack - 1, -1, -1, dtype=idx.dtype)
    return (idx[:, None] - offsets[None, :]) % capacity


def _decode(x: jax.Array, normalize: bool, out_dtype) -> jax.Array:
    """uint8 -> compute dtype, optionally rescaled to [0, 1].

    The cast precedes the divide ON PURPOSE: integers <= 255 are exact
    in every supported compute dtype (bf16 carries 8 significand bits),
    so decoding never needs an f32 intermediate — the property the
    no-f32-materialization test pins on the jaxpr.
    """
    x = x.astype(out_dtype)
    if normalize:
        x = x / jnp.asarray(255.0, out_dtype)
    return x


def _clipped_axis_indices(
    offsets: jax.Array, length: int, pad: int
) -> jax.Array:
    """Per-example source indices of a DrQ shift along one axis:
    ``clip(i + off - pad, 0, length-1)`` — identical to edge-padding by
    ``pad`` and cropping at ``off`` (``ops/augment.random_shift``),
    without materializing the padded frame."""
    return jnp.clip(
        jnp.arange(length)[None, :] + offsets[:, None] - pad, 0, length - 1
    )


def gather_frames_reference(
    ring: jax.Array,
    idx: jax.Array,
    offsets: jax.Array | None = None,
    pad: int = 4,
    normalize: bool = False,
    out_dtype=jnp.float32,
    frame_stack: int = 1,
) -> jax.Array:
    """Pure-jnp reference of the fused pipeline (ground truth).

    ``ring`` is the uint8 replay frame ring ``(capacity, H, W, C)``;
    ``idx`` the sampled rows ``(B,)``; ``offsets`` the per-example DrQ
    shift draws ``(B, 2)`` in ``[0, 2*pad]`` (None = no augmentation).
    Returns ``(B, H, W, frame_stack*C)`` in ``out_dtype``.
    """
    b = idx.shape[0]
    capacity, h, w, c = ring.shape
    rows = stack_rows(idx, frame_stack, capacity)
    frames = jnp.take(ring, rows.reshape(-1), axis=0).reshape(
        b, frame_stack, h, w, c
    )
    if offsets is not None:
        ys = _clipped_axis_indices(offsets[:, 0], h, pad)
        xs = _clipped_axis_indices(offsets[:, 1], w, pad)
        # Shift while still uint8: index moves, no arithmetic.
        frames = jnp.take_along_axis(
            frames, ys[:, None, :, None, None], axis=2
        )
        frames = jnp.take_along_axis(
            frames, xs[:, None, None, :, None], axis=3
        )
    out = _decode(frames, normalize, out_dtype)
    # (B, S, H, W, C) -> (B, H, W, S*C): temporal context on channels,
    # newest frame in the last C channels.
    return out.transpose(0, 2, 3, 1, 4).reshape(b, h, w, frame_stack * c)


# --------------------------------------------------------------------------
# Pallas TPU kernel
# --------------------------------------------------------------------------


def _pixel_kernel(
    rows_ref, offs_ref, ring_ref, o_ref, *,
    pad: int, normalize: bool, augment: bool, out_dtype,
):
    """One ``(example, stack-slot)`` program.

    The replay row was already selected by the scalar-prefetch index
    map (``rows_ref[i, s]`` steers the ring BlockSpec), so the body
    only sees one ``(H, W, C)`` uint8 frame in VMEM. The DrQ shift is
    two one-hot matmul-gathers — selection expressed as MXU work, the
    layout TPUs execute well — computed in f32 where every uint8 value
    is exact, then decoded straight into the output dtype.
    """
    from jax.experimental import pallas as pl  # deferred: TPU-only path

    i = pl.program_id(0)
    frame = ring_ref[0]  # (H, W, C) uint8
    h, w, c = frame.shape
    if not augment:
        o_ref[0] = _decode(frame, normalize, out_dtype)
        return
    oy = offs_ref[i, 0]
    ox = offs_ref[i, 1]
    f = frame.astype(jnp.float32)
    sy = jnp.clip(
        jax.lax.broadcasted_iota(jnp.int32, (h,), 0) + oy - pad, 0, h - 1
    )
    onehot_y = (
        jax.lax.broadcasted_iota(jnp.int32, (h, h), 1) == sy[:, None]
    ).astype(jnp.float32)
    g = jax.lax.dot_general(
        onehot_y, f.reshape(h, w * c), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(h, w, c)
    sx = jnp.clip(
        jax.lax.broadcasted_iota(jnp.int32, (w,), 0) + ox - pad, 0, w - 1
    )
    # onehot_x[w, x] = (w == sx[x]); contracting g's W axis against it
    # yields out[y, c, x] — one transpose back to (y, x, c).
    onehot_x = (
        jax.lax.broadcasted_iota(jnp.int32, (w, w), 0) == sx[None, :]
    ).astype(jnp.float32)
    out = jax.lax.dot_general(
        g, onehot_x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).transpose(0, 2, 1)
    # The matmul-gather is exact selection (one unit term per output),
    # so `out` holds the original integer values: the f32->out_dtype
    # cast is exact and the decode contract matches the reference path
    # bit for bit.
    out = out.astype(out_dtype)
    if normalize:
        out = out / jnp.asarray(255.0, out_dtype)
    o_ref[0] = out


def _gather_frames_pallas(
    ring: jax.Array,
    idx: jax.Array,
    offsets: jax.Array | None,
    pad: int,
    normalize: bool,
    out_dtype,
    frame_stack: int,
    interpret: bool,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not interpret and jax.default_backend() != "tpu":
        # Same trace-time guard as flash_attention: without it a
        # compiled Pallas call on a CPU/GPU process dies much later in
        # lowering with a cryptic Mosaic error.
        raise RuntimeError(
            "fused_frame_gather compiles Pallas TPU kernels but this "
            f"process's default backend is {jax.default_backend()!r}; "
            "use impl='xla' (the pure-jnp reference path) or pass "
            "interpret=True for CPU testing."
        )
    b = idx.shape[0]
    capacity, h, w, c = ring.shape
    rows = stack_rows(idx.astype(jnp.int32), frame_stack, capacity)
    augment = offsets is not None
    if offsets is None:
        # Scalar-prefetch operands are positional; feed a zero block
        # the no-augment kernel never reads.
        offsets = jnp.zeros((b, 2), jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, frame_stack),
        in_specs=[
            pl.BlockSpec(
                (1, h, w, c), lambda i, s, rows, offs: (rows[i, s], 0, 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, h, w, c), lambda i, s, rows, offs: (i, 0, 0, s)
        ),
    )
    return pl.pallas_call(
        functools.partial(
            _pixel_kernel, pad=pad, normalize=normalize, augment=augment,
            out_dtype=out_dtype,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, w, frame_stack * c), out_dtype),
        interpret=interpret,
    )(rows, offsets.astype(jnp.int32), ring)


def fused_frame_gather(
    ring: jax.Array,
    idx: jax.Array,
    offsets: jax.Array | None = None,
    pad: int = 4,
    normalize: bool = False,
    out_dtype=jnp.float32,
    frame_stack: int = 1,
    impl: str = "auto",
    interpret: bool = False,
) -> jax.Array:
    """Dispatch: the Pallas kernel on a TPU-default backend, the jnp
    reference elsewhere (``'auto'`` decides at trace time, like
    ``ops/attention.attention``). All paths are bitwise-equal — the
    choice is a performance decision, never a numeric one."""
    if ring.dtype != jnp.uint8:
        raise ValueError(
            f"fused_frame_gather decodes uint8 replay frames, got "
            f"{ring.dtype}; the HBM ring stores frames as uint8 by "
            "design (buffer/replay.py)"
        )
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        return _gather_frames_pallas(
            ring, idx, offsets, pad, normalize, out_dtype, frame_stack,
            interpret,
        )
    if impl != "xla":
        raise ValueError(f"unknown impl {impl!r} (auto|pallas|xla)")
    return gather_frames_reference(
        ring, idx, offsets, pad, normalize, out_dtype, frame_stack
    )
