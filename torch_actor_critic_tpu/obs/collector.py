"""Run-scoped metrics aggregator: one scraper thread, every plane.

The :class:`ObsCollector` owns the run-wide time series. Sources are
registered by name as either an in-process callable (the learner's own
``TelemetryRecorder.snapshot()`` — no HTTP round trip to yourself) or a
URL scraped over stdlib HTTP (:func:`http_source`: the serve router's
fleet-aggregated ``/metrics``, the staging transport's ``/metrics`` +
``/healthz``). On a fixed interval the scrape thread:

1. snapshots every source — a dead or unreachable target is a counted
   ``scrape_failed`` on that source (``live=False``, ``last_error``),
   never a raise and never a silent gap in the series;
2. folds the flattened snapshots through the plane-generic
   :func:`~torch_actor_critic_tpu.obs.merge.aggregate_snapshots`
   (dynamic mode: every ``*_total``-shaped counter sums, histograms
   bucket-merge, restarts never double-count);
3. evaluates the SLO rule set against the composite row, forwarding
   any ``slo_breach``/``slo_recovered`` events to the telemetry
   recorder;
4. appends the row to ``obs.jsonl`` and publishes it on the
   collector's own ``/metrics`` endpoint (``--obs-port``).

The trainer mirrors :meth:`metrics_columns` into metrics.jsonl as
``obs/`` columns, so the aggregated plane rides the same artifact
every other metric does. Threading: scrape state is guarded by
``_lock``; the HTTP handler only reads under the same lock.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import typing as t
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from torch_actor_critic_tpu.obs.merge import aggregate_snapshots, flatten_numeric
from torch_actor_critic_tpu.obs.slo import SLOEngine, default_rules
from torch_actor_critic_tpu.telemetry.sinks import JsonlSink, json_sanitize

__all__ = ["ObsCollector", "http_source"]

logger = logging.getLogger(__name__)

Source = t.Callable[[], t.Optional[t.Dict[str, t.Any]]]


def http_source(
    url: str,
    paths: t.Tuple[str, ...] = ("/metrics",),
    timeout_s: float = 2.0,
) -> Source:
    """Scrape callable over one process's stdlib-HTTP endpoints.

    The first path's JSON body is the snapshot; each extra path (e.g.
    ``/healthz``) is fetched too and nested under its name with the
    leading slash stripped — so the transport's conservation probe
    lands at ``<source>.healthz.conservation_ok``. Any failure raises
    out to the collector, which records it as a scrape failure."""
    base = url.rstrip("/")
    if "://" not in base:
        base = "http://" + base

    def scrape() -> t.Dict[str, t.Any]:
        out: t.Dict[str, t.Any] = {}
        for i, path in enumerate(paths):
            with urllib.request.urlopen(base + path, timeout=timeout_s) as r:
                body = json.loads(r.read().decode())
            if i == 0:
                out = body if isinstance(body, dict) else {"value": body}
            else:
                out[path.strip("/")] = body
        return out

    return scrape


class ObsCollector:
    """Aggregator thread + ``obs.jsonl`` writer + ``/metrics`` server.

    Built unstarted; :meth:`start` launches the scrape loop (the
    trainer calls it at ``train()`` entry, after every subclass has
    finished wiring its sources). :meth:`close` is idempotent and safe
    on a never-started collector."""

    def __init__(
        self,
        interval_s: float = 2.0,
        run_dir: t.Optional[str] = None,
        port: int = 0,
        rules: t.Optional[t.Sequence] = None,
        telemetry: t.Optional[t.Any] = None,
        max_bytes: int = 0,
    ):
        self.interval_s = float(interval_s)
        self.telemetry = telemetry
        self.slo = SLOEngine(default_rules() if rules is None else rules)
        self.sink = (
            JsonlSink(str(run_dir) + "/obs.jsonl", max_bytes=max_bytes)
            if run_dir is not None else None
        )
        self._lock = threading.Lock()
        self._sources: t.Dict[str, Source] = {}  # guarded-by: _lock
        self._stats: t.Dict[str, dict] = {}  # guarded-by: _lock
        self.scrapes_total = 0  # guarded-by: _lock
        self.scrape_failed_total = 0  # guarded-by: _lock
        self.slo_events_total = 0  # guarded-by: _lock
        self.last_scrape_ms = 0.0  # guarded-by: _lock
        self._last_row: t.Optional[dict] = None  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: t.Optional[threading.Thread] = None  # guarded-by: _lock
        # Per-window subscriber (the elastic controller's
        # observe_window). None by default: with no hook attached the
        # pointer check is the whole cost — the --elastic off contract.
        self.window_hook: t.Optional[t.Callable[[dict], t.Any]] = None
        self.port = 0
        self._server: t.Optional[ThreadingHTTPServer] = self._build_server(
            port
        )  # guarded-by: _lock

    # ------------------------------------------------------------- sources

    def add_source(self, name: str, source: t.Union[str, Source]):
        """Register a plane. A string is a base URL (scraped via
        :func:`http_source`); a callable returns the snapshot dict
        directly (or raises / returns None → counted failure)."""
        if isinstance(source, str):
            source = http_source(source)
        with self._lock:
            self._sources[name] = source
            self._stats.setdefault(name, {
                "scrapes": 0, "failures": 0, "live": False,
                "last_error": None, "last_scrape_ms": 0.0,
            })

    def remove_source(self, name: str) -> None:
        """Forget a plane (elastic scale-in: a drained worker stops
        being scraped instead of turning into a permanent counted
        failure). Its stats row is dropped too — source flapping is
        covered by the merge-layer tests: totals over the survivors
        never go negative and a re-added source re-enters the sum
        fresh."""
        with self._lock:
            self._sources.pop(name, None)
            self._stats.pop(name, None)

    def source_names(self) -> t.Tuple[str, ...]:
        with self._lock:
            return tuple(self._sources)

    # -------------------------------------------------------------- scrape

    def scrape_once(self) -> dict:
        """One window: scrape every source, merge, evaluate SLOs,
        persist. Never raises — a failing source is a counted
        ``scrape_failed`` entry; everything else proceeds."""
        t0 = time.perf_counter()
        with self._lock:
            sources = dict(self._sources)
        snaps: t.Dict[str, t.Optional[dict]] = {}
        raw: t.Dict[str, t.Optional[dict]] = {}
        for name, source in sources.items():
            s0 = time.perf_counter()
            err = None
            try:
                snap = source()
                if snap is not None and not isinstance(snap, dict):
                    snap = {"value": snap}
            except Exception as e:  # noqa: BLE001 - any source failure is a counted scrape_failed, never a crash
                snap, err = None, f"{type(e).__name__}: {e}"[:200]
            elapsed_ms = round(1e3 * (time.perf_counter() - s0), 3)
            raw[name] = snap
            snaps[name] = flatten_numeric(snap) if snap is not None else None
            with self._lock:
                st = self._stats.setdefault(name, {"scrapes": 0, "failures": 0})
                st["scrapes"] = st.get("scrapes", 0) + 1
                st["live"] = snap is not None
                st["last_scrape_ms"] = elapsed_ms
                if snap is None:
                    st["failures"] = st.get("failures", 0) + 1
                    st["last_error"] = err or "source returned None"
                    self.scrape_failed_total += 1
                else:
                    st["last_error"] = None
        merged = aggregate_snapshots(snaps, sum_keys=None)
        row: t.Dict[str, t.Any] = {
            "type": "obs",
            "time": time.time(),
            "sources": self._source_stats(),
            "merged": merged,
        }
        # Per-plane nested snapshots ride alongside the merged fold so
        # SLO paths can address one plane (``fleet.healthz.…``) or the
        # cross-plane totals (``merged.…``).
        for name, snap in raw.items():
            if name not in row:
                row[name] = snap if snap is not None else {"unreachable": True}
        events = self.slo.observe(row)
        slo_snap = self.slo.snapshot()
        row["slo"] = {
            "breaches_total": slo_snap["breaches_total"],
            "active_breaches": slo_snap["active_breaches"],
            "events": events,
        }
        if self.telemetry is not None:
            for ev in events:
                fields = {k: v for k, v in ev.items() if k != "type"}
                self.telemetry.event(ev["type"], **fields)
        scrape_ms = round(1e3 * (time.perf_counter() - t0), 3)
        with self._lock:
            self.scrapes_total += 1
            self.slo_events_total += len(events)
            self.last_scrape_ms = scrape_ms
            self._last_row = row
        if self.sink is not None:
            self.sink.write(row)
        hook = self.window_hook
        if hook is not None:
            try:
                hook(row)
            except Exception:  # noqa: BLE001 - a bad subscriber must not break the scrape series
                logger.exception("obs window hook failed")
        return row

    def _source_stats(self) -> dict:
        with self._lock:
            return {name: dict(st) for name, st in self._stats.items()}

    def metrics_columns(self) -> t.Dict[str, t.Any]:
        """The ``obs/`` columns the trainer mirrors into metrics.jsonl
        each epoch — the stable, flat summary of the plane."""
        with self._lock:
            stats = {n: dict(s) for n, s in self._stats.items()}
            out = {
                "obs/scrapes_total": self.scrapes_total,
                "obs/scrape_failed_total": self.scrape_failed_total,
                "obs/sources_total": len(stats),
                "obs/sources_live": sum(
                    1 for s in stats.values() if s.get("live")
                ),
                "obs/scrape_ms": self.last_scrape_ms,
            }
        slo = self.slo.snapshot()
        out["obs/slo_breaches_total"] = slo["breaches_total"]
        out["obs/slo_active"] = slo["active_breaches"]
        return out

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "ObsCollector":
        """Launch the scrape thread (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return self
            thread = threading.Thread(
                target=self._loop, name="obs-collector", daemon=True
            )
            self._thread = thread
        thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 - the collector thread must outlive any single bad window
                logger.exception("obs scrape window failed")
            self._stop.wait(self.interval_s)

    def _build_server(self, port: int) -> ThreadingHTTPServer:
        collector = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence stdlib access log
                pass

            def do_GET(self):  # noqa: N802 - stdlib handler API
                if self.path not in ("/metrics", "/healthz"):
                    self.send_error(404)
                    return
                if self.path == "/healthz":
                    with collector._lock:
                        live = sum(
                            1 for s in collector._stats.values()
                            if s.get("live")
                        )
                        total = len(collector._stats)
                    body = {"ok": True, "sources_live": live,
                            "sources_total": total}
                else:
                    with collector._lock:
                        body = {
                            "scrapes_total": collector.scrapes_total,
                            "scrape_failed_total": (
                                collector.scrape_failed_total
                            ),
                            "last_scrape_ms": collector.last_scrape_ms,
                            "sources": {
                                n: dict(s)
                                for n, s in collector._stats.items()
                            },
                            "last": collector._last_row,
                        }
                    body["slo"] = collector.slo.snapshot()
                data = json.dumps(json_sanitize(body)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        server.daemon_threads = True
        self.port = server.server_address[1]
        threading.Thread(
            target=server.serve_forever, name="obs-http", daemon=True
        ).start()
        return server

    @property
    def address(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self):
        """Stop the thread, take one final scrape if none ever ran,
        close the sink and server. Safe to call twice or unstarted."""
        self._stop.set()
        # Swap the handles out under the lock, then join/shutdown outside
        # it — the scrape loop and HTTP handler both take ``_lock``.
        with self._lock:
            thread, self._thread = self._thread, None
            server, self._server = self._server, None
        if thread is not None:
            thread.join(timeout=max(5.0, 2 * self.interval_s))
        if server is not None:
            server.shutdown()
            server.server_close()
        if self.sink is not None:
            self.sink.close()
