"""Run-wide observability plane (docs/OBSERVABILITY.md "Run-wide plane").

Everything before this package observes ONE process: a `/metrics`
endpoint per serve worker, one ``telemetry.jsonl`` per learner, one
Perfetto export per process. A fleet run is a learner + N actor
subprocesses + a router + M serve workers — and "why is the learner
starved" needs all of them on one screen. Three pillars:

- :mod:`~torch_actor_critic_tpu.obs.merge` — the fleet aggregation
  semantics (counter-sum over CURRENT snapshots, bucket-wise histogram
  merge, restart no-double-count) lifted out of ``serve/metrics`` so
  they apply to every plane, not just serving.
- :mod:`~torch_actor_critic_tpu.obs.collector` — a run-scoped scraper
  thread folding every process's ``/metrics`` (+ in-process callables)
  into one time series: ``obs.jsonl``, an aggregated ``/metrics``
  endpoint, and ``obs/`` columns in metrics.jsonl. A dead target is a
  counted ``scrape_failed``, never a crash or a silent gap.
- :mod:`~torch_actor_critic_tpu.obs.slo` — declarative SLO rules over
  the aggregated series, evaluated per scrape window with hysteresis,
  emitting ``slo_breach``/``slo_recovered`` events — the interface the
  ROADMAP item-2 autoscaler subscribes to.

Plus :mod:`~torch_actor_critic_tpu.obs.tracecollect`, which merges
per-process trace buffers (learner, actors, staging transport) into
the one Perfetto timeline ``--trace-export`` writes.
"""

from torch_actor_critic_tpu.obs.collector import ObsCollector, http_source
from torch_actor_critic_tpu.obs.merge import aggregate_snapshots
from torch_actor_critic_tpu.obs.slo import (
    SLOEngine,
    SLORule,
    default_rules,
    load_rules,
)
from torch_actor_critic_tpu.obs.tracecollect import actor_span_events

__all__ = [
    "ObsCollector",
    "SLOEngine",
    "SLORule",
    "actor_span_events",
    "aggregate_snapshots",
    "default_rules",
    "http_source",
    "load_rules",
]
