"""Declarative SLO engine over the aggregated obs series.

Rules (``--slo-config`` JSON or :func:`default_rules`) are evaluated
once per collector scrape window against the composite row the
:class:`~torch_actor_critic_tpu.obs.collector.ObsCollector` assembles
(``learner.*`` / ``fleet.*`` / ``serve.*`` dotted paths). Each rule is
a small hysteresis state machine:

- **arm-on-first-pass**: a rule emits nothing until its path first
  exists AND passes — so a goodput floor does not "breach" while the
  fleet is still warming up, and chip-only rules (MFU floor) stay
  silent on CPU runs (``missing_ok``).
- **hysteresis**: ``breach_windows`` consecutive failing windows flip
  an armed rule to breached (one ``slo_breach`` event);
  ``recover_windows`` consecutive passing windows flip it back (one
  ``slo_recovered``). A flapping signal cannot emit an event storm.
- **delta mode**: cumulative counters (``sheds_total``) are judged on
  their per-window increase, not their lifetime value.

The event stream is the exact interface the ROADMAP item-2 autoscaler
subscribes to; :meth:`SLOEngine.report` is the run-exit table.

Rule grammar (JSON list; docs/OBSERVABILITY.md "Run-wide plane")::

    [{"name": "goodput_floor",
      "path": "serve.requests_per_sec",   # dotted into the obs row
      "op": "min",                         # min: value >= threshold ok
      "threshold": 0.5,                    # max: value <= threshold ok
      "mode": "value",                     # or "delta" (per-window)
      "breach_windows": 2,
      "recover_windows": 2,
      "missing_ok": true}]
"""

from __future__ import annotations

import json
import logging
import time
import typing as t

logger = logging.getLogger(__name__)

__all__ = ["SLOEngine", "SLORule", "default_rules", "load_rules"]

_OPS = ("min", "max")
_MODES = ("value", "delta")


class SLORule:
    """One declarative rule: ``op='min'`` passes while the value stays
    at or above ``threshold`` (a floor), ``op='max'`` while it stays at
    or below (a ceiling). Booleans at the path coerce to 0/1, so an
    invariant like ``conservation_ok`` is ``op='min', threshold=1``."""

    def __init__(
        self,
        name: str,
        path: str,
        op: str,
        threshold: float,
        breach_windows: int = 2,
        recover_windows: int = 2,
        mode: str = "value",
        missing_ok: bool = True,
    ):
        if not name or not path:
            raise ValueError("SLO rule needs a name and a path")
        if op not in _OPS:
            raise ValueError(
                f"SLO rule {name!r}: op must be one of {_OPS}, got {op!r}"
            )
        if mode not in _MODES:
            raise ValueError(
                f"SLO rule {name!r}: mode must be one of {_MODES}, "
                f"got {mode!r}"
            )
        if breach_windows < 1 or recover_windows < 1:
            raise ValueError(
                f"SLO rule {name!r}: breach/recover windows must be "
                f">= 1, got {breach_windows}/{recover_windows}"
            )
        self.name = name
        self.path = path
        self.op = op
        self.threshold = float(threshold)
        self.breach_windows = int(breach_windows)
        self.recover_windows = int(recover_windows)
        self.mode = mode
        self.missing_ok = bool(missing_ok)

    def to_dict(self) -> dict:
        return {
            "name": self.name, "path": self.path, "op": self.op,
            "threshold": self.threshold, "mode": self.mode,
            "breach_windows": self.breach_windows,
            "recover_windows": self.recover_windows,
            "missing_ok": self.missing_ok,
        }

    def passes(self, value: float) -> bool:
        if self.op == "min":
            return value >= self.threshold
        return value <= self.threshold


_RULE_KEYS = frozenset(
    ("name", "path", "op", "threshold", "breach_windows",
     "recover_windows", "mode", "missing_ok")
)

# Appended to every grammar error so a typo'd config tells the operator
# the whole vocabulary, not just what broke.
_GRAMMAR_HINT = (
    f"valid keys: {sorted(_RULE_KEYS)}; comparators (op): "
    f"{list(_OPS)}; modes: {list(_MODES)}"
)


def _rule_label(i: int, spec: t.Any) -> str:
    """Name the offending rule in errors: its 'name' when it has one,
    its position otherwise."""
    name = spec.get("name") if isinstance(spec, dict) else None
    return f"rule {i} ({name!r})" if name else f"rule {i}"


def load_rules(path: str) -> t.List[SLORule]:
    """Parse an ``--slo-config`` JSON file. Grammar errors are
    ``ValueError`` at startup — a malformed SLO config should fail the
    run before it silently monitors nothing — and every one names the
    offending rule and lists the valid keys/comparators."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"cannot load SLO config {path}: {e}") from e
    if not isinstance(raw, list):
        raise ValueError(
            f"SLO config {path}: expected a JSON list of rules, got "
            f"{type(raw).__name__}"
        )
    rules = []
    for i, spec in enumerate(raw):
        label = _rule_label(i, spec)
        if not isinstance(spec, dict):
            raise ValueError(
                f"SLO config {path}: {label} is not an object; "
                f"{_GRAMMAR_HINT}"
            )
        unknown = set(spec) - _RULE_KEYS
        if unknown:
            raise ValueError(
                f"SLO config {path}: {label} has unknown keys "
                f"{sorted(unknown)}; {_GRAMMAR_HINT}"
            )
        missing = [k for k in ("name", "path") if not spec.get(k)]
        if "threshold" not in spec:
            missing.append("threshold")
        if missing:
            raise ValueError(
                f"SLO config {path}: {label} is missing "
                f"{', '.join(repr(k) for k in missing)}; "
                f"{_GRAMMAR_HINT}"
            )
        try:
            rules.append(SLORule(**spec))
        except ValueError as e:
            raise ValueError(
                f"SLO config {path}: {label}: {e}; {_GRAMMAR_HINT}"
            ) from e
        except TypeError as e:
            # Wrong-typed values (a dict threshold, a list for an int
            # field): float()/int() raise TypeError — surface it as
            # the same startup ValueError the rest of the grammar uses.
            raise ValueError(
                f"SLO config {path}: {label} has a wrong-typed value "
                f"({e}); {_GRAMMAR_HINT}"
            ) from e
    names = [r.name for r in rules]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise ValueError(
            f"SLO config {path}: duplicate rule names {dupes}"
        )
    return rules


def default_rules() -> t.List[SLORule]:
    """Built-in rule set over the collector's canonical source names
    (``learner``/``fleet``/``serve``). Every rule is ``missing_ok`` and
    arm-on-first-pass, so each engages only when its plane actually
    reports — the MFU floor stays silent on CPU runs, the serving rules
    on serve-less runs."""
    return [
        # Training goodput: post-warmup env throughput must not collapse.
        SLORule("goodput_floor", "learner.metrics.env_steps_per_sec",
                "min", 1.0),
        # Serving tail latency ceiling (fleet-merged histogram).
        SLORule("p99_ceiling", "serve.p99_ms", "max", 500.0),
        # Shed RATE ceiling: per-window increase of the cumulative
        # counter — a burst of load shedding, not lifetime totals.
        SLORule("shed_rate_ceiling", "serve.sheds_total", "max", 500.0,
                mode="delta"),
        # Actor staleness: the staging gate's lag tail (epochs behind).
        SLORule("actor_staleness_ceiling",
                "learner.decoupled.staging.actor_lag.actor_lag_p95",
                "max", 16.0),
        # Cross-process conservation invariant (transport /healthz).
        SLORule("conservation_ok", "fleet.healthz.conservation_ok",
                "min", 1.0, breach_windows=1),
        # Chip-run MFU floor; the path only exists when cost
        # attribution reports (telemetry on, real device peaks).
        SLORule("mfu_floor", "learner.metrics.cost/epoch_mfu",
                "min", 0.05),
    ]


def dig(row: t.Mapping[str, t.Any], path: str) -> t.Optional[float]:
    """Resolve a dotted path to a numeric leaf (bools coerce to 0/1);
    None when the path is absent or non-numeric."""
    node: t.Any = row
    for part in path.split("."):
        if not isinstance(node, t.Mapping) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool):
        return float(node)
    if isinstance(node, (int, float)):
        return float(node)
    return None


class _RuleState:
    __slots__ = (
        "armed", "breached", "ok_streak", "bad_streak", "breaches",
        "recoveries", "last_value", "prev_raw", "worst",
    )

    def __init__(self):
        self.armed = False
        self.breached = False
        self.ok_streak = 0
        self.bad_streak = 0
        self.breaches = 0
        self.recoveries = 0
        self.last_value: float | None = None
        self.prev_raw: float | None = None  # delta-mode memory
        self.worst: float | None = None


class SLOEngine:
    """Evaluate a rule set once per scrape window; emit exactly one
    structured event per state transition. Single-threaded by design:
    only the collector's scrape thread calls :meth:`observe`."""

    def __init__(
        self,
        rules: t.Sequence[SLORule],
        clock: t.Callable[[], float] = time.time,
    ):
        self.rules = list(rules)
        self._clock = clock
        self._state = {r.name: _RuleState() for r in self.rules}
        self.windows_evaluated = 0

    def observe(self, row: t.Mapping[str, t.Any]) -> t.List[dict]:
        """One scrape window: returns the (possibly empty) list of
        ``slo_breach``/``slo_recovered`` events this window caused."""
        self.windows_evaluated += 1
        events: t.List[dict] = []
        now = self._clock()
        for rule in self.rules:
            st = self._state[rule.name]
            raw = dig(row, rule.path)
            if raw is None:
                # Absent path: no verdict this window (missing_ok), or
                # a hard failing window when the rule demands the path.
                if rule.missing_ok or not st.armed:
                    continue
                value = None
            elif rule.mode == "delta":
                prev, st.prev_raw = st.prev_raw, raw
                if prev is None:
                    continue  # first sample: no window delta yet
                value = raw - prev
            else:
                value = raw
            ok = value is not None and rule.passes(value)
            st.last_value = value
            if value is not None:
                worse = (
                    st.worst is None
                    or (rule.op == "min" and value < st.worst)
                    or (rule.op == "max" and value > st.worst)
                )
                if worse:
                    st.worst = value
            if not st.armed:
                if ok:
                    st.armed = True
                    st.ok_streak = 1
                continue
            if st.breached:
                if ok:
                    st.ok_streak += 1
                    if st.ok_streak >= rule.recover_windows:
                        st.breached = False
                        st.bad_streak = 0
                        st.recoveries += 1
                        events.append(self._event(
                            "slo_recovered", rule, value, now
                        ))
                else:
                    st.ok_streak = 0
            else:
                if ok:
                    st.bad_streak = 0
                else:
                    st.bad_streak += 1
                    st.ok_streak = 0
                    if st.bad_streak >= rule.breach_windows:
                        st.breached = True
                        st.breaches += 1
                        events.append(self._event(
                            "slo_breach", rule, value, now
                        ))
        return events

    def _event(self, type_, rule, value, now) -> dict:
        ev = {
            "type": type_,
            "time": now,
            "rule": rule.name,
            "path": rule.path,
            "op": rule.op,
            "mode": rule.mode,
            "threshold": rule.threshold,
            "value": value,
            "window": self.windows_evaluated,
        }
        log = logger.warning if type_ == "slo_breach" else logger.info
        log(
            "SLO %s: %s (%s %s %g, observed %s)",
            "BREACH" if type_ == "slo_breach" else "recovered",
            rule.name, rule.path,
            ">=" if rule.op == "min" else "<=",
            rule.threshold, value,
        )
        return ev

    # ------------------------------------------------------------- reports

    def snapshot(self) -> dict:
        """``/metrics``-style summary: per-rule state + run totals."""
        rules = {}
        for rule in self.rules:
            st = self._state[rule.name]
            rules[rule.name] = {
                "path": rule.path,
                "op": rule.op,
                "threshold": rule.threshold,
                "armed": st.armed,
                "breached": st.breached,
                "breaches_total": st.breaches,
                "recoveries_total": st.recoveries,
                "last_value": st.last_value,
            }
        return {
            "windows_evaluated": self.windows_evaluated,
            "breaches_total": sum(
                s.breaches for s in self._state.values()
            ),
            "active_breaches": sum(
                1 for s in self._state.values() if s.breached
            ),
            "rules": rules,
        }

    def report(self) -> str:
        """Run-exit SLO table (logged by the trainer's close)."""
        header = (
            f"{'rule':<26} {'state':<10} {'breaches':>8} "
            f"{'recovered':>9} {'worst':>12} {'threshold':>10}"
        )
        lines = [
            f"SLO report ({self.windows_evaluated} windows):", header,
            "-" * len(header),
        ]
        for rule in self.rules:
            st = self._state[rule.name]
            state = (
                "BREACHED" if st.breached
                else "ok" if st.armed else "unarmed"
            )
            worst = "-" if st.worst is None else f"{st.worst:.4g}"
            lines.append(
                f"{rule.name:<26} {state:<10} {st.breaches:>8} "
                f"{st.recoveries:>9} {worst:>12} {rule.threshold:>10g}"
            )
        return "\n".join(lines)
