"""Cross-process trace collection: actor span files -> one timeline.

Actor subprocesses cannot hand span buffers to the learner in memory,
so each :class:`~torch_actor_critic_tpu.decoupled.transport.RemoteStagingClient`
(when a fleet run has tracing on) appends its ``stage_push`` spans to
``<run_dir>/stage_spans/actor<id>-<incarnation>.spans.jsonl`` — one
line per successful push, with **absolute** microsecond timestamps
(each actor anchors its own wall clock via
:func:`~torch_actor_critic_tpu.telemetry.traceview.perf_to_us` before
writing, so the files need no alien perf anchor to interpret) and the
``a<actor>.<incarnation>.<seq>`` span id that the transport's ingest
span and the learner's ``drain_window`` span also carry. At export
time :func:`actor_span_events` sweeps the directory and converts every
record onto that actor's own trace lane (``ACTOR_PID_BASE + actor_id``)
— merged with the learner's in-process buffers by ``export_trace``,
this is the one-screen fleet timeline the smoke asserts on.

A malformed line or unreadable file is skipped with a debug log,
never a raise: trace export runs in the run-exit path and must not
mask the run's real outcome.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import typing as t

from torch_actor_critic_tpu.telemetry.traceview import (
    ACTOR_PID_BASE,
    staging_span_events,
)

__all__ = ["actor_span_events"]

logger = logging.getLogger(__name__)


def actor_span_events(trace_dir: str | os.PathLike) -> t.List[dict]:
    """Read every ``*.spans.jsonl`` under ``trace_dir`` and return the
    trace events, each actor on its own ``ACTOR_PID_BASE + actor_id``
    lane. Missing directory -> empty list (a fleet run that never
    staged anything still exports cleanly)."""
    events: t.List[dict] = []
    pattern = os.path.join(str(trace_dir), "*.spans.jsonl")
    for path in sorted(glob.glob(pattern)):
        records: t.List[dict] = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        logger.debug("skipping bad span line in %s", path)
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
        except OSError as e:
            logger.debug("cannot read span file %s: %s", path, e)
            continue
        by_pid: t.Dict[int, t.List[dict]] = {}
        for rec in records:
            aid = rec.get("actor_id")
            pid = (
                ACTOR_PID_BASE + int(aid) if isinstance(aid, int)
                else ACTOR_PID_BASE
            )
            by_pid.setdefault(pid, []).append(rec)
        for pid, recs in sorted(by_pid.items()):
            events.extend(staging_span_events(recs, pid=pid))
    return events
