"""Plane-generic snapshot aggregation.

The fleet-fold semantics built for serving (PR 9) — lifted here so
every plane merges the same way:

- **Counters sum over the CURRENT snapshots**, never over deltas: a
  restarted source resets its own counters, so the aggregate reflects
  exactly what the live processes report and can never double-count a
  dead incarnation.
- **Latency histograms merge bucket-wise**
  (:meth:`FixedBucketHistogram.merge_raw`) and percentiles come from
  the merged estimator — identical to the histogram one process would
  have built from all the samples. Percentiles are never averaged
  (statistically meaningless). A spec-mismatched histogram becomes a
  recorded ``latency_merge_error``, never a raise.
- **Rates of disjoint streams add** (``requests_per_sec``).
- **A dead source stays in the output** as ``{"unreachable": true}``
  and contributes nothing to the totals — partial failure is visible,
  not silent.

``serve/metrics.aggregate_snapshots`` is now a thin delegate passing
its historical key set (output pinned bit-for-bit by
tests/test_fleet.py); the ObsCollector calls the dynamic mode
(``sum_keys=None``) over flattened cross-plane snapshots.
"""

from __future__ import annotations

import typing as t

from torch_actor_critic_tpu.telemetry.histogram import FixedBucketHistogram

__all__ = ["aggregate_snapshots", "flatten_numeric"]


def flatten_numeric(
    snap: t.Mapping[str, t.Any], sep: str = "/", max_depth: int = 3
) -> t.Dict[str, t.Any]:
    """Flatten a nested snapshot to one level of ``a/b/c`` keys,
    keeping numeric leaves plus any top-level ``latency_hist`` (the
    mergeable histogram state rides through un-flattened so
    :func:`aggregate_snapshots` can fold it)."""
    out: t.Dict[str, t.Any] = {}

    def walk(node: t.Mapping[str, t.Any], prefix: str, depth: int):
        for k, v in node.items():
            key = f"{prefix}{sep}{k}" if prefix else str(k)
            if isinstance(v, dict):
                if k == "latency_hist":
                    if not prefix:
                        out[key] = v
                elif depth < max_depth:
                    walk(v, key, depth + 1)
            elif isinstance(v, bool):
                out[key] = int(v)
            elif isinstance(v, (int, float)):
                out[key] = v

    walk(snap, "", 1)
    return out


def _dynamic_sum_key(key: str) -> bool:
    """Counter-shaped keys in dynamic (``sum_keys=None``) mode: the
    monotonic ``*_total`` family plus the gauge-style depth/compile
    keys every plane shares. Classified on the LEAF name so flattened
    paths (``staging/staged_total``) match like flat ones."""
    leaf = key.rsplit("/", 1)[-1]
    return leaf.endswith("_total") or leaf in (
        "queue_depth", "depth", "live_compiles",
    )


def aggregate_snapshots(
    sources: t.Mapping[str, t.Optional[t.Mapping[str, t.Any]]],
    *,
    sum_keys: t.Optional[t.Tuple[str, ...]] = None,
    rate_keys: t.Tuple[str, ...] = ("requests_per_sec",),
    merge_dict_keys: t.Tuple[str, ...] = (),
    hist_key: str = "latency_hist",
    label_keys: t.Optional[t.Tuple[str, ...]] = None,
    sources_key: str = "sources",
    reporting_key: str = "sources_reporting",
) -> t.Dict[str, t.Any]:
    """Fold per-source snapshots into one aggregate view.

    ``sum_keys`` names the counters to sum (each initialized to 0 even
    when absent everywhere — the serving contract); ``None`` sums every
    counter-shaped numeric key discovered in the live snapshots
    (``*_total`` / depth / ``live_compiles``), the cross-plane mode.
    ``rate_keys`` add (rates of disjoint streams), rounded to 2 as the
    fleet aggregate always did; ``merge_dict_keys`` name str->count
    dicts merged by key (``shed_by_reason``). ``label_keys`` selects
    the per-source labelled subset kept under ``sources_key`` (``None``
    keeps each full snapshot). A ``None`` snapshot is an unreachable
    source: labelled, counted out of ``reporting_key``, contributing
    nothing. This function never raises on malformed input — a
    histogram that fails to merge is a recorded
    ``latency_merge_error``."""
    dynamic = sum_keys is None
    out: t.Dict[str, t.Any] = {} if dynamic else {k: 0 for k in sum_keys}
    for k in merge_dict_keys:
        out[k] = {}
    for k in rate_keys:
        out[k] = 0.0
    skip = set(rate_keys) | set(merge_dict_keys) | {hist_key}
    per_source: t.Dict[str, t.Any] = {}
    merged = FixedBucketHistogram()
    merge_error = None
    for name, snap in sources.items():
        if snap is None:
            per_source[name] = {"unreachable": True}
            continue
        per_source[name] = (
            dict(snap) if label_keys is None
            else {k: snap.get(k) for k in label_keys if k in snap}
        )
        keys: t.Iterable[str] = (
            [k for k in snap if k not in skip and _dynamic_sum_key(k)]
            if dynamic else sum_keys
        )
        for k in keys:
            v = snap.get(k)
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + int(v)
        for dk in merge_dict_keys:
            for reason, n in (snap.get(dk) or {}).items():
                out[dk][reason] = out[dk].get(reason, 0) + int(n)
        for rk in rate_keys:
            rv = snap.get(rk)
            if isinstance(rv, (int, float)):
                out[rk] = round(out[rk] + float(rv), 2)
        hist = snap.get(hist_key)
        if hist is not None:
            try:
                merged.merge_raw(hist)
            except (ValueError, KeyError, TypeError) as e:
                merge_error = repr(e)[:200]
    if merged.count:
        p50, p95, p99 = merged.percentiles((50, 95, 99))
        out.update(
            mean_ms=round(merged.mean, 3), p50_ms=round(p50, 3),
            p95_ms=round(p95, 3), p99_ms=round(p99, 3),
            max_ms=round(merged.max, 3),
        )
    out[hist_key] = merged.raw_counts()
    if merge_error is not None:
        out["latency_merge_error"] = merge_error
    out[sources_key] = per_source
    out[reporting_key] = sum(
        1 for v in per_source.values() if not v.get("unreachable")
    )
    return out
