"""JSON frontends over the batcher: in-process client and HTTP server.

:class:`PolicyClient` is the zero-copy path for tests, benchmarks and
co-located actors: observations go straight into the micro-batching
queue as numpy arrays.

:class:`PolicyServer` is a stdlib ``ThreadingHTTPServer`` speaking
JSON — deliberately dependency-free (the container bakes no web
framework) and good for tens of thousands of requests/sec of small
observations, since each handler thread only parses JSON and parks on
a Future while the single dispatcher thread does the real (batched)
work:

- ``POST /act``     ``{"obs": [...] | {"features": [...], "frame": [...]},
  "deterministic": bool, "model": "default"}`` ->
  ``{"action": [...], "generation": N, "model": "..."}``
- ``GET /healthz``  liveness + per-slot generation/epoch (``draining``
  with HTTP 503 once a drain has started, so load balancers eject the
  replica while in-flight work finishes)
- ``GET /metrics``  :meth:`~torch_actor_critic_tpu.serve.metrics.ServeMetrics.snapshot`
- ``POST /reload``  force a checkpoint poll now (hot-reload check)

Overload contract (docs/SERVING.md "Overload & degradation"): a
request the admission layer rejects at submit time — queue full or
deadline infeasible — answers **429** + ``Retry-After`` (the service
is healthy, the rate is not); a request the service cannot currently
serve — breaker open, draining, expired in queue, backend timeout —
answers **503** + ``Retry-After``. Every rejection carries the
structured :class:`~torch_actor_critic_tpu.serve.admission.ShedError`
payload (``reason``, ``retry_after_s``).
"""

from __future__ import annotations

import json
import logging
import math
import random
import signal
import threading
import time
import typing as t
import uuid
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import numpy as np

from torch_actor_critic_tpu.core.types import MultiObservation
from torch_actor_critic_tpu.diagnostics.watchdog import get_watchdog as _watchdog
from torch_actor_critic_tpu.serve.admission import (
    SUBMIT_SHED_REASONS,
    ShedError,
)
from torch_actor_critic_tpu.serve.batcher import ActResult, MicroBatcher
from torch_actor_critic_tpu.serve.metrics import ServeMetrics
from torch_actor_critic_tpu.serve.registry import ModelRegistry

logger = logging.getLogger(__name__)

__all__ = ["PolicyClient", "PolicyServer", "install_drain_handler"]


class PolicyClient:
    """Access to the serving stack, in-process or over HTTP.

    **In-process mode** (``PolicyClient(registry, batcher)``): the
    zero-copy path — observations go straight into the micro-batching
    queue. One per process is enough; it is thread-safe.

    **HTTP mode** (``PolicyClient(url="http://host:port")``): the
    remote path actors and smoke harnesses use against a worker or a
    fleet router.

    **Retry semantics are transport-agnostic** (the decoupled
    actor/learner contract, docs/RESILIENCE.md): in BOTH modes ``act``
    retries rejected requests with **jittered backoff** honoring the
    server's own retry hint — the ``Retry-After`` header on the wire,
    the structured :class:`~torch_actor_critic_tpu.serve.admission.
    ShedError` ``retry_after_s`` in-process: on a retryable rejection
    the client sleeps ``max(hint, backoff·2^attempt)`` plus up to 25%
    jitter (decorrelates a herd of clients all told "retry in 1s"),
    for at most ``retries`` retry attempts — and is
    **deadline-aware**: the ``timeout`` passed to ``act`` is the
    caller's total budget, so a retry that could not complete before
    the deadline is never started and the last rejection (its
    ``ShedError`` taxonomy preserved) is raised instead. 4xx client
    errors and 5xx server faults — ``ValueError``/engine faults
    in-process — are never retried (retrying a malformed request or a
    broken engine is not backoff's job). Pass ``retries=0`` for the
    fail-fast behavior; :class:`PolicyServer`'s internal client does
    (the HTTP frontend IS the admission layer — retrying server-side
    would double-count sheds and hide backpressure from remote
    clients).
    """

    def __init__(
        self,
        registry: ModelRegistry | None = None,
        batcher: MicroBatcher | None = None,
        url: str | None = None,
        retries: int = 3,
        backoff_s: float = 0.25,
        sleep: t.Callable[[float], None] = time.sleep,
        rng: random.Random | None = None,
    ):
        if (url is None) == (batcher is None):
            raise ValueError(
                "pass either (registry, batcher) for in-process mode "
                "or url= for HTTP mode"
            )
        self.registry = registry
        self.batcher = batcher
        self.url = url.rstrip("/") if url is not None else None
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self.retries_total = 0

    def act(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
        timeout: float | None = 30.0,
        request_id: str | None = None,
    ) -> ActResult:
        if self.url is not None:
            return self._act_http(
                obs, deterministic, slot, timeout, request_id
            )
        return self._act_inprocess(
            obs, deterministic, slot, timeout, request_id
        )

    def act_async(
        self, obs: t.Any, deterministic: bool = True, slot: str = "default",
        request_id: str | None = None,
    ):
        if self.url is not None:
            raise RuntimeError(
                "act_async is in-process only; HTTP mode callers run "
                "act() on their own threads"
            )
        return self.batcher.submit(
            obs, deterministic, slot, request_id=request_id
        )

    # ----------------------------------------------------- in-process mode

    def _act_inprocess(self, obs, deterministic, slot, timeout, request_id):
        """In-process ``act`` with the SAME bounded, deadline-aware
        retry/backoff contract as HTTP mode: a structured rejection
        (``ShedError`` — queue full, breaker open, draining, expired)
        is retried up to ``retries`` times with jittered backoff off
        the shed's own ``retry_after_s`` hint, never past the caller's
        ``timeout``; the last rejection is re-raised with its taxonomy
        intact. Engine faults and request-shape errors propagate
        unretried (the 5xx/4xx analogue)."""
        deadline = (
            time.perf_counter() + timeout if timeout is not None else None
        )
        attempt = 0
        while True:
            remaining = (
                deadline - time.perf_counter()
                if deadline is not None else None
            )
            if remaining is not None and remaining <= 0:
                raise ShedError(
                    "deadline_infeasible",
                    f"client deadline of {timeout:.3f}s exhausted "
                    f"before attempt {attempt + 1}",
                )
            try:
                return self.batcher.act(
                    obs, deterministic, slot,
                    timeout=remaining, request_id=request_id,
                )
            except ShedError as e:
                if attempt >= self.retries:
                    raise
                delay = max(
                    e.retry_after_s, self.backoff_s * (2 ** attempt)
                )
                delay *= 1.0 + 0.25 * self._rng.random()  # jitter
                if deadline is not None and (
                    time.perf_counter() + delay >= deadline
                ):
                    # Never retry past the caller's deadline: raise
                    # the rejection we have (taxonomy intact) instead
                    # of one we'd manufacture by timing out mid-retry.
                    raise
                self.retries_total += 1
                attempt += 1
                self._sleep(delay)

    # ---------------------------------------------------------- HTTP mode

    def _act_http(self, obs, deterministic, slot, timeout, request_id):
        import urllib.error as urlerr
        import urllib.request as urlreq

        if hasattr(obs, "features"):  # MultiObservation pytree
            raw_obs: t.Any = {
                "features": np.asarray(obs.features).tolist(),
                "frame": np.asarray(obs.frame).tolist(),
            }
        else:
            raw_obs = np.asarray(obs).tolist()
        body = json.dumps({
            "obs": raw_obs, "deterministic": bool(deterministic),
            "model": slot,
        }).encode()
        deadline = (
            time.perf_counter() + timeout if timeout is not None else None
        )
        attempt = 0
        while True:
            remaining = (
                deadline - time.perf_counter()
                if deadline is not None else None
            )
            if remaining is not None and remaining <= 0:
                raise ShedError(
                    "deadline_infeasible",
                    f"client deadline of {timeout:.3f}s exhausted "
                    f"before attempt {attempt + 1}",
                )
            headers = {"Content-Type": "application/json"}
            if request_id is not None:
                headers["X-Request-Id"] = request_id
            try:
                req = urlreq.Request(
                    self.url + "/act", data=body, headers=headers
                )
                with urlreq.urlopen(
                    req, timeout=remaining if remaining is not None else 30.0
                ) as resp:
                    out = json.loads(resp.read())
                epoch = out.get("epoch")
                return ActResult(
                    np.asarray(out["action"], dtype=np.float32),
                    int(out.get("generation", 0)),
                    int(epoch) if epoch is not None else None,
                )
            except urlerr.HTTPError as e:
                try:
                    payload = json.loads(e.read())
                except (ValueError, OSError):
                    payload = {}
                if e.code not in (429, 503):
                    raise RuntimeError(
                        f"/act failed with HTTP {e.code}: "
                        f"{payload.get('error', '')}"
                    ) from None
                reason = payload.get("reason", f"http_{e.code}")
                if attempt >= self.retries:
                    raise ShedError(
                        reason,
                        payload.get(
                            "error",
                            f"rejected with {e.code} after "
                            f"{attempt + 1} attempts",
                        ),
                        retry_after_s=float(
                            payload.get("retry_after_s", 1.0)
                        ),
                        detail=payload,
                    ) from None
                ra = e.headers.get("Retry-After") if e.headers else None
                delay = max(
                    float(ra) if ra else 0.0,
                    self.backoff_s * (2 ** attempt),
                )
                delay *= 1.0 + 0.25 * self._rng.random()  # jitter
                if deadline is not None and (
                    time.perf_counter() + delay >= deadline
                ):
                    # Never retry past the caller's deadline: raise
                    # the rejection we have instead of one we'd
                    # manufacture by timing out mid-retry.
                    raise ShedError(
                        reason,
                        payload.get(
                            "error",
                            f"rejected with {e.code}; deadline too "
                            "near to honor Retry-After",
                        ),
                        retry_after_s=delay,
                        detail=payload,
                    ) from None
                self.retries_total += 1
                attempt += 1
                self._sleep(delay)


def _parse_obs(raw, obs_spec):
    """JSON observation -> numpy pytree matching ``obs_spec`` dtypes.

    Flat models take a plain (nested) list; visual models take
    ``{"features": ..., "frame": ...}`` (frames as uint8 nested lists).
    """
    if isinstance(obs_spec, MultiObservation):
        if not isinstance(raw, dict) or set(raw) != {"features", "frame"}:
            raise ValueError(
                'visual slot expects obs {"features": [...], "frame": [...]}'
            )
        return MultiObservation(
            features=np.asarray(
                raw["features"], dtype=obs_spec.features.dtype
            ),
            frame=np.asarray(raw["frame"], dtype=obs_spec.frame.dtype),
        )
    return np.asarray(raw, dtype=obs_spec.dtype)


class PolicyServer:
    """HTTP frontend owning the registry's batcher + metrics.

    ``port=0`` binds an ephemeral port (read it back from ``.port`` —
    the serve-smoke and test harness path). ``start()`` serves on a
    daemon thread; ``serve_forever()`` blocks (the CLI path).
    """

    def __init__(
        self,
        registry: ModelRegistry,
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        metrics: ServeMetrics | None = None,
        seed: int = 0,
        request_timeout_s: float = 30.0,
        act_timeout_s: float = 30.0,
        extra_snapshot: t.Callable[[], dict] | None = None,
        capacity: int = 1024,
        span_log=None,
        mode: str = "continuous",
        devices: t.Sequence | int | None = None,
        submesh: t.Tuple[int, int] | None = None,
        precision: str = "f32",
        fsdp_min_bytes: int | None = None,
        transition_logger=None,
    ):
        self.registry = registry
        # Data flywheel (replay/flywheel.py, docs/REPLAY.md): when set,
        # every successfully SERVED /act (behind admission — sheds and
        # breaker refusals never log) is sampled into a replay disk
        # tier, completed by the caller's POST /outcome. None (default)
        # costs one pointer check per answered request.
        self.transition_logger = transition_logger
        # Per-request trace spans (telemetry.traceview.RequestSpanLog):
        # attached by --trace-export; None costs one pointer check per
        # request in the batcher.
        self.span_log = span_log
        # Co-located processes (a trainer serving its own policy, a
        # custom health exporter) merge their own snapshot into
        # /metrics — e.g. a telemetry recorder's training phases under
        # one "training" key, so both planes report through one
        # endpoint and schema (docs/OBSERVABILITY.md).
        self.extra_snapshot = extra_snapshot
        # Per-connection socket timeout + bounded wait on the batcher
        # future: without these one stalled client (or a wedged engine)
        # pins a ThreadingHTTPServer handler thread FOREVER — the
        # stdlib default is no timeout at all — and a few thousand such
        # clients exhaust the thread pool, i.e. a trivial slow-loris.
        self.request_timeout_s = float(request_timeout_s)
        self.act_timeout_s = float(act_timeout_s)
        self.metrics = metrics if metrics is not None else ServeMetrics()
        # devices=None (or 1) keeps the single-device batcher; an int
        # > 1 or an explicit device list builds an EngineFleet — one
        # engine replica per device behind this server's one admission
        # layer (serve/fleet.py). The fleet duck-types the batcher
        # surface, so everything downstream is unchanged. A submesh or
        # non-f32 precision always takes the fleet path (sub-mesh
        # replicas, serve/sharded.py) — even with one replica.
        if submesh is not None or precision != "f32" or (
            devices is not None and not (
                isinstance(devices, int) and devices <= 1
            )
        ):
            from torch_actor_critic_tpu.serve.fleet import EngineFleet

            self.batcher: t.Any = EngineFleet(
                registry, devices=devices, max_batch=max_batch,
                max_wait_ms=max_wait_ms, metrics=self.metrics,
                seed=seed, capacity=capacity, span_log=span_log,
                mode=mode, submesh=submesh, precision=precision,
                fsdp_min_bytes=fsdp_min_bytes,
            )
            self.batcher.warmup()
        else:
            self.batcher = MicroBatcher(
                registry, max_batch=max_batch, max_wait_ms=max_wait_ms,
                metrics=self.metrics, seed=seed, capacity=capacity,
                span_log=span_log, mode=mode,
            )
        # retries=0: the frontend must surface sheds to remote clients
        # immediately (THEY own retry policy); a retrying internal
        # client would double-count sheds and sit on handler threads.
        self.client = PolicyClient(registry, self.batcher, retries=0)
        # Graceful-drain state (docs/SERVING.md "Overload &
        # degradation"): once draining, /healthz answers 503 so load
        # balancers stop routing here, new /act requests are shed with
        # 503 + Retry-After, and the queue flushes through the engine
        # before the process exits — rolling restarts drop zero
        # accepted requests.
        self._draining = False  # guarded-by: _drain_lock
        self._drain_lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Socket timeout for the whole connection (stdlib applies
            # the class attribute via connection.settimeout in setup();
            # handle_one_request maps the timeout to close_connection),
            # so a client that stops sending mid-request releases its
            # handler thread instead of wedging it forever.
            timeout = server.request_timeout_s

            # Keep the stdlib's per-request stderr lines out of the
            # serving hot path; route to logging at debug level.
            def log_message(self, fmt, *args):  # noqa: A003
                logger.debug("http: " + fmt, *args)

            def _send(
                self,
                code: int,
                payload: dict,
                headers: dict | None = None,
            ):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path == "/healthz":
                    draining = server.draining
                    self._send(
                        503 if draining else 200,
                        {
                            "status": "draining" if draining else "ok",
                            "queue_depth": server.batcher.queue_depth(),
                            "slots": server.registry.slots(),
                        },
                        headers={"Retry-After": "1"} if draining else None,
                    )
                elif self.path == "/metrics":
                    snap = server.metrics.snapshot()
                    # Compile accounting + the process-wide watchdog
                    # view (docs/OBSERVABILITY.md): `compiles_total` /
                    # per-slot bucket breakdown answer "did a live
                    # request pay a compile", `xla` carries source-
                    # attributed counts and steady-state anomalies.
                    comp = server.registry.compile_stats()
                    snap["compiles_total"] = comp["compiles_total"]
                    snap["live_compiles"] = comp["live_compiles"]
                    snap["bundle_compiles"] = comp.get("bundle_compiles", 0)
                    snap["compiles"] = comp["slots"]
                    snap["xla"] = _watchdog().snapshot()
                    # Overload containment state: admission bound and
                    # per-slot breaker trips/probes/state.
                    snap["queue_capacity"] = server.batcher.capacity
                    snap["draining"] = server.draining
                    snap["breakers"] = server.registry.breaker_stats()
                    # Engine-per-device fleet view (serve/fleet.py):
                    # per-replica load/EMA/dispatch share + per-replica
                    # breaker states and compile accounting.
                    if hasattr(server.batcher, "replica_stats"):
                        snap["fleet"] = {
                            "replicas": server.batcher.replica_stats(),
                            "compiles": server.batcher.compile_stats(),
                        }
                    # Sub-mesh serving view (serve/sharded.py):
                    # sub-mesh shape, precision tier, per-replica
                    # params-transfer bytes on reload.
                    if hasattr(server.batcher, "sharding_stats"):
                        sharding = server.batcher.sharding_stats()
                        if sharding is not None:
                            snap["sharding"] = sharding
                    # Per-bucket live roofline: registered program
                    # FLOPs/bytes over measured forward time
                    # (docs/OBSERVABILITY.md "Cost attribution").
                    snap["costs"] = server.metrics.cost_snapshot()
                    # Flywheel intake counters (sampled acts, matched
                    # outcomes, disk-tier residency).
                    if server.transition_logger is not None:
                        try:
                            snap["flywheel"] = (
                                server.transition_logger.snapshot()
                            )
                        except Exception as e:  # noqa: BLE001 — the
                            # base snapshot must survive a broken hook
                            snap["flywheel_error"] = repr(e)[:200]
                    if server.extra_snapshot is not None:
                        try:
                            snap.update(server.extra_snapshot())
                        except Exception as e:  # noqa: BLE001 — the
                            # base snapshot must survive a broken hook
                            snap["extra_snapshot_error"] = repr(e)[:200]
                    self._send(200, snap)
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802 — stdlib API
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length) if length else b"{}"
                    body = json.loads(raw or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._send(400, {"error": f"bad JSON body: {e}"})
                    return
                if self.path == "/act":
                    self._act(body)
                elif self.path == "/outcome":
                    self._outcome(body)
                elif self.path == "/reload":
                    self._send(200, {
                        "reload": server.registry.reload(body.get("model"))
                    })
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def _act(self, body: dict):
                # Correlation id: client-supplied X-Request-Id or a
                # generated one; echoed on EVERY response (incl. 429/
                # 503) and threaded through the shed/breaker log lines
                # and the per-request trace spans, so a rejection can
                # be matched to its timeline.
                rid = self.headers.get("X-Request-Id") or uuid.uuid4().hex[:16]
                rid_hdr = {"X-Request-Id": rid}
                if server.draining:
                    logger.warning(
                        "shed request_id=%s reason=draining", rid
                    )
                    self._send(
                        503,
                        {
                            "error": "server is draining; not accepting "
                                     "new requests",
                            "reason": "draining",
                            "request_id": rid,
                        },
                        headers={"Retry-After": "1", **rid_hdr},
                    )
                    return
                slot = body.get("model", "default")
                try:
                    engine, _, _ = server.registry.acquire(slot)
                except KeyError as e:
                    self._send(404, {"error": str(e)}, headers=rid_hdr)
                    return
                if "obs" not in body:
                    self._send(400, {"error": 'missing "obs"'}, headers=rid_hdr)
                    return
                try:
                    obs = _parse_obs(body["obs"], engine.obs_spec)
                    res = server.client.act(
                        obs,
                        deterministic=bool(body.get("deterministic", True)),
                        slot=slot,
                        timeout=server.act_timeout_s,
                        request_id=rid,
                    )
                except ShedError as e:
                    # Admission control / breaker / drain: submit-time
                    # rejections (queue_full, deadline_infeasible) are
                    # 429 — the service is healthy, the RATE is not;
                    # everything else (breaker_open, draining, expired
                    # in queue) is 503 — back off and let the load
                    # balancer try another replica. Both carry
                    # Retry-After from the shed's own estimate.
                    code = 429 if e.reason in SUBMIT_SHED_REASONS else 503
                    retry_after = max(1, math.ceil(e.retry_after_s))
                    logger.warning(
                        "shed request_id=%s slot=%s reason=%s -> %d",
                        rid, slot, e.reason, code,
                    )
                    self._send(
                        code, dict(e.to_payload(), request_id=rid),
                        headers={"Retry-After": str(retry_after), **rid_hdr},
                    )
                    return
                except FutureTimeoutError:
                    # Batcher overload/stall is transient, not a server
                    # bug: 503 + Retry-After tells well-behaved clients
                    # (and load balancers) to back off and retry, where
                    # a generic 500 reads as "broken, page someone".
                    logger.warning(
                        "timeout request_id=%s slot=%s after %.1fs",
                        rid, slot, server.act_timeout_s,
                    )
                    self._send(
                        503,
                        {
                            "error": "policy backend timed out; retry",
                            "timeout_s": server.act_timeout_s,
                            "request_id": rid,
                        },
                        headers={"Retry-After": "1", **rid_hdr},
                    )
                    return
                except (ValueError, TypeError) as e:
                    self._send(400, {"error": str(e)}, headers=rid_hdr)
                    return
                except Exception as e:  # noqa: BLE001 — engine failure
                    logger.exception("act failed (request_id=%s)", rid)
                    self._send(
                        500, {"error": repr(e)[:500], "request_id": rid},
                        headers=rid_hdr,
                    )
                    return
                if server.transition_logger is not None:
                    # Flywheel intake: the answered half of a
                    # transition, keyed by the correlation id the
                    # caller will echo in POST /outcome. Never allowed
                    # to fail a request that was already served.
                    try:
                        server.transition_logger.note_act(
                            rid, obs, np.asarray(res.action)
                        )
                    except Exception:  # noqa: BLE001
                        logger.exception(
                            "transition log failed (request_id=%s)", rid
                        )
                self._send(200, {
                    "action": np.asarray(res.action).tolist(),
                    "generation": res.generation,
                    "epoch": res.epoch,
                    "model": slot,
                }, headers=rid_hdr)

            def _outcome(self, body: dict):
                """Complete a flywheel transition: the caller reports
                what the environment did with the served action."""
                if server.transition_logger is None:
                    self._send(404, {
                        "error": "transition logging is not enabled "
                                 "(start with --log-transitions DIR)",
                    })
                    return
                rid = body.get("request_id")
                if not rid:
                    self._send(400, {"error": 'missing "request_id"'})
                    return
                if "reward" not in body or "next_obs" not in body:
                    self._send(400, {
                        "error": 'missing "reward"/"next_obs"',
                    })
                    return
                try:
                    engine, _, _ = server.registry.acquire(
                        body.get("model", "default")
                    )
                    next_obs = _parse_obs(
                        body["next_obs"], engine.obs_spec
                    )
                    matched = server.transition_logger.note_outcome(
                        rid,
                        float(body["reward"]),
                        next_obs,
                        bool(body.get("done", False)),
                    )
                except (KeyError, ValueError, TypeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                # matched=False (unknown/expired/unsampled id) is not
                # an error — downsampling drops ids by design; the
                # caller should fire-and-forget outcomes.
                self._send(200, {"logged": bool(matched), "request_id": rid})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None  # guarded-by: _drain_lock
        # shutdown() on a loop that NEVER ran blocks forever (stdlib
        # waits on the flag only serve_forever sets); close() skips it
        # unless one of the serve entry points actually started.
        self._loop_started = False  # guarded-by: _drain_lock

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self):
        """Serve on a background daemon thread (tests, smoke)."""
        # Registered slots warmed up before start; from here on any
        # serving-bucket compile is a steady-state anomaly (slots that
        # register later run their warmup as `expected`).
        _watchdog().install().mark_steady("serve/")
        with self._drain_lock:
            self._loop_started = True
            thread = self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="policy-http",
                daemon=True,
            )
        thread.start()
        return self

    def serve_forever(self):
        """Block serving until interrupted (the CLI path)."""
        _watchdog().install().mark_steady("serve/")
        with self._drain_lock:
            self._loop_started = True
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:  # pragma: no cover — operator stop
            pass
        finally:
            self.close()

    @property
    def draining(self) -> bool:
        with self._drain_lock:
            return self._draining

    def drain(self, flush_timeout_s: float = 30.0) -> dict:
        """Graceful drain: stop admitting, flush, report.

        From the first call, ``/healthz`` answers 503 ``draining`` (the
        load balancer ejects this replica) and new ``/act`` requests
        are shed with 503 + ``Retry-After`` — then every request
        already accepted flushes through the engine (the batcher close
        path answers its whole queue before joining), so in-flight HTTP
        handlers parked on Futures all complete normally. Idempotent;
        returns what happened so the caller (SIGTERM handler, tests)
        can assert zero accepted requests were dropped."""
        with self._drain_lock:
            first = not self._draining
            self._draining = True
        if first:
            logger.info(
                "draining: admissions stopped, flushing %d queued "
                "requests", self.batcher.queue_depth(),
            )
        self.batcher.close(timeout=flush_timeout_s)
        remaining = self.batcher.queue_depth()
        if remaining:  # pragma: no cover — only a wedged engine
            logger.warning(
                "drain flush left %d requests unanswered after %.1fs",
                remaining, flush_timeout_s,
            )
        snap = self.metrics.snapshot()
        return {
            "drained": remaining == 0,
            "queued_at_exit": remaining,
            "responses_total": snap["responses_total"],
            "sheds_total": snap["sheds_total"],
        }

    def close(self, thread_join_timeout_s: float = 10.0) -> dict:
        """Stop everything; returns a structured result. A server
        thread that survives its join (a handler wedged past every
        timeout) is LOGGED and surfaced in the result instead of
        silently leaking — the caller deciding to exit anyway should
        know a non-daemon-joinable thread is still out there."""
        result = {"server_thread_stopped": True}
        _watchdog().clear_steady("serve/")
        # Read/clear the lifecycle handles under the lock; shutdown()
        # and join() run OUTSIDE it — a wedged handler wanting the
        # drain lock must never deadlock close().
        with self._drain_lock:
            loop_started = self._loop_started
            thread, self._thread = self._thread, None
        if loop_started:
            self._httpd.shutdown()
        self._httpd.server_close()
        if thread is not None:
            thread.join(timeout=thread_join_timeout_s)
            if thread.is_alive():
                logger.warning(
                    "server thread %r still alive after %.1fs join "
                    "(daemon=%s) — leaking it; a handler is wedged "
                    "past its timeouts",
                    thread.name, thread_join_timeout_s, thread.daemon,
                )
                result["server_thread_stopped"] = False
                result["server_thread"] = {
                    "name": thread.name,
                    "daemon": thread.daemon,
                }
        self.batcher.close()
        self.registry.close()
        return result

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def install_drain_handler(
    server: PolicyServer,
    signals: t.Sequence[int] = (signal.SIGTERM,),
    flush_timeout_s: float = 30.0,
) -> t.Callable[[], None]:
    """SIGTERM → graceful drain → clean exit (the rolling-restart
    contract): admissions stop (503 + ``Retry-After``; ``/healthz``
    flips to ``draining``), the queue flushes through the engine, the
    HTTP loop is released — ``serve_forever`` returns, ``close()`` runs
    and the process exits 0 having answered every accepted request.

    The drain runs on a helper thread: a Python signal handler executes
    on the main thread, which for the CLI is the one blocked inside
    ``serve_forever`` — flushing there would deadlock. Must be called
    from the main thread (stdlib ``signal`` requirement). Returns the
    drain trigger so tests can invoke the same path directly."""

    def _drain_and_release():
        try:
            info = server.drain(flush_timeout_s=flush_timeout_s)
            logger.info("drain complete: %s", info)
        finally:
            # Releases serve_forever(); its finally-close() handles the
            # rest. Safe when start() was used instead: shutdown() of a
            # stopped loop is a no-op.
            server._httpd.shutdown()

    def _handler(signum, frame):  # pragma: no cover — exercised via
        # the direct trigger in tests (signal delivery itself is the
        # stdlib's contract, not ours)
        logger.info("signal %d: starting graceful drain", signum)
        threading.Thread(
            target=_drain_and_release, name="drain", daemon=True
        ).start()

    for sig in signals:
        signal.signal(sig, _handler)
    return lambda: threading.Thread(
        target=_drain_and_release, name="drain", daemon=True
    ).start()
