"""JSON frontends over the batcher: in-process client and HTTP server.

:class:`PolicyClient` is the zero-copy path for tests, benchmarks and
co-located actors: observations go straight into the micro-batching
queue as numpy arrays.

:class:`PolicyServer` is a stdlib ``ThreadingHTTPServer`` speaking
JSON — deliberately dependency-free (the container bakes no web
framework) and good for tens of thousands of requests/sec of small
observations, since each handler thread only parses JSON and parks on
a Future while the single dispatcher thread does the real (batched)
work:

- ``POST /act``     ``{"obs": [...] | {"features": [...], "frame": [...]},
  "deterministic": bool, "model": "default"}`` ->
  ``{"action": [...], "generation": N, "model": "..."}``
- ``GET /healthz``  liveness + per-slot generation/epoch
- ``GET /metrics``  :meth:`~torch_actor_critic_tpu.serve.metrics.ServeMetrics.snapshot`
- ``POST /reload``  force a checkpoint poll now (hot-reload check)
"""

from __future__ import annotations

import json
import logging
import threading
import typing as t
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import numpy as np

from torch_actor_critic_tpu.core.types import MultiObservation
from torch_actor_critic_tpu.diagnostics.watchdog import get_watchdog as _watchdog
from torch_actor_critic_tpu.serve.batcher import ActResult, MicroBatcher
from torch_actor_critic_tpu.serve.metrics import ServeMetrics
from torch_actor_critic_tpu.serve.registry import ModelRegistry

logger = logging.getLogger(__name__)

__all__ = ["PolicyClient", "PolicyServer"]


class PolicyClient:
    """Direct in-process access to the serving stack — same batching,
    no HTTP. One per process is enough; it is thread-safe."""

    def __init__(self, registry: ModelRegistry, batcher: MicroBatcher):
        self.registry = registry
        self.batcher = batcher

    def act(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
        timeout: float | None = 30.0,
    ) -> ActResult:
        return self.batcher.act(obs, deterministic, slot, timeout=timeout)

    def act_async(
        self, obs: t.Any, deterministic: bool = True, slot: str = "default"
    ):
        return self.batcher.submit(obs, deterministic, slot)


def _parse_obs(raw, obs_spec):
    """JSON observation -> numpy pytree matching ``obs_spec`` dtypes.

    Flat models take a plain (nested) list; visual models take
    ``{"features": ..., "frame": ...}`` (frames as uint8 nested lists).
    """
    if isinstance(obs_spec, MultiObservation):
        if not isinstance(raw, dict) or set(raw) != {"features", "frame"}:
            raise ValueError(
                'visual slot expects obs {"features": [...], "frame": [...]}'
            )
        return MultiObservation(
            features=np.asarray(
                raw["features"], dtype=obs_spec.features.dtype
            ),
            frame=np.asarray(raw["frame"], dtype=obs_spec.frame.dtype),
        )
    return np.asarray(raw, dtype=obs_spec.dtype)


class PolicyServer:
    """HTTP frontend owning the registry's batcher + metrics.

    ``port=0`` binds an ephemeral port (read it back from ``.port`` —
    the serve-smoke and test harness path). ``start()`` serves on a
    daemon thread; ``serve_forever()`` blocks (the CLI path).
    """

    def __init__(
        self,
        registry: ModelRegistry,
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        metrics: ServeMetrics | None = None,
        seed: int = 0,
        request_timeout_s: float = 30.0,
        act_timeout_s: float = 30.0,
        extra_snapshot: t.Callable[[], dict] | None = None,
    ):
        self.registry = registry
        # Co-located processes (a trainer serving its own policy, a
        # custom health exporter) merge their own snapshot into
        # /metrics — e.g. a telemetry recorder's training phases under
        # one "training" key, so both planes report through one
        # endpoint and schema (docs/OBSERVABILITY.md).
        self.extra_snapshot = extra_snapshot
        # Per-connection socket timeout + bounded wait on the batcher
        # future: without these one stalled client (or a wedged engine)
        # pins a ThreadingHTTPServer handler thread FOREVER — the
        # stdlib default is no timeout at all — and a few thousand such
        # clients exhaust the thread pool, i.e. a trivial slow-loris.
        self.request_timeout_s = float(request_timeout_s)
        self.act_timeout_s = float(act_timeout_s)
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.batcher = MicroBatcher(
            registry, max_batch=max_batch, max_wait_ms=max_wait_ms,
            metrics=self.metrics, seed=seed,
        )
        self.client = PolicyClient(registry, self.batcher)
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Socket timeout for the whole connection (stdlib applies
            # the class attribute via connection.settimeout in setup();
            # handle_one_request maps the timeout to close_connection),
            # so a client that stops sending mid-request releases its
            # handler thread instead of wedging it forever.
            timeout = server.request_timeout_s

            # Keep the stdlib's per-request stderr lines out of the
            # serving hot path; route to logging at debug level.
            def log_message(self, fmt, *args):  # noqa: A003
                logger.debug("http: " + fmt, *args)

            def _send(
                self,
                code: int,
                payload: dict,
                headers: dict | None = None,
            ):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path == "/healthz":
                    self._send(200, {
                        "status": "ok",
                        "queue_depth": server.batcher.queue_depth(),
                        "slots": server.registry.slots(),
                    })
                elif self.path == "/metrics":
                    snap = server.metrics.snapshot()
                    # Compile accounting + the process-wide watchdog
                    # view (docs/OBSERVABILITY.md): `compiles_total` /
                    # per-slot bucket breakdown answer "did a live
                    # request pay a compile", `xla` carries source-
                    # attributed counts and steady-state anomalies.
                    comp = server.registry.compile_stats()
                    snap["compiles_total"] = comp["compiles_total"]
                    snap["live_compiles"] = comp["live_compiles"]
                    snap["compiles"] = comp["slots"]
                    snap["xla"] = _watchdog().snapshot()
                    if server.extra_snapshot is not None:
                        try:
                            snap.update(server.extra_snapshot())
                        except Exception as e:  # noqa: BLE001 — the
                            # base snapshot must survive a broken hook
                            snap["extra_snapshot_error"] = repr(e)[:200]
                    self._send(200, snap)
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802 — stdlib API
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length) if length else b"{}"
                    body = json.loads(raw or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._send(400, {"error": f"bad JSON body: {e}"})
                    return
                if self.path == "/act":
                    self._act(body)
                elif self.path == "/reload":
                    self._send(200, {
                        "reload": server.registry.reload(body.get("model"))
                    })
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def _act(self, body: dict):
                slot = body.get("model", "default")
                try:
                    engine, _, _ = server.registry.acquire(slot)
                except KeyError as e:
                    self._send(404, {"error": str(e)})
                    return
                if "obs" not in body:
                    self._send(400, {"error": 'missing "obs"'})
                    return
                try:
                    obs = _parse_obs(body["obs"], engine.obs_spec)
                    res = server.client.act(
                        obs,
                        deterministic=bool(body.get("deterministic", True)),
                        slot=slot,
                        timeout=server.act_timeout_s,
                    )
                except FutureTimeoutError:
                    # Batcher overload/stall is transient, not a server
                    # bug: 503 + Retry-After tells well-behaved clients
                    # (and load balancers) to back off and retry, where
                    # a generic 500 reads as "broken, page someone".
                    self._send(
                        503,
                        {
                            "error": "policy backend timed out; retry",
                            "timeout_s": server.act_timeout_s,
                        },
                        headers={"Retry-After": "1"},
                    )
                    return
                except (ValueError, TypeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 — engine failure
                    logger.exception("act failed")
                    self._send(500, {"error": repr(e)[:500]})
                    return
                self._send(200, {
                    "action": np.asarray(res.action).tolist(),
                    "generation": res.generation,
                    "model": slot,
                })

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self):
        """Serve on a background daemon thread (tests, smoke)."""
        # Registered slots warmed up before start; from here on any
        # serving-bucket compile is a steady-state anomaly (slots that
        # register later run their warmup as `expected`).
        _watchdog().install().mark_steady("serve/")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="policy-http", daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self):
        """Block serving until interrupted (the CLI path)."""
        _watchdog().install().mark_steady("serve/")
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:  # pragma: no cover — operator stop
            pass
        finally:
            self.close()

    def close(self):
        _watchdog().clear_steady("serve/")
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self.batcher.close()
        self.registry.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
