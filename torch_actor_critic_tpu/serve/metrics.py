"""Serving metrics: queue depth, batch occupancy, rate, latency tails.

A single lock-guarded accumulator shared by the batcher and the HTTP
frontend. Latencies keep a bounded sliding window (default 8192
samples) for percentile estimates — enough resolution for p99 at
serving rates while bounding memory; total counters never reset, and
:meth:`snapshot` derives requests/sec over the window between snapshots
(falling back to lifetime rate on the first call).
"""

from __future__ import annotations

import collections
import threading
import time
import typing as t

import numpy as np

__all__ = ["ServeMetrics"]


class ServeMetrics:
    def __init__(self, latency_window: int = 8192):
        self._lock = threading.Lock()
        self._t_start = time.perf_counter()
        self._t_snapshot = self._t_start
        self.requests_total = 0
        self.responses_total = 0
        self.errors_total = 0
        self.batches_total = 0
        self.rows_total = 0
        self.padded_rows_total = 0  # sum of bucket sizes dispatched
        self.queue_depth = 0
        self._responses_at_snapshot = 0
        self._snapshots_taken = 0
        self._latencies_ms: collections.deque = collections.deque(
            maxlen=latency_window
        )

    # ----------------------------------------------------------- recording

    def record_enqueue(self, depth: int):
        with self._lock:
            self.requests_total += 1
            self.queue_depth = depth

    def record_batch(self, rows: int, bucket: int):
        with self._lock:
            self.batches_total += 1
            self.rows_total += rows
            self.padded_rows_total += bucket

    def record_done(self, latency_ms: float):
        with self._lock:
            self.responses_total += 1
            self._latencies_ms.append(latency_ms)

    def record_error(self):
        with self._lock:
            self.errors_total += 1

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> t.Dict[str, t.Any]:
        """Point-in-time metrics dict (the ``/metrics`` payload and the
        bench JSON's ``serving`` keys)."""
        with self._lock:
            now = time.perf_counter()
            window_s = now - self._t_snapshot
            window_responses = self.responses_total - self._responses_at_snapshot
            lifetime_s = now - self._t_start
            first_snapshot = self._snapshots_taken == 0
            self._snapshots_taken += 1
            self._t_snapshot = now
            self._responses_at_snapshot = self.responses_total
            lat = np.asarray(self._latencies_ms, dtype=np.float64)
            out = {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "errors_total": self.errors_total,
                "batches_total": self.batches_total,
                "queue_depth": self.queue_depth,
                "uptime_s": round(lifetime_s, 3),
                # Occupancy: real rows per dispatched row slot — 1.0
                # means every forward ran a full bucket, low values mean
                # deadline flushes of tiny batches (tune max_wait_ms).
                "mean_batch_occupancy": (
                    round(self.rows_total / self.padded_rows_total, 4)
                    if self.padded_rows_total else None
                ),
                "mean_rows_per_batch": (
                    round(self.rows_total / self.batches_total, 2)
                    if self.batches_total else None
                ),
                # Rate over the window since the previous snapshot. The
                # lifetime fallback applies ONLY to the very first
                # snapshot (no window exists yet); afterwards an idle
                # window honestly reports 0.0 instead of echoing a
                # stale lifetime rate.
                "requests_per_sec": round(
                    (self.responses_total / lifetime_s
                     if lifetime_s > 1e-9 else 0.0)
                    if first_snapshot
                    else (window_responses / window_s
                          if window_s > 1e-9 else 0.0),
                    2,
                ),
            }
        if lat.size:
            p50, p95, p99 = np.percentile(lat, [50, 95, 99])
            out.update(
                p50_ms=round(float(p50), 3),
                p95_ms=round(float(p95), 3),
                p99_ms=round(float(p99), 3),
                max_ms=round(float(lat.max()), 3),
            )
        return out
