"""Serving metrics: queue depth, batch occupancy, rate, latency tails.

A single lock-guarded accumulator shared by the batcher and the HTTP
frontend. Latencies feed a **fixed-bucket** log-spaced histogram
(:class:`~torch_actor_critic_tpu.telemetry.histogram.FixedBucketHistogram`
— the same estimator the training telemetry snapshot uses, so both
planes report percentiles through one schema, docs/OBSERVABILITY.md):
constant memory at any request volume, Prometheus-style cumulative
semantics (percentiles are over the process lifetime, never reset).
Total counters never reset either; :meth:`snapshot` derives
requests/sec over the window between snapshots (falling back to the
lifetime rate on the first call).
"""

from __future__ import annotations

import threading
import time
import typing as t

from torch_actor_critic_tpu.telemetry.histogram import FixedBucketHistogram

__all__ = ["ServeMetrics", "aggregate_snapshots"]

# Monotonic counters a fleet aggregate sums over its CURRENT workers.
# Summing live values (instead of accumulating deltas over time) is
# what makes the aggregate restart-safe: a worker that restarted
# resets its own counters, so the fleet total simply reflects the new
# process — it can never double-count the dead incarnation.
_SUM_KEYS = (
    "requests_total", "responses_total", "errors_total", "batches_total",
    "queue_depth", "sheds_total", "shed_expired_total",
    "compiles_total", "live_compiles",
    "reload_transfer_bytes_total", "param_placements_total",
)


class ServeMetrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._t_start = time.perf_counter()
        self._t_snapshot = self._t_start  # guarded-by: _lock
        self.requests_total = 0  # guarded-by: _lock
        self.responses_total = 0  # guarded-by: _lock
        self.errors_total = 0  # guarded-by: _lock
        self.batches_total = 0  # guarded-by: _lock
        self.rows_total = 0  # guarded-by: _lock
        self.padded_rows_total = 0  # bucket sizes sum; guarded-by: _lock
        self.queue_depth = 0  # guarded-by: _lock
        # Admission-control accounting (docs/SERVING.md "Overload &
        # degradation"): submit-time rejections by reason, plus
        # accepted-then-purged requests whose deadline expired in the
        # queue (the TPU never ran them).
        self.sheds_total = 0  # guarded-by: _lock
        self.shed_by_reason: t.Dict[str, int] = {}  # guarded-by: _lock
        self.shed_expired_total = 0  # guarded-by: _lock
        self._responses_at_snapshot = 0  # guarded-by: _lock
        self._snapshots_taken = 0  # guarded-by: _lock
        self._latency = FixedBucketHistogram()  # guarded-by: _lock
        # Per-bucket forward-time accounting (cost attribution): the
        # dispatcher reports each engine call's measured duration so
        # /metrics can combine it with the bucket program's registered
        # FLOPs/bytes into a live roofline (docs/OBSERVABILITY.md
        # "Cost attribution & roofline").
        self._bucket_time: t.Dict[int, t.Dict[str, float]] = (
            {}
        )  # guarded-by: _lock
        self._peaks = None  # costmodel.Peaks, lazy; guarded-by: _lock
        # Params-placement accounting (sub-mesh serving,
        # docs/SERVING.md "Sharded serving & precision tiers"): bytes
        # actually moved by generation-/precision-keyed device_puts —
        # the counter the one-transfer-per-device hot-reload contract
        # is asserted against.
        self.reload_transfer_bytes_total = 0  # guarded-by: _lock
        self.param_placements_total = 0  # guarded-by: _lock
        # Which registered jit identity cost_snapshot resolves bucket
        # programs under; the sub-mesh fleet flips this to its own
        # entry point ("serve/sharded_forward").
        self.cost_prefix = "serve/forward"

    # ----------------------------------------------------------- recording

    def record_enqueue(self, depth: int):
        with self._lock:
            self.requests_total += 1
            self.queue_depth = depth

    def record_batch(self, rows: int, bucket: int, dur_s: float = 0.0):
        with self._lock:
            self.batches_total += 1
            self.rows_total += rows
            self.padded_rows_total += bucket
            if dur_s > 0.0:
                agg = self._bucket_time.setdefault(
                    bucket, {"calls": 0, "rows": 0, "total_s": 0.0}
                )
                agg["calls"] += 1
                agg["rows"] += rows
                agg["total_s"] += dur_s

    def record_done(self, latency_ms: float):
        with self._lock:
            self.responses_total += 1
            self._latency.record(latency_ms)

    def record_error(self):
        with self._lock:
            self.errors_total += 1

    def record_shed(self, reason: str):
        """One request rejected by admission control (submit time) or
        failed fast by the circuit breaker (dispatch time)."""
        with self._lock:
            self.sheds_total += 1
            self.shed_by_reason[reason] = (
                self.shed_by_reason.get(reason, 0) + 1
            )

    def record_transfer(self, nbytes: int):
        """One params placement (a replica's generation- or
        precision-keyed ``device_put``) of ``nbytes`` actual bytes."""
        with self._lock:
            self.reload_transfer_bytes_total += int(nbytes)
            self.param_placements_total += 1

    def record_expired(self, n: int = 1):
        """Accepted requests purged at group-collection time because
        their deadline passed while queued — never dispatched."""
        with self._lock:
            self.shed_expired_total += n
            self.sheds_total += n
            self.shed_by_reason["expired"] = (
                self.shed_by_reason.get("expired", 0) + n
            )

    # ------------------------------------------------------------ snapshot

    def cost_snapshot(self) -> t.Dict[str, t.Any]:
        """Per-bucket live roofline for ``/metrics`` ``costs``: each
        bucket's registered program cost (``serve/forward[bN]``,
        populated at engine warmup) against its measured cumulative
        forward time — achieved FLOP/s, arithmetic intensity, MFU and
        compute-/memory-bound classification when peaks are known.
        Buckets with no registered cost or no traffic are omitted."""
        from torch_actor_critic_tpu.telemetry.costmodel import (
            Peaks,
            get_cost_registry,
            roofline,
        )

        with self._lock:
            buckets = {
                b: dict(agg) for b, agg in self._bucket_time.items()
            }
            if self._peaks is None:
                self._peaks = Peaks.detect()
            peaks = self._peaks
        registry = get_cost_registry()
        out: t.Dict[str, t.Any] = {}
        for b, agg in sorted(buckets.items()):
            cost = registry.get(f"{self.cost_prefix}[b{b}]")
            if cost is None or agg["total_s"] <= 0.0:
                continue
            entry = roofline(
                cost, agg["total_s"], calls=int(agg["calls"]), peaks=peaks
            )
            entry["rows"] = int(agg["rows"])
            out[f"b{b}"] = entry
        return out

    def snapshot(self) -> t.Dict[str, t.Any]:
        """Point-in-time metrics dict (the ``/metrics`` payload and the
        bench JSON's ``serving`` keys)."""
        with self._lock:
            now = time.perf_counter()
            window_s = now - self._t_snapshot
            window_responses = self.responses_total - self._responses_at_snapshot
            lifetime_s = now - self._t_start
            first_snapshot = self._snapshots_taken == 0
            self._snapshots_taken += 1
            self._t_snapshot = now
            self._responses_at_snapshot = self.responses_total
            out = {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "errors_total": self.errors_total,
                "batches_total": self.batches_total,
                "queue_depth": self.queue_depth,
                "sheds_total": self.sheds_total,
                "shed_by_reason": dict(self.shed_by_reason),
                "shed_expired_total": self.shed_expired_total,
                "reload_transfer_bytes_total": (
                    self.reload_transfer_bytes_total
                ),
                "param_placements_total": self.param_placements_total,
                "uptime_s": round(lifetime_s, 3),
                # Occupancy: real rows per dispatched row slot — 1.0
                # means every forward ran a full bucket, low values mean
                # deadline flushes of tiny batches (tune max_wait_ms).
                "mean_batch_occupancy": (
                    round(self.rows_total / self.padded_rows_total, 4)
                    if self.padded_rows_total else None
                ),
                "mean_rows_per_batch": (
                    round(self.rows_total / self.batches_total, 2)
                    if self.batches_total else None
                ),
                # Rate over the window since the previous snapshot. The
                # lifetime fallback applies ONLY to the very first
                # snapshot (no window exists yet); afterwards an idle
                # window honestly reports 0.0 instead of echoing a
                # stale lifetime rate.
                "requests_per_sec": round(
                    (self.responses_total / lifetime_s
                     if lifetime_s > 1e-9 else 0.0)
                    if first_snapshot
                    else (window_responses / window_s
                          if window_s > 1e-9 else 0.0),
                    2,
                ),
            }
            # Latency tails AND the mean from the same fixed-bucket
            # histogram: p50/p95/p99 interpolated (error bounded by the
            # ~19% bucket width), mean/max exact side counters.
            if self._latency.count:
                p50, p95, p99 = self._latency.percentiles((50, 95, 99))
                out.update(
                    mean_ms=round(self._latency.mean, 3),
                    p50_ms=round(p50, 3),
                    p95_ms=round(p95, 3),
                    p99_ms=round(p99, 3),
                    max_ms=round(self._latency.max, 3),
                )
            # The mergeable histogram state (counts vector + spec):
            # a fleet router folds every worker's export into ONE
            # histogram, so fleet percentiles come from the same
            # estimator — never from averaging per-worker percentiles,
            # which is statistically meaningless.
            out["latency_hist"] = self._latency.raw_counts()
        return out


def aggregate_snapshots(
    workers: t.Mapping[str, t.Mapping[str, t.Any]],
) -> t.Dict[str, t.Any]:
    """Fold per-worker ``/metrics`` snapshots into one fleet view
    (docs/SERVING.md "Fleet").

    Counters are summed over the CURRENT snapshots and every input is
    kept, per-worker-labelled, under ``workers`` — a worker that
    restarted resets its own counters, so the fleet totals reflect
    exactly what the live processes report and can never double-count
    a dead incarnation. ``requests_per_sec`` is the sum of per-worker
    window rates (rates of disjoint request streams add). Latency
    percentiles come from merging every worker's raw bucket counts
    into one :class:`FixedBucketHistogram` — identical to the
    histogram one process would have built from all the samples
    (pinned by tests/test_fleet.py). Workers whose snapshot failed
    (value ``None``) appear with ``{"unreachable": true}`` and
    contribute nothing to the totals.

    Since PR 19 this is a thin delegate over the plane-generic
    :func:`torch_actor_critic_tpu.obs.merge.aggregate_snapshots` —
    the fold semantics were lifted there so the ObsCollector applies
    them to every plane; this wrapper pins the serving key set."""
    from torch_actor_critic_tpu.obs.merge import (
        aggregate_snapshots as merge_snapshots,
    )

    return merge_snapshots(
        workers,
        sum_keys=_SUM_KEYS,
        rate_keys=("requests_per_sec",),
        merge_dict_keys=("shed_by_reason",),
        hist_key="latency_hist",
        label_keys=_SUM_KEYS + (
            "requests_per_sec", "shed_by_reason", "uptime_s",
            "p50_ms", "p99_ms", "queue_capacity", "draining",
        ),
        sources_key="workers",
        reporting_key="workers_reporting",
    )
