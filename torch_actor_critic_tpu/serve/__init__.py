"""TPU-native batched policy-inference service.

The inference-side counterpart of the training stack: the trainer
produces Orbax checkpoints, this package serves them. The design
follows the Podracer observation (arXiv:2104.06272) that TPU inference
throughput is won by batching many independent requests into ONE jitted
forward pass, and the TorchBeast server-side dynamic-batching pattern
(arXiv:1910.03552):

- :mod:`~torch_actor_critic_tpu.serve.engine` — the jitted forward:
  squashed-Gaussian mean or sampled action over a fixed set of
  power-of-two **bucket** batch shapes, so XLA compiles a handful of
  programs instead of one per request size.
- :mod:`~torch_actor_critic_tpu.serve.batcher` — a thread-safe
  micro-batching queue coalescing concurrent ``act`` calls up to
  ``max_batch`` rows or a ``max_wait_ms`` deadline.
- :mod:`~torch_actor_critic_tpu.serve.registry` — a multi-slot model
  registry with checkpoint **hot-reload**: new epochs in the Orbax dir
  swap in atomically under a generation counter; in-flight batches
  finish on the params they captured, no request is ever dropped.
- :mod:`~torch_actor_critic_tpu.serve.server` — a stdlib
  ``ThreadingHTTPServer`` JSON frontend (``/act``, ``/healthz``,
  ``/metrics``, ``/reload``) plus the in-process
  :class:`~torch_actor_critic_tpu.serve.server.PolicyClient`.
- :mod:`~torch_actor_critic_tpu.serve.metrics` — queue depth, batch
  occupancy, request rate, latency percentiles and shed accounting.
- :mod:`~torch_actor_critic_tpu.serve.fleet` — engine-per-device
  replication (docs/SERVING.md "Fleet"): one engine replica +
  dispatcher per local device behind a shared admission layer, routed
  least-loaded (``load_rows × seconds-per-row EMA``) and health-gated
  on per-replica breakers; hot-reload propagates by generation-keyed
  params placement.
- :mod:`~torch_actor_critic_tpu.serve.sharded` — GSPMD sub-mesh
  serving (docs/SERVING.md "Sharded serving & precision tiers"): one
  policy replica sharded over a ``(tp, fsdp)`` device group via the
  training side's ``param_specs``, so the fleet serves models too big
  for a single chip's HBM; plus the low-precision tiers (``bf16``,
  weight-quantized ``int8``) behind a bitwise-pinned ``f32`` compat
  mode.
- :mod:`~torch_actor_critic_tpu.serve.router` — the multi-process
  fleet router (``serve.py --fleet N``): health-gated membership over
  N workers (eject draining/breaker-open/unreachable, re-admit on
  recovery), connection-failure failover, hop-tagged
  ``X-Request-Id``, rolling hot-reload, and fleet-aggregated
  ``/metrics`` (histogram merge).
- :mod:`~torch_actor_critic_tpu.serve.admission` /
  :mod:`~torch_actor_critic_tpu.serve.breaker` — overload containment
  (docs/SERVING.md "Overload & degradation"): bounded-queue admission
  with deadline-aware shedding (structured
  :class:`~torch_actor_critic_tpu.serve.admission.ShedError` → HTTP
  429/503 + ``Retry-After``) and a per-slot engine circuit breaker
  (consecutive failures / in-graph non-finite detection trip it; a
  half-open probe re-admits traffic after cooldown).

Entry point: ``python serve.py`` at the repo root (see docs/SERVING.md).
"""

from torch_actor_critic_tpu.serve.admission import (  # noqa: F401
    BreakerOpenError,
    NonFiniteActionError,
    ShedError,
)
from torch_actor_critic_tpu.serve.batcher import MicroBatcher  # noqa: F401
from torch_actor_critic_tpu.serve.breaker import CircuitBreaker  # noqa: F401
from torch_actor_critic_tpu.serve.engine import PolicyEngine  # noqa: F401
from torch_actor_critic_tpu.serve.fleet import EngineFleet  # noqa: F401
from torch_actor_critic_tpu.serve.metrics import (  # noqa: F401
    ServeMetrics,
    aggregate_snapshots,
)
from torch_actor_critic_tpu.serve.registry import ModelRegistry  # noqa: F401
from torch_actor_critic_tpu.serve.sharded import (  # noqa: F401
    ShardedPolicyEngine,
)
from torch_actor_critic_tpu.serve.router import FleetRouter  # noqa: F401
from torch_actor_critic_tpu.serve.server import (  # noqa: F401
    PolicyClient,
    PolicyServer,
    install_drain_handler,
)
