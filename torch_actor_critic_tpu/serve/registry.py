"""Multi-slot model registry with validated Orbax checkpoint hot-reload.

A serving process holds one or more named **slots** (e.g. ``default``,
``canary``), each an immutable-at-a-glance triple
``(engine, params, generation)``. Swaps are atomic: the triple is
replaced in one reference assignment under the slot lock, the
generation counter increments, and any batch already dispatched keeps
the triple it captured — in-flight requests finish on the OLD weights
and nothing is ever dropped or recompiled mid-request (the engine and
its bucketed jit cache survive a swap; only params change).

Hot-reload sources a slot from the training run's Orbax checkpoint
directory (:class:`~torch_actor_critic_tpu.utils.checkpoint.Checkpointer`
layout): :meth:`reload` checks ``latest_step`` against the slot's
loaded epoch and swaps when the trainer has written a newer one —
called manually (the HTTP ``/reload`` endpoint) or by the background
poller (:meth:`start_polling`).

Every swap is **sentinel-validated** (docs/RESILIENCE.md): the PR 2
all-finite reduction
(:func:`~torch_actor_critic_tpu.resilience.sentinel.tree_all_finite`)
runs over restored params *before* the atomic swap. A NaN-corrupted
checkpoint — the exact fault the training-side sentinel rolls back
from — is ``rejected`` and the slot keeps serving its **last-good
generation**; reload reports the rejection instead of poisoning every
subsequent response. Reload IO additionally gets the
:mod:`~torch_actor_critic_tpu.resilience.retry` transient-fault policy
(bounded retry with backoff), and each slot reloads independently: one
slot's failure never aborts the others
(per-slot ``{ok|noop|rejected|error}`` statuses).

Each slot also owns a :class:`~torch_actor_critic_tpu.serve.breaker.
CircuitBreaker` the micro-batcher consults per group; breaker
transitions land in a bounded event log (:meth:`breaker_events`) and
per-slot state/trips/probes export via :meth:`breaker_stats` onto
``/metrics``.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
import typing as t

from torch_actor_critic_tpu.resilience.retry import call_with_retries
from torch_actor_critic_tpu.resilience.sentinel import tree_all_finite
from torch_actor_critic_tpu.serve.breaker import CircuitBreaker
from torch_actor_critic_tpu.serve.engine import PolicyEngine

logger = logging.getLogger(__name__)

__all__ = ["ModelRegistry"]


class _Slot:
    __slots__ = (
        "engine", "state", "checkpointer", "lock", "breaker",
        "reload_rejected_total",
    )

    def __init__(self, engine, params, epoch, checkpointer, breaker):
        self.engine = engine
        # (params, generation, epoch): swapped as ONE tuple so readers
        # can never observe a params/generation mismatch.
        self.state = (params, 0, epoch)
        self.checkpointer = checkpointer
        self.breaker = breaker
        self.reload_rejected_total = 0
        self.lock = threading.Lock()


class ModelRegistry:
    def __init__(
        self,
        reload_retries: int = 1,
        reload_retry_backoff_s: float = 0.5,
        sleep: t.Callable[[float], None] = time.sleep,
        restore_shardings: t.Callable[[t.Any], t.Any] | None = None,
        sanitize: bool = False,
    ):
        # Transfer sanitizer tier (--sanitize, docs/ANALYSIS.md):
        # every engine this registry builds runs its forward dispatch
        # under jax.transfer_guard("disallow") with explicit input
        # placement. Off = the engines are built exactly as before.
        self._sanitize = bool(sanitize)
        # Direct-to-sharded checkpoint restore (sub-mesh serving,
        # docs/SERVING.md "Sharded serving & precision tiers"): a
        # callable (abstract actor-params tree -> Sharding tree) handed
        # to Checkpointer.restore_actor_params so Orbax lands every
        # array in its NamedSharding layout — no host-RAM gather of a
        # model that may not fit one host. Applied at registration and
        # on every hot-reload.
        self._restore_shardings = restore_shardings
        self._slots: t.Dict[str, _Slot] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._poller: threading.Thread | None = None  # guarded-by: _lock
        self._poll_stop = threading.Event()
        # Transient-IO policy for hot-reload (resilience/retry.py):
        # each slot's probe+restore gets `reload_retries` extra
        # attempts with exponential backoff before the error lands in
        # its status. `sleep` is injectable so tests drive the ladder
        # without real waiting.
        self._reload_retries = int(reload_retries)
        self._reload_retry_backoff_s = float(reload_retry_backoff_s)
        self._sleep = sleep
        # Bounded breaker-transition log: the telemetry-events view of
        # every slot breaker (each entry is a JSONL-ready dict), capped
        # so a flapping breaker cannot grow host memory.
        self._breaker_events: collections.deque = (  # guarded-by: _lock
            collections.deque(maxlen=256)
        )

    # ------------------------------------------------------- registration

    def register(
        self,
        name: str,
        actor_def,
        obs_spec,
        params=None,
        ckpt_dir: str | None = None,
        max_batch: int = 64,
        buckets: t.Sequence[int] | None = None,
        warmup: bool = True,
        replace: bool = False,
        breaker: CircuitBreaker | None = None,
        bundle=None,
    ) -> dict:
        """Create a slot. ``params`` seeds it directly (tests/bench);
        ``ckpt_dir`` loads the latest epoch from an Orbax dir and arms
        hot-reload for it. Exactly one of the two is required.
        ``warmup`` compiles every bucket before the slot goes live, so
        the first live request never pays a compile. ``breaker``
        overrides the slot's default circuit breaker (tests inject one
        with a fake clock).

        Registering a name that already exists raises unless
        ``replace=True`` — a silent overwrite would discard the old
        slot's engine/checkpointer and restart its generation counter
        at 0, which clients tracking generations would see as the
        counter going backwards. With ``replace=True`` the displaced
        slot's checkpointer is closed and the replacement is logged.

        ``bundle`` (a :class:`~torch_actor_critic_tpu.aot
        .WarmStartBundle`) arms the warmup with pre-compiled
        executables: the slot's programs load from the bundle's
        persistent cache (``bundle`` column of compile_stats) instead
        of compiling live. A mismatched bundle is REJECTED loudly —
        counted on the watchdog (``bundle_rejected``) — and the slot
        falls back to a plain compile-from-scratch warmup; a stale
        bundle can cost the cold start back, never a slot."""
        if (params is None) == (ckpt_dir is None):
            raise ValueError("pass exactly one of params / ckpt_dir")
        with self._lock:
            exists = name in self._slots
        if exists and not replace:
            raise ValueError(
                f"model slot {name!r} already registered; pass "
                "replace=True to displace it (resets its generation "
                "counter to 0)"
            )
        engine = PolicyEngine(
            actor_def, obs_spec, max_batch=max_batch, buckets=buckets,
            sanitize=self._sanitize,
        )
        checkpointer = None
        epoch = None
        if ckpt_dir is not None:
            from torch_actor_critic_tpu.utils.checkpoint import Checkpointer

            checkpointer = Checkpointer(ckpt_dir, save_buffer=False)
            params, meta = checkpointer.restore_actor_params(
                shardings=self._restore_shardings
            )
            epoch = meta["epoch"]
        # A slot must never go live on poisoned weights: the same
        # sentinel that validates every hot-reload validates the
        # initial load (a NaN checkpoint fails registration loudly
        # instead of serving NaN actions until someone notices).
        if not tree_all_finite(params):
            if checkpointer is not None:
                checkpointer.close()
            raise ValueError(
                f"refusing to register slot {name!r}: params contain "
                "non-finite values (divergence sentinel, "
                "docs/RESILIENCE.md)"
            )
        if breaker is None:
            breaker = CircuitBreaker(name=name)
        breaker.name = name
        user_hook = breaker.on_event

        def _hook(event, _user=user_hook, _slot=name):
            self._note_breaker_event(dict(event, slot=_slot))
            if _user is not None:
                _user(event)

        breaker.on_event = _hook
        if warmup:
            if bundle is not None:
                from torch_actor_critic_tpu.aot import BundleMismatchError
                from torch_actor_critic_tpu.diagnostics.watchdog import (
                    get_watchdog,
                )

                try:
                    engine.warmup(params, bundle=bundle)
                except BundleMismatchError as e:
                    get_watchdog().note_bundle_rejected(
                        f"slot {name!r}: {e.reason}"
                    )
                    engine.warmup(params)
            else:
                engine.warmup(params)
        slot = _Slot(engine, params, epoch, checkpointer, breaker)
        with self._lock:
            displaced = self._slots.get(name)
            self._slots[name] = slot
        if displaced is not None:
            logger.warning(
                "slot %r replaced; generation counter restarts at 0",
                name,
            )
            if displaced.checkpointer is not None:
                displaced.checkpointer.close()
        logger.info(
            "registered slot %r (epoch=%s, buckets=%s, warmup=%s)",
            name, epoch, engine.buckets, warmup,
        )
        return {"slot": name, "epoch": epoch, "generation": 0}

    # ------------------------------------------------------------ reading

    def _slot(self, name: str) -> _Slot:
        # Under the registry lock: a lookup racing register(...,
        # replace=True) must see either the old slot or the new one,
        # never a half-updated dict view. Callers never hold _lock
        # here (found by tac-lint, unlocked-guarded-access).
        with self._lock:
            try:
                return self._slots[name]
            except KeyError:
                raise KeyError(
                    f"unknown model slot {name!r}; have "
                    f"{sorted(self._slots)}"
                ) from None

    def acquire(self, name: str = "default"):
        """``(engine, params, generation)`` — the triple a batch runs
        with. The caller keeps these references for the whole forward;
        a concurrent swap cannot mutate them."""
        slot = self._slot(name)
        with slot.lock:
            params, generation, _ = slot.state
        return slot.engine, params, generation

    def epoch_of(self, name: str = "default") -> int | None:
        """The training epoch the slot's current params were published
        at (``None`` for directly-seeded slots that never saw a
        checkpoint or publish). The batcher stamps every response with
        this (:class:`~torch_actor_critic_tpu.serve.batcher.ActResult`
        ``.epoch``) so decoupled actors can tag transitions with a
        staleness key that survives serving-process restarts — the
        generation counter is per-process, the epoch is durable."""
        slot = self._slot(name)
        with slot.lock:
            return slot.state[2]

    def breaker(self, name: str = "default") -> CircuitBreaker | None:
        """The slot's circuit breaker (None only for foreign slots —
        every registered slot has one)."""
        with self._lock:
            slot = self._slots.get(name)
        return slot.breaker if slot is not None else None

    def slots(self) -> t.Dict[str, dict]:
        """Health/introspection view of every slot."""
        out = {}
        with self._lock:
            items = list(self._slots.items())
        for name, slot in items:
            with slot.lock:
                _, generation, epoch = slot.state
                rejected = slot.reload_rejected_total
            out[name] = {
                "generation": generation,
                "epoch": epoch,
                "hot_reload": slot.checkpointer is not None,
                "buckets": list(slot.engine.buckets),
                "compiled": sorted(
                    [list(k) for k in slot.engine.compiled_buckets()]
                ),
                "breaker": slot.breaker.state,
                "reload_rejected_total": rejected,
                "bundle_loaded": slot.engine.bundle_loaded,
            }
        return out

    def compile_stats(self) -> dict:
        """Service-wide jit-compile accounting: total + per-slot,
        per-bucket warmup/live breakdown (``/metrics`` feeds the
        recompilation watchdog's view with this; a nonzero
        ``live_compiles`` is the silently-recompiling-bucket signal —
        docs/OBSERVABILITY.md)."""
        with self._lock:
            items = list(self._slots.items())
        slots = {name: slot.engine.compile_stats() for name, slot in items}
        return {
            "compiles_total": sum(s["compiles_total"] for s in slots.values()),
            "live_compiles": sum(s["live_compiles"] for s in slots.values()),
            "bundle_compiles": sum(
                s.get("bundle_compiles", 0) for s in slots.values()
            ),
            "slots": slots,
        }

    # ----------------------------------------------------- circuit breaker

    def _note_breaker_event(self, event: dict):
        event = dict(event, ts=time.time())
        with self._lock:
            self._breaker_events.append(event)
        logger.warning("breaker event: %s", event)

    def note_breaker_event(self, event: dict):
        """Record one breaker transition into the bounded event log —
        the hook per-device replica breakers
        (:mod:`~torch_actor_critic_tpu.serve.fleet`) report through,
        so fleet and slot breaker events share one telemetry stream
        (entries carry ``replica`` when a replica emitted them)."""
        self._note_breaker_event(event)

    def breaker_events(self) -> t.List[dict]:
        """The most recent breaker transitions (bounded), each a
        JSONL-ready telemetry event dict."""
        with self._lock:
            return list(self._breaker_events)

    def breaker_stats(self) -> dict:
        """Per-slot breaker state for ``/metrics``: state machine
        position, trip/probe totals, thresholds."""
        with self._lock:
            items = list(self._slots.items())
        slots = {name: slot.breaker.snapshot() for name, slot in items}
        with self._lock:
            events_total = len(self._breaker_events)
        return {
            "trips_total": sum(s["trips_total"] for s in slots.values()),
            "open_slots": sorted(
                name for name, s in slots.items() if s["state"] != "closed"
            ),
            "events_total": events_total,
            "slots": slots,
        }

    # --------------------------------------------------------- hot reload

    def swap(
        self,
        name: str,
        params,
        epoch: int | None = None,
        validate: bool = True,
    ) -> int:
        """Atomically install new params; returns the new generation.

        ``validate`` runs the all-finite sentinel first and raises
        ``ValueError`` (no swap, last-good params keep serving) on
        non-finite params. Only the fault-injection harness passes
        ``validate=False`` — to plant the poisoned weights the breaker
        and reload tests need."""
        slot = self._slot(name)
        if validate and not tree_all_finite(params):
            raise ValueError(
                f"refusing to swap slot {name!r}: params contain "
                "non-finite values; the current generation keeps "
                "serving (divergence sentinel, docs/RESILIENCE.md)"
            )
        with slot.lock:
            _, generation, old_epoch = slot.state
            slot.state = (
                params, generation + 1,
                epoch if epoch is not None else old_epoch,
            )
            return generation + 1

    def _reload_slot(self, name: str, slot: _Slot) -> dict:
        """One slot's reload attempt -> its status dict. Never raises:
        ``{ok|noop|rejected|error}`` so multi-slot reloads always
        complete for every slot."""
        if slot.checkpointer is None:
            return {
                "status": "noop", "reloaded": False,
                "reason": "no checkpoint dir",
            }
        with slot.lock:
            _, generation, loaded_epoch = slot.state

        def probe_and_restore():
            # The Orbax manager caches its step list; refresh to see
            # epochs the TRAINER process wrote since our last look.
            slot.checkpointer.refresh()
            latest = slot.checkpointer.latest_epoch()
            if latest is None or (
                loaded_epoch is not None and latest <= loaded_epoch
            ):
                return None
            # Restore OUTSIDE the slot lock: a multi-second Orbax
            # read must not stall acquire() (live traffic keeps
            # flowing on the old params until the swap below).
            return latest, slot.checkpointer.restore_actor_params(
                latest, shardings=self._restore_shardings
            )

        try:
            out = call_with_retries(
                probe_and_restore,
                attempts=self._reload_retries + 1,
                base_delay_s=self._reload_retry_backoff_s,
                sleep=self._sleep,
                what=f"slot {name!r} hot-reload",
            )
            if out is None:
                return {
                    "status": "noop", "reloaded": False,
                    "epoch": loaded_epoch, "generation": generation,
                }
            latest, (params, meta) = out
            # Sentinel gate BEFORE the swap (deterministic — never
            # retried): a NaN-corrupted checkpoint keeps the previous
            # generation serving and the rejection is reported, not
            # raised mid-serve.
            if not tree_all_finite(params):
                with slot.lock:
                    slot.reload_rejected_total += 1
                logger.warning(
                    "slot %r reload REJECTED: epoch %s params are "
                    "non-finite; generation %s (last good) keeps "
                    "serving",
                    name, latest, generation,
                )
                return {
                    "status": "rejected", "reloaded": False,
                    "epoch": latest, "generation": generation,
                    "reason": "non-finite parameters (all-finite "
                              "sentinel); last-good generation kept",
                }
            generation = self.swap(name, params, epoch=latest, validate=False)
            logger.info(
                "slot %r hot-reloaded epoch %s (generation %s)",
                name, latest, generation,
            )
            return {
                "status": "ok", "reloaded": True,
                "epoch": latest, "generation": generation,
            }
        except Exception as e:  # noqa: BLE001 — a half-written or
            # corrupt checkpoint must not take serving down; the
            # slot keeps its current params and reports the error.
            logger.warning("slot %r reload failed: %r", name, e)
            return {
                "status": "error", "reloaded": False,
                "error": repr(e)[:200],
            }

    def reload(self, name: str | None = None) -> t.Dict[str, dict]:
        """Check checkpoint-backed slots for a newer epoch; swap those
        that have one (sentinel-validated). Returns per-slot
        ``{ok|noop|rejected|error}`` statuses — one slot's failure
        never aborts reloading the remaining slots."""
        with self._lock:
            names = [name] if name is not None else list(self._slots)
        out = {}
        for n in names:
            try:
                out[n] = self._reload_slot(n, self._slot(n))
            except Exception as e:  # noqa: BLE001 — isolation: even a
                # failure OUTSIDE the per-slot path (unknown name,
                # a concurrently-removed slot) costs one status entry
                out[n] = {
                    "status": "error", "reloaded": False,
                    "error": repr(e)[:200],
                }
        return out

    def start_polling(self, interval_s: float = 5.0):
        """Background hot-reload: poll checkpoint dirs every
        ``interval_s`` seconds. The watcher never dies to one bad
        poll — reload already isolates per-slot failures, and any
        error that still escapes is logged and the next tick polls
        again."""
        def loop():
            while not self._poll_stop.wait(timeout=interval_s):
                try:
                    self.reload()
                except Exception:  # noqa: BLE001 — pragma: no cover —
                    # reload() isolates per-slot errors; this is the
                    # watcher's own last line of defense
                    logger.exception("hot-reload poll failed; will retry")

        with self._lock:
            if self._poller is not None:
                raise RuntimeError("poller already running")
            self._poll_stop.clear()
            self._poller = threading.Thread(
                target=loop, name="ckpt-poller", daemon=True
            )
            poller = self._poller
        poller.start()

    def stop_polling(self):
        # Swap the handle out under the lock, join OUTSIDE it: the
        # poller's reload() briefly takes _lock, so joining while
        # holding it would stall the stop by up to one full poll.
        with self._lock:
            poller = self._poller
            self._poller = None
        if poller is None:
            return
        self._poll_stop.set()
        poller.join(timeout=10.0)

    def close(self):
        self.stop_polling()
        with self._lock:
            slots = list(self._slots.values())
        for slot in slots:
            if slot.checkpointer is not None:
                slot.checkpointer.close()
