"""Multi-slot model registry with Orbax checkpoint hot-reload.

A serving process holds one or more named **slots** (e.g. ``default``,
``canary``), each an immutable-at-a-glance triple
``(engine, params, generation)``. Swaps are atomic: the triple is
replaced in one reference assignment under the slot lock, the
generation counter increments, and any batch already dispatched keeps
the triple it captured — in-flight requests finish on the OLD weights
and nothing is ever dropped or recompiled mid-request (the engine and
its bucketed jit cache survive a swap; only params change).

Hot-reload sources a slot from the training run's Orbax checkpoint
directory (:class:`~torch_actor_critic_tpu.utils.checkpoint.Checkpointer`
layout): :meth:`reload` checks ``latest_step`` against the slot's
loaded epoch and swaps when the trainer has written a newer one —
called manually (the HTTP ``/reload`` endpoint) or by the background
poller (:meth:`start_polling`).
"""

from __future__ import annotations

import logging
import threading
import time
import typing as t

from torch_actor_critic_tpu.serve.engine import PolicyEngine

logger = logging.getLogger(__name__)

__all__ = ["ModelRegistry"]


class _Slot:
    __slots__ = ("engine", "state", "checkpointer", "lock")

    def __init__(self, engine, params, epoch, checkpointer):
        self.engine = engine
        # (params, generation, epoch): swapped as ONE tuple so readers
        # can never observe a params/generation mismatch.
        self.state = (params, 0, epoch)
        self.checkpointer = checkpointer
        self.lock = threading.Lock()


class ModelRegistry:
    def __init__(self):
        self._slots: t.Dict[str, _Slot] = {}
        self._lock = threading.Lock()
        self._poller: threading.Thread | None = None
        self._poll_stop = threading.Event()

    # ------------------------------------------------------- registration

    def register(
        self,
        name: str,
        actor_def,
        obs_spec,
        params=None,
        ckpt_dir: str | None = None,
        max_batch: int = 64,
        buckets: t.Sequence[int] | None = None,
        warmup: bool = True,
        replace: bool = False,
    ) -> dict:
        """Create a slot. ``params`` seeds it directly (tests/bench);
        ``ckpt_dir`` loads the latest epoch from an Orbax dir and arms
        hot-reload for it. Exactly one of the two is required.
        ``warmup`` compiles every bucket before the slot goes live, so
        the first live request never pays a compile.

        Registering a name that already exists raises unless
        ``replace=True`` — a silent overwrite would discard the old
        slot's engine/checkpointer and restart its generation counter
        at 0, which clients tracking generations would see as the
        counter going backwards. With ``replace=True`` the displaced
        slot's checkpointer is closed and the replacement is logged."""
        if (params is None) == (ckpt_dir is None):
            raise ValueError("pass exactly one of params / ckpt_dir")
        with self._lock:
            exists = name in self._slots
        if exists and not replace:
            raise ValueError(
                f"model slot {name!r} already registered; pass "
                "replace=True to displace it (resets its generation "
                "counter to 0)"
            )
        engine = PolicyEngine(
            actor_def, obs_spec, max_batch=max_batch, buckets=buckets
        )
        checkpointer = None
        epoch = None
        if ckpt_dir is not None:
            from torch_actor_critic_tpu.utils.checkpoint import Checkpointer

            checkpointer = Checkpointer(ckpt_dir, save_buffer=False)
            params, meta = checkpointer.restore_actor_params()
            epoch = meta["epoch"]
        if warmup:
            engine.warmup(params)
        slot = _Slot(engine, params, epoch, checkpointer)
        with self._lock:
            displaced = self._slots.get(name)
            self._slots[name] = slot
        if displaced is not None:
            logger.warning(
                "slot %r replaced; generation counter restarts at 0",
                name,
            )
            if displaced.checkpointer is not None:
                displaced.checkpointer.close()
        logger.info(
            "registered slot %r (epoch=%s, buckets=%s, warmup=%s)",
            name, epoch, engine.buckets, warmup,
        )
        return {"slot": name, "epoch": epoch, "generation": 0}

    # ------------------------------------------------------------ reading

    def _slot(self, name: str) -> _Slot:
        try:
            return self._slots[name]
        except KeyError:
            raise KeyError(
                f"unknown model slot {name!r}; have {sorted(self._slots)}"
            ) from None

    def acquire(self, name: str = "default"):
        """``(engine, params, generation)`` — the triple a batch runs
        with. The caller keeps these references for the whole forward;
        a concurrent swap cannot mutate them."""
        slot = self._slot(name)
        with slot.lock:
            params, generation, _ = slot.state
        return slot.engine, params, generation

    def slots(self) -> t.Dict[str, dict]:
        """Health/introspection view of every slot."""
        out = {}
        with self._lock:
            items = list(self._slots.items())
        for name, slot in items:
            with slot.lock:
                _, generation, epoch = slot.state
            out[name] = {
                "generation": generation,
                "epoch": epoch,
                "hot_reload": slot.checkpointer is not None,
                "buckets": list(slot.engine.buckets),
                "compiled": sorted(
                    [list(k) for k in slot.engine.compiled_buckets()]
                ),
            }
        return out

    def compile_stats(self) -> dict:
        """Service-wide jit-compile accounting: total + per-slot,
        per-bucket warmup/live breakdown (``/metrics`` feeds the
        recompilation watchdog's view with this; a nonzero
        ``live_compiles`` is the silently-recompiling-bucket signal —
        docs/OBSERVABILITY.md)."""
        with self._lock:
            items = list(self._slots.items())
        slots = {name: slot.engine.compile_stats() for name, slot in items}
        return {
            "compiles_total": sum(s["compiles_total"] for s in slots.values()),
            "live_compiles": sum(s["live_compiles"] for s in slots.values()),
            "slots": slots,
        }

    # --------------------------------------------------------- hot reload

    def swap(self, name: str, params, epoch: int | None = None) -> int:
        """Atomically install new params; returns the new generation."""
        slot = self._slot(name)
        with slot.lock:
            _, generation, old_epoch = slot.state
            slot.state = (
                params, generation + 1,
                epoch if epoch is not None else old_epoch,
            )
            return generation + 1

    def reload(self, name: str | None = None) -> t.Dict[str, dict]:
        """Check checkpoint-backed slots for a newer epoch; swap those
        that have one. Returns per-slot status."""
        with self._lock:
            names = [name] if name is not None else list(self._slots)
        out = {}
        for n in names:
            slot = self._slot(n)
            if slot.checkpointer is None:
                out[n] = {"reloaded": False, "reason": "no checkpoint dir"}
                continue
            with slot.lock:
                _, generation, loaded_epoch = slot.state
            try:
                # The Orbax manager caches its step list; refresh to see
                # epochs the TRAINER process wrote since our last look.
                slot.checkpointer.refresh()
                latest = slot.checkpointer.latest_epoch()
                if latest is None or (
                    loaded_epoch is not None and latest <= loaded_epoch
                ):
                    out[n] = {
                        "reloaded": False, "epoch": loaded_epoch,
                        "generation": generation,
                    }
                    continue
                # Restore OUTSIDE the slot lock: a multi-second Orbax
                # read must not stall acquire() (live traffic keeps
                # flowing on the old params until the swap below).
                params, meta = slot.checkpointer.restore_actor_params(latest)
                generation = self.swap(n, params, epoch=latest)
                out[n] = {
                    "reloaded": True, "epoch": latest,
                    "generation": generation,
                }
                logger.info(
                    "slot %r hot-reloaded epoch %s (generation %s)",
                    n, latest, generation,
                )
            except Exception as e:  # noqa: BLE001 — a half-written or
                # corrupt checkpoint must not take serving down; the
                # slot keeps its current params and reports the error.
                logger.warning("slot %r reload failed: %r", n, e)
                out[n] = {"reloaded": False, "error": repr(e)[:200]}
        return out

    def start_polling(self, interval_s: float = 5.0):
        """Background hot-reload: poll checkpoint dirs every
        ``interval_s`` seconds."""
        if self._poller is not None:
            raise RuntimeError("poller already running")
        self._poll_stop.clear()

        def loop():
            while not self._poll_stop.wait(timeout=interval_s):
                self.reload()

        self._poller = threading.Thread(
            target=loop, name="ckpt-poller", daemon=True
        )
        self._poller.start()

    def stop_polling(self):
        if self._poller is None:
            return
        self._poll_stop.set()
        self._poller.join(timeout=10.0)
        self._poller = None

    def close(self):
        self.stop_polling()
        with self._lock:
            slots = list(self._slots.values())
        for slot in slots:
            if slot.checkpointer is not None:
                slot.checkpointer.close()
