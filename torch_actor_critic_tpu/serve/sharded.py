"""GSPMD sub-mesh serving: one policy replica sharded over N devices.

The serving plane scaled OUT (engine-per-device fleet, PR 9 worker
processes) but never UP: every replica held the full params pytree on
one chip, capping the servable model at a single chip's HBM. This
module is the Sebulba move (Podracer, arXiv:2104.06272) applied to
inference — carve the device topology into disjoint ``(tp, fsdp)``
**sub-meshes**, each hosting ONE sharded model copy, and let the fleet
dispatch across sub-meshes exactly as it dispatched across single
devices.

:class:`ShardedPolicyEngine` keeps the entire
:class:`~torch_actor_critic_tpu.serve.engine.PolicyEngine` contract —
bucketed jit cache, warmup, in-graph all-finite flag, compile
accounting — and changes only the program and the params layout:

- **At rest, params are sharded** over the sub-mesh by the SAME
  ``param_specs`` (tp role + size-thresholded fsdp) the training side
  uses (:mod:`torch_actor_critic_tpu.parallel.sharding`): each device
  holds ``1/(tp*fsdp)`` of every qualifying array. That is the HBM
  budget win — the model only ever needs to FIT sharded.
- **The f32 tier is bitwise-pinned** to the single-device engine: the
  jitted forward first constrains every param leaf back to replicated
  (GSPMD materializes the all-gathers over sub-mesh ICI), then runs
  the identical apply — all compute operands replicated means the
  identical scalar program, so responses agree bit-for-bit with
  ``PolicyEngine`` (pinned by tests/test_serve_sharded.py). Exactness
  is the compat contract; the gathers are the price.
- **The low-precision tiers keep the sharded layout through the
  compute**: ``bf16`` rebuilds the actor at the MXU's native matmul
  width (the PR-12 ``compute_dtype`` policy — params stay f32 at
  rest, casts happen in-graph); ``int8`` serves weight-quantized
  params (per-channel symmetric scales computed ONCE at
  register/reload time, dequant-in-graph) so the weight stream costs
  a quarter of the HBM bandwidth. Both let the GSPMD partitioner run
  genuinely tensor-parallel matmuls — reduction order differs from
  the single-device engine in the last bits, which these tiers
  already concede by construction.

Hot-reload stays one-transfer-per-device: the fleet's sub-mesh replica
view performs a generation-keyed **sharded** ``device_put`` (each
device receives exactly its shards), cached on ``(generation,
precision)`` so a tier change invalidates stale-dtype placements, and
every placement's actual bytes land on the transfer counter
(``/metrics`` ``sharding``). Provable on CPU with the forced
multi-device shim (tests/conftest.py): XLA partitions for virtual
host devices exactly as for chips (docs/SERVING.md "Sharded serving &
precision tiers").
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torch_actor_critic_tpu.parallel.sharding import (
    FSDP_MIN_BYTES,
    make_submesh,
    param_specs,
    partition_submeshes,
)
from torch_actor_critic_tpu.serve.engine import PolicyEngine

__all__ = [
    "Int8Param",
    "PRECISIONS",
    "ShardedPolicyEngine",
    "dequantize_params",
    "make_submesh",
    "partition_submeshes",
    "quantize_params",
]

PRECISIONS = ("f32", "bf16", "int8")


class Int8Param(t.NamedTuple):
    """One weight-quantized parameter: ``q`` is the int8 tensor (the
    original kernel's shape), ``scale`` the per-output-channel f32
    symmetric scales (last-dim length). Dequantized in-graph as
    ``q.astype(f32) * scale``; a NamedTuple so it IS a pytree — jit,
    ``device_put`` with per-leaf shardings and checkpoint-free reload
    all traverse it like any other params subtree."""

    q: t.Any
    scale: t.Any


def _quantizable(leaf) -> bool:
    """Weight-only int8 quantizes 2-D+ float arrays (the matmul
    kernels, where the bandwidth lives); biases, scalars and integer
    leaves stay f32 — they are noise in the weight stream and
    precision-critical in the epilogue."""
    dt = getattr(leaf, "dtype", None)
    return (
        hasattr(leaf, "ndim") and leaf.ndim >= 2
        and dt is not None and jnp.issubdtype(dt, jnp.floating)
    )


def quantize_params(params: t.Any) -> t.Any:
    """Per-channel symmetric int8 weight quantization, host-side.

    Runs at register/reload time — NEVER per request. For each
    quantizable leaf ``W`` the per-output-channel scale is
    ``max|W[..., c]| / 127`` (zero-max channels get a tiny floor so
    the scale never divides by zero), and ``q = round(W / scale)``
    clipped to int8. The round-trip error is bounded elementwise by
    ``scale / 2`` (pinned by tests/test_serve_sharded.py)."""

    def one(leaf):
        if not _quantizable(leaf):
            return leaf
        w = np.asarray(leaf, dtype=np.float32)
        amax = np.abs(w).max(axis=tuple(range(w.ndim - 1)))
        scale = np.maximum(amax, 1e-12) / 127.0
        q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
        return Int8Param(q=q, scale=scale.astype(np.float32))

    return jax.tree_util.tree_map(one, params)


def dequantize_params(params: t.Any, dtype=jnp.float32) -> t.Any:
    """In-graph inverse of :func:`quantize_params`: ``q * scale`` back
    to ``dtype``, leaving unquantized leaves alone. Traceable — this
    is the first op of the int8 tier's jitted forward, so the weights
    cross HBM as int8 and widen on-chip."""

    def one(leaf):
        if isinstance(leaf, Int8Param):
            return leaf.q.astype(dtype) * leaf.scale.astype(dtype)
        return leaf

    return jax.tree_util.tree_map(
        one, params, is_leaf=lambda x: isinstance(x, Int8Param)
    )


class ShardedPolicyEngine(PolicyEngine):
    """:class:`PolicyEngine` whose forward runs jit-with-sharding over
    a ``(tp, fsdp)`` sub-mesh, with a precision tier.

    ``mesh`` must be a 2-axis ``(tp, fsdp)`` Mesh
    (:func:`~torch_actor_critic_tpu.parallel.sharding.make_submesh`).
    ``precision`` picks the tier (module docstring); ``fsdp_min_bytes``
    is the at-rest sharding threshold (tests pass 0 so tiny models
    actually shard). Params handed to :meth:`act` must have gone
    through :meth:`place_params` (the fleet's replica view does this,
    generation-keyed); the engine itself is stateless about them.
    """

    TRACE_PREFIX = "serve/sharded_forward"

    def __init__(
        self,
        actor_def,
        obs_spec: t.Any,
        mesh: Mesh,
        precision: str = "f32",
        max_batch: int = 64,
        buckets: t.Sequence[int] | None = None,
        fsdp_min_bytes: int = FSDP_MIN_BYTES,
        sanitize: bool = False,
    ):
        if tuple(mesh.axis_names) != ("tp", "fsdp"):
            raise ValueError(
                f"ShardedPolicyEngine needs a (tp, fsdp) sub-mesh "
                f"(parallel.sharding.make_submesh), got axes "
                f"{mesh.axis_names}"
            )
        if precision not in PRECISIONS:
            raise ValueError(
                f"precision must be one of {PRECISIONS}, got {precision!r}"
            )
        self.mesh = mesh
        self._precision = precision
        self.fsdp_min_bytes = int(fsdp_min_bytes)
        self._replicated = NamedSharding(mesh, P())
        super().__init__(
            actor_def, obs_spec, max_batch=max_batch, buckets=buckets,
            sanitize=sanitize,
        )

    @property
    def precision(self) -> str:
        return self._precision

    def _cost_devices(self) -> int:
        # Per-chip cost registration: the lowered analysis covers the
        # whole logical program; dividing by the sub-mesh size keeps
        # roofline/MFU comparable with every other entry point
        # (docs/OBSERVABILITY.md "Cost attribution").
        return int(self.mesh.size)

    def _build_forwards(self) -> None:
        replicated = self._replicated
        precision = self._precision
        # bf16 tier = the PR-12 compute_dtype policy applied to
        # serving: rebuild the actor at bf16 matmul width (params stay
        # f32 at rest; the module casts in-graph and its heads return
        # f32 — distribution math is precision-sensitive).
        if precision == "bf16":
            if not hasattr(self.actor_def, "dtype"):
                raise ValueError(
                    f"{type(self.actor_def).__name__} has no compute-"
                    "dtype knob; the bf16 serving tier needs a model "
                    "built with the PR-12 compute_dtype policy"
                )
            apply_def = self.actor_def.clone(dtype=jnp.bfloat16)
        else:
            apply_def = self.actor_def

        def materialize(params):
            """The tier's in-graph params story. int8: dequantize (the
            weights crossed HBM as int8). f32: constrain every leaf
            back to replicated BEFORE any compute — all-gather over
            sub-mesh ICI — which pins the tier bitwise to the
            single-device engine (identical scalar program on every
            device). bf16/int8 keep the at-rest sharded layout and let
            the partitioner run real tensor-parallel matmuls."""
            if precision == "int8":
                return dequantize_params(params)
            if precision == "f32":
                return jax.tree_util.tree_map(
                    lambda x: x
                    if jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)
                    else jax.lax.with_sharding_constraint(x, replicated),
                    params,
                )
            return params

        donate = jax.default_backend() not in ("cpu",)

        def fwd_sampled(params, obs, key):
            action, _ = apply_def.apply(
                materialize(params), obs, key,
                deterministic=False, with_logprob=False,
            )
            action = jax.lax.with_sharding_constraint(action, replicated)
            return action, jnp.all(jnp.isfinite(action))

        def fwd_deterministic(params, obs):
            action, _ = apply_def.apply(
                materialize(params), obs, None,
                deterministic=True, with_logprob=False,
            )
            action = jax.lax.with_sharding_constraint(action, replicated)
            return action, jnp.all(jnp.isfinite(action))

        self._fwd = {
            True: jax.jit(
                fwd_deterministic, donate_argnums=(1,) if donate else ()
            ),
            False: jax.jit(
                fwd_sampled, donate_argnums=(1,) if donate else ()
            ),
        }

    # ------------------------------------------------------ params layout

    def param_shardings(self, params: t.Any) -> t.Any:
        """The at-rest :class:`NamedSharding` tree for ``params``
        (PRE-quantization shapes): training's ``param_specs`` over this
        sub-mesh. Structurally matches :meth:`prepare_params` output —
        a quantized kernel's ``q`` inherits the kernel's spec (same
        shape, 4x fewer bytes), its ``scale`` replicates."""
        specs = param_specs(params, self.mesh, self.fsdp_min_bytes)
        if self._precision == "int8":
            specs = jax.tree_util.tree_map(
                lambda leaf, s: Int8Param(q=s, scale=P())
                if _quantizable(leaf) else s,
                params, specs,
            )
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )

    def prepare_params(self, params: t.Any) -> t.Any:
        """Tier-specific host-side transform, run once per
        register/reload: int8 quantizes; f32/bf16 pass through (bf16
        keeps f32 master weights at rest — the in-graph cast is free
        on the MXU and a bf16 at-rest copy would double placements
        on a tier flip)."""
        if self._precision == "int8":
            return quantize_params(params)
        return params

    def place_params(self, params: t.Any) -> t.Tuple[t.Any, int]:
        """Prepare + shard-place ``params`` on the sub-mesh; returns
        ``(placed, transferred_bytes)``. One ``device_put`` per leaf
        moves exactly each device's shards — a sharded leaf costs its
        logical bytes total across the sub-mesh, a replicated leaf
        costs ``bytes * mesh.size``; the sum is the per-replica
        hot-reload transfer the ``/metrics`` ``sharding`` section
        reports."""
        shardings = self.param_shardings(params)
        prepared = self.prepare_params(params)
        placed = jax.tree_util.tree_map(
            jax.device_put, prepared, shardings
        )
        transferred = int(sum(
            sum(s.data.nbytes for s in leaf.addressable_shards)
            for leaf in jax.tree_util.tree_leaves(placed)
        ))
        return placed, transferred

    # ------------------------------------------------------- input staging

    def _device_obs(self, padded):
        # Committed-replicated placement: the jit sees every input with
        # an explicit sub-mesh sharding (params committed sharded, obs/
        # key committed replicated), so partitioning never guesses.
        return jax.device_put(padded, self._replicated)

    def _device_key(self, key):
        return jax.device_put(key, self._replicated)

    def replicate(self) -> "ShardedPolicyEngine":
        """A fresh engine with this configuration (same sub-mesh, same
        tier) and an empty jit cache — mirrors the base contract; the
        fleet builds per-sub-mesh engines itself, each on its OWN
        mesh."""
        return ShardedPolicyEngine(
            self.actor_def, self.obs_spec, self.mesh,
            precision=self._precision, max_batch=self.max_batch,
            buckets=self.buckets, fsdp_min_bytes=self.fsdp_min_bytes,
            sanitize=self.sanitize,
        )
