"""Admission-control error taxonomy for the serving plane.

Under overload a service has exactly three honest answers: do the work,
reject it *now* with a signal the client can act on, or (worst) accept
it and fail it later after burning resources nobody benefits from. The
seed batcher only knew the first and third — an unbounded queue grew
host memory without bound under any sustained burst past service rate,
and a request whose client had already timed out still occupied the
queue and a TPU forward. These exceptions are the vocabulary of the
second answer; every one carries a machine-readable ``reason`` and a
``retry_after_s`` hint so the HTTP frontend can map it onto the
standard overload contract (429/503 + ``Retry-After``,
docs/SERVING.md "Overload & degradation"):

- ``queue_full`` / ``deadline_infeasible`` — rejected at submit time
  (the server's fault domain is healthy, the *rate* is not): HTTP 429.
- ``expired`` — accepted but purged at group-collection time because
  the request's own deadline passed while it was queued; the TPU never
  ran it. Surfaces as 503 (the client already waited its budget).
- ``draining`` — the process is shutting down and not admitting new
  work: HTTP 503 (a load balancer should route elsewhere).
- ``breaker_open`` (:class:`BreakerOpenError`) — the slot's engine is
  tripped (:mod:`~torch_actor_critic_tpu.serve.breaker`): HTTP 503.
  From an :class:`~torch_actor_critic_tpu.serve.fleet.EngineFleet`
  this means EVERY replica's breaker refused — one tripped replica is
  silently routed around.

The fleet router (:mod:`~torch_actor_critic_tpu.serve.router`) adds
two reasons of its own on the wire, both 503 + ``Retry-After``:
``no_workers`` (every worker ejected from membership) and
``worker_unreachable`` (the last proxy attempt died at the connection
level after failover exhausted the admitted set).

:class:`NonFiniteActionError` is the engine-side fault the breaker
counts: the jitted forward's own fused all-finite reduction (the PR 2
sentinel predicate, in-graph) found NaN/inf in the action output —
poisoned params or a numerics bug, never a client error.
"""

from __future__ import annotations

import typing as t

__all__ = [
    "ShedError",
    "BreakerOpenError",
    "NonFiniteActionError",
    "SUBMIT_SHED_REASONS",
]

# Reasons rejected before the request entered the queue — the 429
# family (client should back off and retry); everything else is 503.
SUBMIT_SHED_REASONS = ("queue_full", "deadline_infeasible")


class ShedError(RuntimeError):
    """A request rejected (or purged) by admission control.

    ``reason`` is one of ``queue_full``, ``deadline_infeasible``,
    ``expired``, ``draining``, ``breaker_open``; ``retry_after_s`` is
    the server's best estimate of when retrying could succeed (the
    ``Retry-After`` header, floored at 1 s on the wire).
    """

    def __init__(
        self,
        reason: str,
        message: str,
        retry_after_s: float = 1.0,
        detail: t.Mapping[str, t.Any] | None = None,
    ):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = float(retry_after_s)
        self.detail = dict(detail or {})

    def to_payload(self) -> dict:
        """The structured JSON body the HTTP frontend answers with."""
        return dict(
            self.detail,
            error=str(self),
            reason=self.reason,
            retry_after_s=round(self.retry_after_s, 3),
        )


class BreakerOpenError(ShedError):
    """The slot's circuit breaker is open (or its half-open probe quota
    is spent): fail fast with 503 instead of queueing work the engine
    would only fail slowly."""

    def __init__(self, slot: str, retry_after_s: float, state: str):
        super().__init__(
            "breaker_open",
            f"model slot {slot!r} circuit breaker is {state}; "
            "the engine is failing and traffic is shed until a probe "
            "succeeds",
            retry_after_s=retry_after_s,
            detail={"slot": slot, "breaker_state": state},
        )
        self.slot = slot
        self.state = state


class NonFiniteActionError(RuntimeError):
    """The engine forward produced NaN/inf action rows (detected by the
    in-graph fused all-finite reduction). Counted as an engine failure
    by the circuit breaker — a response containing NaN must never reach
    a client."""

    def __init__(self, bucket: int, deterministic: bool):
        super().__init__(
            f"policy forward returned non-finite actions "
            f"(bucket={bucket}, deterministic={deterministic}) — "
            "poisoned params or a numerics fault; the response was "
            "withheld and the failure reported to the circuit breaker"
        )
        self.bucket = bucket
        self.deterministic = deterministic
