"""Multi-process fleet router: health-gated membership over N workers.

One worker process drives one accelerator's engines well; "millions of
users" needs N of them behind something that knows which ones are
alive. :class:`FleetRouter` is that something — a thin stdlib-HTTP
layer (no web framework; same constraint as
:class:`~torch_actor_critic_tpu.serve.server.PolicyServer`) in front
of N ``serve.py`` workers:

- **Membership** is health-gated: a poll thread GETs each worker's
  ``/healthz`` every ``poll_interval_s`` and runs the state machine

  ::

      admitted ──(healthz 503 "draining")──────► ejected(draining)
      admitted ──(every slot breaker open)─────► ejected(breaker_open)
      admitted ──(eject_after conn failures)───► ejected(unreachable)
      ejected  ──(healthz 200, breaker closed)─► admitted

  Ejection only stops NEW routing — requests already proxied to a
  draining worker finish there (the worker's own drain answers them).
- **Routing**: least last-known queue depth among admitted workers,
  round-robin on ties. A proxy attempt that fails at the connection
  level ejects the worker immediately and **fails over** to the next
  admitted worker — a request the router accepted is retried until a
  worker answers or every worker has been tried, which is what makes
  a mid-flood worker kill invisible to clients (``make fleet-smoke``).
  429s relay as-is (per-worker admission said *rate*, not *health* —
  the client's Retry-After dance handles it); 503s fail over.
- **Request identity**: the client's ``X-Request-Id`` (or a generated
  one) gains a ``>workerN`` hop tag per proxy attempt, echoed on the
  response and handed to the worker — so the router hop span, the
  worker's batcher spans and the engine forward stitch into ONE
  request timeline in the PR-7 Perfetto export
  (:func:`~torch_actor_critic_tpu.telemetry.traceview.router_hop_events`).
- **Fleet /metrics**: per-worker snapshots are fetched live and folded
  by :func:`~torch_actor_critic_tpu.serve.metrics.aggregate_snapshots`
  — counters summed, latency histograms merged bucket-wise, every
  input kept per-worker-labelled, restarts never double-counted.
- **Rolling reload** (``POST /reload``): one worker at a time — eject
  from rotation (new traffic drains away; in-flight finishes), trigger
  the worker's validated hot-reload, wait for ``/healthz`` to confirm,
  re-admit. A worker whose reload is rejected (NaN checkpoint) keeps
  its last-good generation and rejoins; the fleet never serves a
  mixed-health rotation and never drops an accepted request.

Entry point: ``python serve.py --fleet N`` (spawns the workers and
this router; docs/SERVING.md "Fleet").
"""

from __future__ import annotations

import json
import logging
import threading
import time
import typing as t
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib import error as urlerr
from urllib import request as urlreq

from torch_actor_critic_tpu.serve.metrics import aggregate_snapshots

logger = logging.getLogger(__name__)

__all__ = ["FleetRouter", "WorkerState"]


class WorkerState:
    """One worker's membership record."""

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url.rstrip("/")
        self.admitted = True
        self.reason: str | None = None  # why ejected
        self.admin_hold = False  # rolling-reload: poll may not re-admit
        self.consecutive_failures = 0
        self.queue_depth = 0  # last-polled, routing signal
        self.routed_total = 0
        self.transitions = 0
        self.last_health: dict | None = None

    def view(self) -> dict:
        return {
            "url": self.url,
            "admitted": self.admitted,
            "reason": self.reason,
            "queue_depth": self.queue_depth,
            "routed_total": self.routed_total,
            "transitions": self.transitions,
        }


class FleetRouter:
    """Health-gated routing over N ``PolicyServer`` workers.

    ``workers`` is a list of base URLs (``http://host:port``), named
    ``w0..wN-1`` in order. ``port=0`` binds an ephemeral router port
    (read ``.port``/``.address`` back — the test/smoke path).
    """

    def __init__(
        self,
        workers: t.Sequence[str],
        host: str = "127.0.0.1",
        port: int = 0,
        poll_interval_s: float = 1.0,
        eject_after: int = 2,
        request_timeout_s: float = 30.0,
        health_timeout_s: float = 2.0,
        span_log=None,
    ):
        if not workers:
            raise ValueError("FleetRouter needs at least one worker URL")
        self.workers: t.Dict[str, WorkerState] = {
            f"w{i}": WorkerState(f"w{i}", url)
            for i, url in enumerate(workers)
        }
        self.poll_interval_s = float(poll_interval_s)
        self.eject_after = int(eject_after)
        self.request_timeout_s = float(request_timeout_s)
        self.health_timeout_s = float(health_timeout_s)
        self.span_log = span_log
        self._lock = threading.Lock()
        self._rr = 0  # guarded-by: _lock
        self._reload_lock = threading.Lock()
        self._poll_stop = threading.Event()
        self._poller: threading.Thread | None = None  # guarded-by: _lock
        self.routed_total = 0  # guarded-by: _lock
        self.failovers_total = 0  # guarded-by: _lock
        self.no_worker_total = 0  # guarded-by: _lock
        # Injectable extra /metrics section: serve.py points this at
        # the warm pool + elastic controller so their counters ride
        # the fleet-aggregated payload under ``fleet``. None (the
        # default) adds no key — the --elastic off key-pin contract.
        self.fleet_extra: t.Callable[[], dict] | None = None
        router = self

        class Handler(BaseHTTPRequestHandler):
            timeout = router.request_timeout_s

            def log_message(self, fmt, *args):  # noqa: A003
                logger.debug("router http: " + fmt, *args)

            def _send(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path == "/healthz":
                    view = router.membership()
                    healthy = view["admitted_workers"]
                    self._send(
                        200 if healthy else 503,
                        dict(
                            view,
                            status="ok" if healthy else "no_workers",
                        ),
                        headers=None if healthy else {"Retry-After": "1"},
                    )
                elif self.path == "/metrics":
                    self._send(200, router.aggregate_metrics())
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802 — stdlib API
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b"{}"
                if self.path == "/act":
                    code, payload, headers = router.route_act(
                        raw, self.headers.get("X-Request-Id")
                    )
                    self._send(code, payload, headers=headers)
                elif self.path == "/reload":
                    self._send(200, {"reload": router.rolling_reload()})
                else:
                    self._send(404, {"error": f"no route {self.path}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None  # guarded-by: _lock

    # ---------------------------------------------------------- membership

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def _set_admitted(self, w: WorkerState, admitted: bool, reason=None):
        """Callers hold ``self._lock``."""
        if w.admitted == admitted:
            w.reason = reason if not admitted else None
            return
        w.admitted = admitted
        w.reason = reason if not admitted else None
        w.transitions += 1
        logger.warning(
            "worker %s (%s) %s%s", w.name, w.url,
            "re-admitted" if admitted else "EJECTED",
            "" if admitted else f" ({reason})",
        )

    def _poll_worker(self, w: WorkerState):
        try:
            with urlreq.urlopen(
                w.url + "/healthz", timeout=self.health_timeout_s
            ) as resp:
                health = json.loads(resp.read())
            code = 200
        except urlerr.HTTPError as e:
            try:
                health = json.loads(e.read())
            except (ValueError, OSError):
                health = {}
            code = e.code
        except (urlerr.URLError, OSError, ValueError):
            with self._lock:
                w.consecutive_failures += 1
                w.last_health = None
                if (
                    w.admitted
                    and w.consecutive_failures >= self.eject_after
                ):
                    self._set_admitted(w, False, "unreachable")
            return
        slots = health.get("slots") or {}
        breakers_open = bool(slots) and all(
            s.get("breaker") == "open" for s in slots.values()
        )
        with self._lock:
            w.consecutive_failures = 0
            w.last_health = health
            w.queue_depth = int(health.get("queue_depth") or 0)
            if health.get("status") == "draining" or code == 503:
                self._set_admitted(w, False, "draining")
            elif breakers_open:
                # Every slot's engine is tripped: the worker answers
                # healthz but can serve nothing — out of rotation
                # until a probe recovers some slot.
                self._set_admitted(w, False, "breaker_open")
            elif not w.admin_hold:
                self._set_admitted(w, True)

    def poll_once(self):
        """One membership sweep over every worker (the poll thread's
        body; tests call it directly for deterministic transitions)."""
        for w in list(self.workers.values()):
            self._poll_worker(w)

    def add_worker(self, url: str) -> str:
        """Admit a worker at runtime (warm-pool scale-up / dead-worker
        replacement, docs/SERVING.md "Cold start"): registers the URL
        under the next free ``wN`` name and health-polls it once so an
        already-warm worker enters rotation immediately. Returns the
        assigned name."""
        with self._lock:
            idx = 0
            while f"w{idx}" in self.workers:
                idx += 1
            name = f"w{idx}"
            w = WorkerState(name, url)
            self.workers[name] = w
        logger.info("router: worker %s added at %s", name, url)
        self._poll_worker(w)
        return name

    def drain_worker(self, name: str) -> str | None:
        """Hold a worker out of rotation for an elastic scale-in: eject
        with ``admin_hold`` set so the poll thread cannot re-admit it
        while it drains. New traffic routes elsewhere immediately;
        requests the worker already accepted finish there (its own
        PR-5 graceful drain answers them once it is SIGTERMed — the
        caller's next step). Returns the worker URL, or None for an
        unknown name."""
        with self._lock:
            w = self.workers.get(name)
            if w is None:
                return None
            w.admin_hold = True
            self._set_admitted(w, False, "scale_in")
            return w.url

    def remove_worker(self, name: str) -> None:
        """Forget a worker after its drain completed (elastic scale-in
        teardown). Only a held-out or ejected worker may be removed —
        removing an admitted one would drop routed requests, which the
        drain path exists to prevent."""
        with self._lock:
            w = self.workers.get(name)
            if w is None:
                raise KeyError(f"no worker named {name!r}")
            if w.admitted and not w.admin_hold:
                raise ValueError(
                    f"worker {name} is still admitted; drain_worker() "
                    "it first"
                )
            del self.workers[name]
        logger.info("router: worker %s removed", name)

    def membership(self) -> dict:
        with self._lock:
            views = {n: w.view() for n, w in self.workers.items()}
            routed, failovers = self.routed_total, self.failovers_total
        return {
            "workers": views,
            "admitted_workers": sum(
                1 for v in views.values() if v["admitted"]
            ),
            "routed_total": routed,
            "failovers_total": failovers,
        }

    # ------------------------------------------------------------- routing

    def _pick_locked(self, exclude: t.Set[str]) -> WorkerState | None:
        """Least last-known queue depth among admitted workers not yet
        tried for this request; round-robin on ties."""
        names = list(self.workers)
        n = len(names)
        best = None
        for off in range(n):
            w = self.workers[names[(self._rr + off) % n]]
            if not w.admitted or w.name in exclude:
                continue
            if best is None or w.queue_depth < best.queue_depth:
                best = w
        if best is not None:
            self._rr = (names.index(best.name) + 1) % n
        return best

    def route_act(
        self, body: bytes, request_id: str | None
    ) -> t.Tuple[int, dict, dict]:
        """Proxy one /act: ``(status, payload, response_headers)``.

        Fails over across admitted workers on connection errors (the
        worker is ejected on the spot) and 503s; relays 429 and 4xx
        as-is. The hop-tagged request id is echoed so the client sees
        which worker answered."""
        rid = request_id or uuid.uuid4().hex[:16]
        tried: t.Set[str] = set()
        last: t.Tuple[int, dict, dict] | None = None
        for _attempt in range(len(self.workers)):
            with self._lock:
                w = self._pick_locked(tried)
                if w is not None:
                    w.routed_total += 1
                    self.routed_total += 1
                    if _attempt:
                        self.failovers_total += 1
            if w is None:
                break
            tried.add(w.name)
            hop_rid = f"{rid}>{w.name}"
            t0 = time.perf_counter()
            try:
                req = urlreq.Request(
                    w.url + "/act", data=body,
                    headers={
                        "Content-Type": "application/json",
                        "X-Request-Id": hop_rid,
                    },
                )
                with urlreq.urlopen(
                    req, timeout=self.request_timeout_s
                ) as resp:
                    payload = json.loads(resp.read())
                self._note_hop(rid, w.name, t0, "ok")
                return 200, payload, {"X-Request-Id": hop_rid}
            except urlerr.HTTPError as e:
                try:
                    payload = json.loads(e.read())
                except (ValueError, OSError):
                    payload = {"error": f"worker {w.name} HTTP {e.code}"}
                headers = {"X-Request-Id": hop_rid}
                ra = e.headers.get("Retry-After") if e.headers else None
                if ra:
                    headers["Retry-After"] = ra
                self._note_hop(rid, w.name, t0, f"http_{e.code}")
                if e.code == 503:
                    # Draining / breaker-open / backend timeout: this
                    # worker cannot serve it NOW — another may. Keep
                    # the response in case every worker says 503.
                    last = (e.code, payload, headers)
                    continue
                # 429 (rate) and client errors (4xx) relay unchanged:
                # retrying elsewhere would either pile onto a
                # saturated fleet or repeat a malformed request.
                return e.code, payload, headers
            except (urlerr.URLError, OSError) as e:
                # Connection-level death: eject NOW (the poll thread
                # would take poll_interval to notice) and fail over.
                with self._lock:
                    self._set_admitted(w, False, "unreachable")
                self._note_hop(rid, w.name, t0, "unreachable")
                logger.warning(
                    "worker %s unreachable mid-request (%r); failing "
                    "over", w.name, e,
                )
                last = (
                    503,
                    {
                        "error": f"worker {w.name} unreachable",
                        "reason": "worker_unreachable",
                        "request_id": rid,
                    },
                    {"Retry-After": "1", "X-Request-Id": hop_rid},
                )
                continue
        if last is not None:
            return last
        with self._lock:
            self.no_worker_total += 1
        return (
            503,
            {
                "error": "no admitted workers in the fleet",
                "reason": "no_workers",
                "request_id": rid,
            },
            {"Retry-After": "1", "X-Request-Id": rid},
        )

    def _note_hop(self, rid, worker, t0, outcome):
        if self.span_log is None:
            return
        now = time.perf_counter()
        self.span_log.record({
            "request_id": rid, "worker": worker,
            "t_route": t0, "t_done": now, "outcome": outcome,
        })

    # ------------------------------------------------------------- metrics

    def _fetch_worker_metrics(self, w: WorkerState) -> dict | None:
        try:
            with urlreq.urlopen(
                w.url + "/metrics", timeout=self.health_timeout_s
            ) as resp:
                return json.loads(resp.read())
        except (urlerr.URLError, OSError, ValueError):
            return None

    def aggregate_metrics(self) -> dict:
        """The fleet ``/metrics`` payload: per-worker snapshots folded
        by :func:`aggregate_snapshots` (sums for counters, merged
        latency buckets — a restarted worker's reset counters simply
        re-enter the sum, never double-counted), plus the router's own
        membership/routing counters under ``router`` and, when
        ``fleet_extra`` is attached, the warm-pool/elastic section
        under ``fleet`` (spare count, last-refill status, controller
        counters — docs/SERVING.md "Fleet")."""
        snaps = {
            w.name: self._fetch_worker_metrics(w)
            for w in list(self.workers.values())
        }
        out = aggregate_snapshots(snaps)
        with self._lock:
            no_worker = self.no_worker_total
        out["router"] = dict(self.membership(), no_worker_total=no_worker)
        extra = self.fleet_extra
        if extra is not None:
            try:
                out["fleet"] = extra()
            except Exception:  # noqa: BLE001 - metrics must not fail on a torn-down pool
                logger.exception("fleet extra metrics section failed")
        return out

    # ------------------------------------------------------ rolling reload

    def rolling_reload(
        self, settle_timeout_s: float = 10.0
    ) -> t.Dict[str, dict]:
        """Hot-reload the fleet one worker at a time, zero dropped
        requests: eject from rotation (new traffic routes elsewhere;
        in-flight requests finish on the worker), POST its ``/reload``
        (the worker-side validated hot-reload: a NaN checkpoint is
        rejected there and last-good keeps serving), wait for
        ``/healthz`` to confirm it is serving, re-admit. Serialized
        per-fleet (the lock): two concurrent rolling reloads would
        otherwise eject two workers at once. Workers an elastic drain
        already holds (``admin_hold`` set) are skipped, and a drain
        that grabs a worker mid-reload keeps its hold — the reload
        never re-admits a scale-in victim."""
        out: t.Dict[str, dict] = {}
        with self._reload_lock:
            for name in list(self.workers):
                w = self.workers.get(name)
                if w is None:
                    continue  # removed while the reload walked the fleet
                with self._lock:
                    if w.admin_hold:
                        # Already held out by an elastic drain: the
                        # victim may be SIGTERMed mid-exit; POSTing
                        # /reload at it and clearing its hold below
                        # would re-admit a dying worker and break the
                        # drain reaper's remove_worker.
                        out[name] = {"skipped": "admin_hold"}
                        continue
                    w.admin_hold = True
                    self._set_admitted(w, False, "rolling_reload")
                status: dict = {}
                try:
                    req = urlreq.Request(
                        w.url + "/reload", data=b"{}",
                        headers={"Content-Type": "application/json"},
                    )
                    with urlreq.urlopen(
                        req, timeout=max(self.request_timeout_s, 30.0)
                    ) as resp:
                        status["reload"] = json.loads(
                            resp.read()
                        ).get("reload")
                except (urlerr.URLError, OSError, ValueError) as e:
                    status["error"] = repr(e)[:200]
                # Confirm the worker is serving again before re-admit.
                deadline = time.monotonic() + settle_timeout_s
                healthy = False
                while time.monotonic() < deadline:
                    try:
                        with urlreq.urlopen(
                            w.url + "/healthz",
                            timeout=self.health_timeout_s,
                        ) as resp:
                            healthy = (
                                json.loads(resp.read()).get("status")
                                == "ok"
                            )
                        if healthy:
                            break
                    except (urlerr.URLError, OSError, ValueError):
                        pass
                    time.sleep(0.05)
                with self._lock:
                    if w.reason == "scale_in":
                        # An elastic drain grabbed this worker while
                        # the reload waited on it; the hold (and the
                        # eventual removal) belongs to the drain
                        # reaper now — do not clear it or re-admit.
                        status["readmitted"] = False
                        status["drained"] = True
                    else:
                        w.admin_hold = False
                        if healthy:
                            self._set_admitted(w, True)
                        status["readmitted"] = healthy
                out[name] = status
        return out

    # --------------------------------------------------------------- admin

    def start(self):
        """Serve + poll on daemon threads (tests, smoke)."""
        self._poll_stop.clear()

        def poll_loop():
            while not self._poll_stop.wait(timeout=self.poll_interval_s):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — pragma: no cover —
                    # membership must survive any one bad poll
                    logger.exception("membership poll failed; will retry")

        with self._lock:
            poller = self._poller = threading.Thread(
                target=poll_loop, name="fleet-membership", daemon=True
            )
            http = self._thread = threading.Thread(
                target=self._httpd.serve_forever, name="fleet-router",
                daemon=True,
            )
        poller.start()
        http.start()
        return self

    def serve_forever(self):
        """Block serving until interrupted (the CLI path)."""
        self.start()
        with self._lock:
            http = self._thread
        try:
            http.join()
        except KeyboardInterrupt:  # pragma: no cover — operator stop
            pass
        finally:
            self.close()

    def close(self):
        self._poll_stop.set()
        # Swap the handle out under the lock, join OUTSIDE it: the
        # poll loop's poll_once() takes _lock per worker.
        with self._lock:
            poller, self._poller = self._poller, None
        if poller is not None:
            poller.join(timeout=10.0)
        self._httpd.shutdown()
        self._httpd.server_close()
        with self._lock:
            http, self._thread = self._thread, None
        if http is not None:
            http.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
