"""Per-slot engine circuit breaker: fail fast, probe, recover.

An engine that has started failing — poisoned params emitting NaN
actions, an accelerator fault, a wedged runtime — fails *slowly*: every
request still pays queueing plus a doomed forward before erroring. The
breaker is the standard containment state machine (closed → open →
half-open) applied to the policy engine, with the failure predicate
coming from the serving path itself: a forward that raised, or one
whose in-graph fused all-finite reduction flagged non-finite actions
(:class:`~torch_actor_critic_tpu.serve.admission.NonFiniteActionError`).

States:

- **closed** — healthy. Failures are counted; ``fail_threshold``
  *consecutive* failures trip the breaker (one transient fault in a
  stream of successes never does — success resets the streak).
- **open** — every request for the slot is shed immediately
  (503 + ``Retry-After`` = remaining cooldown) with no engine work.
  After ``cooldown_s`` the breaker lazily enters half-open on the next
  ``allow()``.
- **half-open** — exactly ``probe_quota`` request groups are let
  through as probes; the rest keep shedding. A probe success closes
  the breaker (full recovery); a probe failure re-opens it for another
  cooldown.

Deterministic by construction: the clock is injected (``clock``), so
tests drive open→half-open transitions by advancing a fake clock —
the no-sleeps rule of ``tests/test_resilience.py`` carried over to
``tests/test_overload.py``. Thread-safe: one lock guards every
transition; the dispatcher thread records outcomes while HTTP handler
threads read ``admits()``.

Every transition emits a structured event dict through ``on_event``
(the registry wires this to its bounded event log and the process
logger; ``/metrics`` exports per-slot state/trips/probes via
``ModelRegistry.breaker_stats``).
"""

from __future__ import annotations

import logging
import threading
import time
import typing as t

logger = logging.getLogger(__name__)

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(
        self,
        fail_threshold: int = 5,
        cooldown_s: float = 5.0,
        probe_quota: int = 1,
        clock: t.Callable[[], float] = time.monotonic,
        on_event: t.Callable[[dict], None] | None = None,
        name: str = "default",
    ):
        if fail_threshold < 1:
            raise ValueError(
                f"fail_threshold must be >= 1, got {fail_threshold}"
            )
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        if probe_quota < 1:
            raise ValueError(f"probe_quota must be >= 1, got {probe_quota}")
        self.fail_threshold = int(fail_threshold)
        self.cooldown_s = float(cooldown_s)
        self.probe_quota = int(probe_quota)
        self.name = name
        self._clock = clock
        self.on_event = on_event
        self._lock = threading.Lock()
        self._state = CLOSED  # guarded-by: _lock
        self._consecutive_failures = 0  # guarded-by: _lock
        self._opened_at: float | None = None  # guarded-by: _lock
        self._probes_inflight = 0  # guarded-by: _lock
        self.trips_total = 0  # guarded-by: _lock
        self.probes_total = 0  # guarded-by: _lock
        self.failures_total = 0  # guarded-by: _lock
        self.successes_total = 0  # guarded-by: _lock

    # ------------------------------------------------------------- events

    def _emit(self, event: str, **fields):
        """Build + deliver one transition event. Callers hold
        ``self._lock`` (every emit sits inside a state transition), so
        the snapshot fields are consistent; the ``on_event`` sink is
        therefore invoked under the breaker lock and must not call
        back into this breaker."""
        payload = dict(
            event=event, breaker=self.name, state=self._state,
            consecutive_failures=self._consecutive_failures,
            trips_total=self.trips_total, **fields,
        )
        if self.on_event is not None:
            try:
                self.on_event(payload)
            except Exception:  # noqa: BLE001 — a broken event sink must
                logger.exception("breaker event sink failed")  # not
                # take the state machine down with it

    # -------------------------------------------------------------- state

    def _refresh_locked(self, now: float):
        """Lazy open → half-open transition once the cooldown elapsed
        (no timer thread: the next admission check performs it)."""
        if (
            self._state == OPEN
            and self._opened_at is not None
            and now - self._opened_at >= self.cooldown_s
        ):
            self._state = HALF_OPEN
            self._probes_inflight = 0
            self._emit("breaker_half_open")

    @property
    def state(self) -> str:
        with self._lock:
            self._refresh_locked(self._clock())
            return self._state

    def retry_after_s(self) -> float:
        """Seconds until a retry could plausibly be admitted: remaining
        cooldown when open, one cooldown's worth of patience otherwise."""
        with self._lock:
            now = self._clock()
            self._refresh_locked(now)
            if self._state == OPEN and self._opened_at is not None:
                return max(0.0, self.cooldown_s - (now - self._opened_at))
            return self.cooldown_s

    def admits(self) -> bool:
        """Submit-time check (non-consuming): False only while hard
        open. Half-open admits — queued requests become probe
        candidates; :meth:`allow` rations the actual probes."""
        with self._lock:
            self._refresh_locked(self._clock())
            return self._state != OPEN

    def allow(self) -> bool:
        """Dispatch-time check, called once per request group. Closed
        always allows; open never does; half-open allows up to
        ``probe_quota`` concurrent probe groups."""
        with self._lock:
            self._refresh_locked(self._clock())
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return False
            if self._probes_inflight >= self.probe_quota:
                return False
            self._probes_inflight += 1
            self.probes_total += 1
            self._emit("breaker_probe")
            return True

    # ------------------------------------------------------------ outcomes

    def record_success(self):
        with self._lock:
            self.successes_total += 1
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self._opened_at = None
                self._probes_inflight = 0
                self._emit("breaker_close")

    def record_failure(self, error: BaseException | None = None):
        with self._lock:
            self.failures_total += 1
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                # The probe failed: back to a full cooldown.
                self._state = OPEN
                self._opened_at = self._clock()
                self.trips_total += 1
                self._probes_inflight = 0
                self._emit("breaker_reopen", error=repr(error)[:200])
            elif (
                self._state == CLOSED
                and self._consecutive_failures >= self.fail_threshold
            ):
                self._state = OPEN
                self._opened_at = self._clock()
                self.trips_total += 1
                self._emit("breaker_open", error=repr(error)[:200])

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        """The ``/metrics`` view of this breaker."""
        with self._lock:
            self._refresh_locked(self._clock())
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "failures_total": self.failures_total,
                "successes_total": self.successes_total,
                "trips_total": self.trips_total,
                "probes_total": self.probes_total,
                "fail_threshold": self.fail_threshold,
                "cooldown_s": self.cooldown_s,
            }
