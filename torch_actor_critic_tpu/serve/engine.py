"""The jitted policy forward over a bucketed set of batch shapes.

XLA compiles one program per input shape, so serving raw request-sized
batches would compile an unbounded set of executables (and pay a
multi-second compile on the first request of every new size — a latency
cliff no service can absorb). The engine instead pads every batch up to
a small fixed menu of power-of-two **buckets** and compiles exactly
``len(buckets) x 2`` programs (deterministic / sampled), all warmed up
front at startup. Padding rows are zeros; the pad is sliced off before
the response leaves the engine, and row ``i`` of the output depends
only on row ``i`` of the input (every model op is row-wise over the
batch axis), so padded and unpadded forwards agree bitwise.

The jit cache is keyed ``(bucket, deterministic)`` per engine instance;
the registry holds one engine per model slot, which makes the full
service-wide key the ISSUE's ``(bucket, deterministic, model_slot)``.

Works for the flat :class:`~torch_actor_critic_tpu.models.actor.Actor`
and the pytree-observation
:class:`~torch_actor_critic_tpu.models.visual.VisualActor` alike: an
observation is whatever pytree the model takes, and padding maps over
its leaves. Deterministic serving returns the squashed-Gaussian mean
(``tanh(mu) * act_limit``); sampled serving draws the reparameterized
action with an explicit PRNG key.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import typing as t

import jax
import jax.numpy as jnp
import numpy as np

from torch_actor_critic_tpu.diagnostics.watchdog import get_watchdog
from torch_actor_critic_tpu.serve.admission import NonFiniteActionError

logger = logging.getLogger(__name__)

__all__ = ["PolicyEngine", "default_buckets"]


def default_buckets(max_batch: int) -> t.Tuple[int, ...]:
    """Powers of two ``2, 4, ... , max_batch`` (``max_batch`` itself is
    always covered, rounded up to the next power of two).

    The ladder starts at 2, not 1 — even for ``max_batch=1``, whose
    lone request is padded up to a 2-row bucket: XLA:CPU lowers a
    batch-1 matmul to a matvec whose accumulation order differs in the
    last bit from the gemm path every larger batch takes. Padding a
    lone request to 2 rows costs nothing and keeps responses
    **batch-shape invariant** — the same observation returns the same
    bits whichever bucket it lands in (pinned by tests/test_serve.py).
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    buckets = []
    b = 2
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(b)
    return tuple(buckets)


class PolicyEngine:
    """Bucketed, jitted ``(params, obs, key) -> action`` for one actor.

    ``actor_def`` is any module honoring the actor contract
    ``apply(params, obs, key, deterministic, with_logprob)``;
    ``obs_spec`` is the single-observation ShapeDtypeStruct pytree the
    env layer exposes (``pool.obs_spec``). Thread-safe: jitted
    executables are immutable once built, and the cache dict is guarded
    for the build-on-miss path.

    Subclass seams (:mod:`~torch_actor_critic_tpu.serve.sharded` uses
    all of them): :meth:`_build_forwards` constructs the jitted
    ``self._fwd`` pair, :attr:`TRACE_PREFIX` names the watchdog/cost
    identity, :attr:`precision` tags the numeric tier the fleet keys
    its params-placement cache on, and :meth:`_cost_devices` is the
    per-chip divisor the warmup cost registration records.
    """

    # Watchdog source / cost-registry identity prefix; per-bucket names
    # are f"{TRACE_PREFIX}[b{N}]".
    TRACE_PREFIX = "serve/forward"

    def __init__(
        self,
        actor_def,
        obs_spec: t.Any,
        max_batch: int = 64,
        buckets: t.Sequence[int] | None = None,
        sanitize: bool = False,
    ):
        self.actor_def = actor_def
        self.obs_spec = obs_spec
        self.max_batch = int(max_batch)
        # Transfer sanitizer (--sanitize, docs/ANALYSIS.md "Runtime
        # sanitizers"): with the tier on, the forward dispatch runs
        # under jax.transfer_guard("disallow") — any IMPLICIT
        # host<->device transfer on the hot path (numpy leaking into
        # the jit, a stray scalar) becomes a hard failure instead of an
        # invisible per-request transfer tax. Inputs are then placed
        # EXPLICITLY (jax.device_put, exempt from the guard) by
        # _device_obs/_device_key. Off (the default) leaves the code
        # path untouched.
        self.sanitize = bool(sanitize)
        self.buckets = tuple(sorted(set(
            int(b) for b in (buckets or default_buckets(self.max_batch))
        )))
        if self.buckets[0] < 1:
            raise ValueError(f"buckets must be >= 1, got {self.buckets}")
        if self.buckets[-1] < self.max_batch:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} < max_batch "
                f"{self.max_batch}: requests between them could never "
                "be padded to a compiled shape"
            )
        self._build_forwards()
        self._compiled: set = set()  # {(bucket, det)}; guarded-by: _lock
        self._lock = threading.Lock()
        # Precomputed jax.profiler span labels (one per bucket): under
        # an active trace each serving forward shows up as a labeled
        # span; with no trace the annotation is a no-op TraceMe, so the
        # serving hot path pays ~nothing (docs/OBSERVABILITY.md).
        self._trace_names = {
            b: f"{self.TRACE_PREFIX}[b{b}]" for b in self.buckets
        }
        # Compile accounting (docs/OBSERVABILITY.md recompile
        # watchdog): per-bucket warmup vs LIVE vs bundle-load compile
        # counts — a silently-recompiling bucket was previously
        # indistinguishable from a slow one, and a bundle-loaded
        # executable must not masquerade as either (the cost is a disk
        # read, not an XLA run). First-seen (bucket, deterministic)
        # keys count here; the process-wide watchdog additionally
        # attributes every real backend compile (including re-compiles
        # of already-seen keys) to this engine's `serve/forward[bN]`
        # source labels and flags post-steady ones as anomalies.
        self._compile_counts: t.Dict[int, t.List[int]] = (  # guarded-by: _lock
            {}
        )  # bucket -> [warmup, live, bundle]
        self.compiles_total = 0  # guarded-by: _lock
        self._warmup_active = False  # guarded-by: _lock
        self._bundle_active = False  # guarded-by: _lock
        self._warmed = False  # guarded-by: _lock
        self.bundle_loaded = False  # guarded-by: _lock
        self._watchdog = get_watchdog().install()

    def _build_forwards(self) -> None:
        """Construct the jitted ``self._fwd`` pair (``{True:
        deterministic, False: sampled}``). Subclasses override to change
        the program (the sub-mesh engine jits with shardings) while the
        bucketing/padding/compile-accounting machinery above stays
        shared."""
        # Donating the padded obs buffer lets XLA reuse its HBM for the
        # output on accelerators; on CPU donation is unsupported and
        # only produces warnings, so gate it. The PRNG key is NOT
        # donated: its buffer is tiny, and donation would delete any
        # key a caller holds across calls.
        donate = jax.default_backend() not in ("cpu",)

        # Each forward also returns an all-finite flag over the action
        # output — the PR 2 sentinel predicate fused INTO the serving
        # graph (one reduction per batch, no extra host<->device sync:
        # the flag rides the same transfer as the actions it guards).
        # A NaN action must never reach a client, and host-side
        # np.isfinite over the full output would re-read every row the
        # accelerator just produced.
        def fwd_sampled(params, obs, key):
            action, _ = self.actor_def.apply(
                params, obs, key, deterministic=False, with_logprob=False
            )
            return action, jnp.all(jnp.isfinite(action))

        def fwd_deterministic(params, obs):
            action, _ = self.actor_def.apply(
                params, obs, None, deterministic=True, with_logprob=False
            )
            return action, jnp.all(jnp.isfinite(action))

        self._fwd = {
            True: jax.jit(
                fwd_deterministic, donate_argnums=(1,) if donate else ()
            ),
            False: jax.jit(
                fwd_sampled, donate_argnums=(1,) if donate else ()
            ),
        }

    @property
    def precision(self) -> str:
        """Numeric serving tier. The base engine always computes in
        f32; the sub-mesh engine's tiers override this. The fleet keys
        its per-replica params-placement cache on
        ``(generation, precision)`` so a tier change can never serve
        stale-dtype params (docs/SERVING.md "Sharded serving")."""
        return "f32"

    def _cost_devices(self) -> int:
        """Mesh size the warmup cost registration divides by, so the
        registered FLOPs/bytes are PER-CHIP (one chip vs one chip's
        peak in roofline/MFU — the PR-8 convention)."""
        return 1

    def prepare_params(self, params):
        """Transform raw checkpoint params into what :meth:`act`
        consumes — identity here; the int8 tier quantizes
        (register/reload time, NEVER per request)."""
        return params

    def _device_obs(self, padded):
        """Pre-place one padded observation pytree for the forward
        (identity by default: jit moves host arrays itself). Under
        ``sanitize`` the placement is an EXPLICIT ``jax.device_put`` so
        the guarded forward sees device arrays only — the one
        host->device hop per request, visible and intentional."""
        if self.sanitize:
            return jax.device_put(padded)
        return padded

    def _device_key(self, key):
        """Pre-place the sampled-action PRNG key (identity by default;
        explicit ``device_put`` under ``sanitize``, mirroring
        :meth:`_device_obs`)."""
        if self.sanitize and key is not None:
            return jax.device_put(key)
        return key

    def replicate(self) -> "PolicyEngine":
        """A fresh engine with this one's configuration and an EMPTY
        jit cache — the per-device replica constructor
        (:mod:`~torch_actor_critic_tpu.serve.fleet`): each device
        needs its own compiled executables and compile accounting,
        while actor definition, obs spec and bucket ladder are
        shared."""
        return PolicyEngine(
            self.actor_def, self.obs_spec, max_batch=self.max_batch,
            buckets=self.buckets, sanitize=self.sanitize,
        )

    # ----------------------------------------------------------- buckets

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (n must be <= max bucket)."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} rows exceeds the largest bucket "
            f"{self.buckets[-1]}; the batcher must split it first"
        )

    def compiled_buckets(self) -> t.FrozenSet[t.Tuple[int, bool]]:
        """The ``(bucket, deterministic)`` shapes traced so far — the
        jit-cache keys this engine has populated."""
        return frozenset(self._compiled)

    def compile_stats(self) -> dict:
        """Per-bucket warmup/live/bundle compile counts for
        ``/metrics``: ``live`` must stay 0 in a healthy service — every
        compile belongs in warmup (or came from the warm-start bundle
        at disk-read cost), and a nonzero live count means a real
        request paid a multi-second compile (the recompilation watchdog
        logs the offending bucket as it happens)."""
        with self._lock:
            return {
                "compiles_total": self.compiles_total,
                "live_compiles": sum(
                    c[1] for c in self._compile_counts.values()
                ),
                "bundle_compiles": sum(
                    c[2] for c in self._compile_counts.values()
                ),
                "bundle_loaded": self.bundle_loaded,
                "buckets": {
                    str(b): {"warmup": c[0], "live": c[1], "bundle": c[2]}
                    for b, c in sorted(self._compile_counts.items())
                },
            }

    # ----------------------------------------------------------- forward

    def _pad(self, obs: t.Any, n: int, bucket: int) -> t.Any:
        if n == bucket:
            return obs

        def pad_leaf(x):
            pad = np.zeros((bucket - n,) + tuple(x.shape[1:]), dtype=x.dtype)
            return np.concatenate([np.asarray(x), pad], axis=0)

        return jax.tree_util.tree_map(pad_leaf, obs)

    def act(
        self,
        params,
        obs: t.Any,
        key: jax.Array | None = None,
        deterministic: bool = True,
    ) -> np.ndarray:
        """One padded forward; ``obs`` leaves carry a leading batch axis
        of n <= max bucket rows; returns the n action rows."""
        n = int(jax.tree_util.tree_leaves(obs)[0].shape[0])
        bucket = self.bucket_for(n)
        padded = self._device_obs(self._pad(obs, n, bucket))
        # Sanitize tier: the dispatch itself runs with implicit
        # transfers disallowed — the explicit _device_obs/_device_key
        # placements above are exempt, so a clean path passes and a
        # stray host value (numpy params, a scalar) fails loudly.
        guard = (
            jax.transfer_guard("disallow")
            if self.sanitize else contextlib.nullcontext()
        )
        with self._watchdog.source(self._trace_names[bucket]), \
                jax.profiler.TraceAnnotation(self._trace_names[bucket]):
            if deterministic:
                with guard:
                    out, finite = self._fwd[True](params, padded)
            else:
                if key is None:
                    raise ValueError("sampled serving needs a PRNG key")
                device_key = self._device_key(key)
                with guard:
                    out, finite = self._fwd[False](
                        params, padded, device_key
                    )
        with self._lock:
            key_ = (bucket, bool(deterministic))
            if key_ not in self._compiled:
                self._compiled.add(key_)
                counts = self._compile_counts.setdefault(bucket, [0, 0, 0])
                live = not (self._warmup_active or self._bundle_active)
                counts[
                    2 if self._bundle_active else (1 if live else 0)
                ] += 1
                self.compiles_total += 1
                if live and self._warmed:
                    logger.warning(
                        "serving bucket %d (deterministic=%s) compiled "
                        "OUTSIDE warmup — a live request paid the "
                        "compile; add the bucket to warmup or check the "
                        "bucket ladder (docs/OBSERVABILITY.md)",
                        bucket, deterministic,
                    )
        if not bool(finite):
            raise NonFiniteActionError(bucket, bool(deterministic))
        return np.asarray(out)[:n]

    # ------------------------------------------------------------ warmup

    def _verify_bundle(
        self,
        bundle,
        params,
        deterministic_only: bool,
        buckets: t.Sequence[int] | None,
    ) -> None:
        """Before a bundle-armed warmup dispatches anything: every
        program this warmup will compile must exist in the bundle with
        input avals matching the exact arguments the jit will see.
        Raises ``aot.BundleMismatchError`` (loud rejection; the caller
        counts it and falls back to a plain warmup). Deserializing each
        program here also proves the serialized artifact round-trips —
        a corrupt bundle is a rejection, not a crash mid-warmup."""
        from torch_actor_critic_tpu.aot.manifest import program_name

        # The sampled artifacts take RAW key data (bundle.py: typed-key
        # avals don't serialize) — verify against that convention.
        key_data = jax.random.key_data(jax.random.key(0))
        for bucket in (buckets or self.buckets):
            zero_obs = jax.tree_util.tree_map(
                lambda s: np.zeros((bucket,) + tuple(s.shape), s.dtype),
                self.obs_spec,
            )
            for det in (True,) if deterministic_only else (True, False):
                name = program_name(self.TRACE_PREFIX, bucket, det)
                call_args = (
                    (params, zero_obs) if det
                    else (params, zero_obs, key_data)
                )
                bundle.verify_program(name, *call_args)

    def warmup(
        self,
        params,
        deterministic_only: bool = False,
        buckets: t.Sequence[int] | None = None,
        bundle=None,
    ) -> t.List[t.Tuple[int, bool]]:
        """Trace + compile every ``(bucket, deterministic)`` program up
        front so no live request ever pays a compile. Returns the list
        of shapes warmed. Compiles in here count as ``warmup`` in
        :meth:`compile_stats` and are ``expected`` to the recompilation
        watchdog (a slot registered after the serving plane went steady
        must not flag its own warmup as anomalies).

        With ``bundle`` (a verified-compatible
        :class:`~torch_actor_critic_tpu.aot.WarmStartBundle`), the
        programs are first checked against the bundle's serialized
        avals — a mismatch raises ``BundleMismatchError`` before any
        dispatch — and the warmup dispatches then run under the
        watchdog's ``bundle_load()`` scope: with the persistent cache
        pointed at the bundle's ``xla_cache/`` they are disk reads, and
        they count in the third (``bundle``) column of
        :meth:`compile_stats`, not as warmup or live compiles."""
        from torch_actor_critic_tpu.telemetry.costmodel import (
            get_cost_registry,
        )

        if bundle is not None:
            self._verify_bundle(bundle, params, deterministic_only, buckets)
        warmed = []
        key = jax.random.key(0)
        with self._lock:
            self._warmup_active = True
            self._bundle_active = bundle is not None
        try:
            scope = (
                self._watchdog.bundle_load() if bundle is not None
                else self._watchdog.expected()
            )
            with scope:
                for bucket in (buckets or self.buckets):
                    zero_obs = jax.tree_util.tree_map(
                        lambda s: np.zeros(
                            (bucket,) + tuple(s.shape), s.dtype
                        ),
                        self.obs_spec,
                    )
                    # Per-bucket program cost -> the registry, BEFORE
                    # the act() below (donation may consume zero_obs on
                    # accelerators). compiled=False: one cheap re-trace
                    # per bucket at warmup, no extra backend compile —
                    # FLOPs are exact, bytes pre-fusion (an upper
                    # bound; docs/OBSERVABILITY.md "Cost attribution").
                    get_cost_registry().register_jit(
                        self._trace_names[bucket],
                        self._fwd[True],
                        jax.tree_util.tree_map(
                            lambda x: jax.ShapeDtypeStruct(
                                np.shape(x), x.dtype
                            ),
                            params,
                        ),
                        jax.tree_util.tree_map(
                            lambda x: jax.ShapeDtypeStruct(
                                x.shape, x.dtype
                            ),
                            zero_obs,
                        ),
                        compiled=False,
                        devices=self._cost_devices(),
                    )
                    for det in (True,) if deterministic_only else (True, False):
                        if det:
                            sub = None
                        else:
                            key, sub = jax.random.split(key)
                        out = self.act(params, zero_obs, sub, deterministic=det)
                        warmed.append((bucket, det))
                    del out
        finally:
            with self._lock:
                self._warmup_active = False
                self._bundle_active = False
                self._warmed = True
                if bundle is not None:
                    self.bundle_loaded = True
        if bundle is not None:
            self._watchdog.note_bundle_hit(len(warmed))
        return warmed
