"""Thread-safe micro-batching queue in front of the policy engine.

The server-side dynamic-batching pattern (TorchBeast, arXiv:1910.03552;
Podracer, arXiv:2104.06272): concurrent ``act(obs)`` calls land in one
queue, and a single dispatcher thread coalesces them into engine
forwards of up to ``max_batch`` rows — waiting at most ``max_wait_ms``
past the oldest queued request before flushing whatever it has. One
forward per coalesced group amortizes dispatch latency across every
caller in it; the engine pads the group to its bucket shape
(:mod:`~torch_actor_critic_tpu.serve.engine`), and responses are
sliced back per request, so callers never observe the batching.

Grouping rules:

- only requests with the same ``(slot, deterministic)`` share a
  forward (different slots are different params; the deterministic
  flag is a static compile argument);
- a request with more rows than ``max_batch`` is **split** into
  max_batch-sized engine calls and its rows reassembled in order;
- queue order is preserved within a group, and every request —
  including ones drained during shutdown — gets its future resolved:
  nothing is dropped.

Each response carries the model **generation** it was computed under
(:mod:`~torch_actor_critic_tpu.serve.registry`): the dispatcher
captures ``(engine, params, generation)`` once per group, so a
hot-reload swap mid-group simply means the group finishes on the old
weights and the next group picks up the new ones.
"""

from __future__ import annotations

import collections
import threading
import time
import typing as t
from concurrent.futures import Future

import jax
import numpy as np

from torch_actor_critic_tpu.serve.metrics import ServeMetrics

__all__ = ["MicroBatcher", "ActResult"]


class ActResult(t.NamedTuple):
    """One resolved ``act`` call: the action rows (leading axis matches
    the request's) and the model generation that computed them."""

    action: np.ndarray
    generation: int


class _Request:
    __slots__ = ("obs", "rows", "slot", "deterministic", "future", "t_enq")

    def __init__(self, obs, rows, slot, deterministic):
        self.obs = obs
        self.rows = rows
        self.slot = slot
        self.deterministic = deterministic
        self.future: Future = Future()
        self.t_enq = time.perf_counter()


class MicroBatcher:
    """Coalesces concurrent policy requests into bucketed forwards.

    ``registry`` resolves slot names to ``(engine, params, generation)``
    (:class:`~torch_actor_critic_tpu.serve.registry.ModelRegistry`).
    ``max_batch`` bounds rows per engine call; ``max_wait_ms`` bounds
    the queueing latency added to the OLDEST request in a group (a lone
    request never waits longer than the deadline). ``seed`` keys the
    sampled-action PRNG stream.
    """

    def __init__(
        self,
        registry,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        metrics: ServeMetrics | None = None,
        seed: int = 0,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.registry = registry
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self._key = jax.random.key(seed)
        self._queue: collections.deque[_Request] = collections.deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._running = True
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="micro-batcher", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- submit

    def submit(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
    ) -> Future:
        """Enqueue one request; returns a Future resolving to
        :class:`ActResult`. ``obs`` is a single observation pytree or a
        batch of them (leading axis); the response's leading axis
        matches the request's."""
        engine, _, _ = self.registry.acquire(slot)  # validates slot name
        obs, rows, batched = self._ensure_batched(engine, obs)
        req = _Request(obs, rows, slot, bool(deterministic))
        outer: Future = Future()

        def _copy(f: Future):
            err = f.exception()
            if err is not None:
                outer.set_exception(err)
                return
            res: ActResult = f.result()
            action = res.action if batched else res.action[0]
            outer.set_result(ActResult(action, res.generation))

        req.future.add_done_callback(_copy)
        with self._nonempty:
            # Checked under the lock: a request enqueued after close()
            # flipped the flag would never be drained.
            if not self._running:
                raise RuntimeError("MicroBatcher is closed")
            self._queue.append(req)
            self.metrics.record_enqueue(len(self._queue))
            self._nonempty.notify()
        return outer

    def act(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
        timeout: float | None = 30.0,
    ) -> ActResult:
        """Blocking :meth:`submit`."""
        return self.submit(obs, deterministic, slot).result(timeout=timeout)

    def _ensure_batched(self, engine, obs):
        """(batched_obs, n_rows, was_batched) — unbatched observations
        (leaf ndim == spec ndim) gain a leading axis of 1."""
        spec_leaves = jax.tree_util.tree_leaves(engine.obs_spec)
        obs_leaves = jax.tree_util.tree_leaves(obs)
        if len(obs_leaves) != len(spec_leaves):
            raise ValueError(
                f"observation pytree has {len(obs_leaves)} leaves, "
                f"slot expects {len(spec_leaves)}"
            )
        ndim = np.ndim(obs_leaves[0])
        spec_ndim = len(spec_leaves[0].shape)
        if ndim == spec_ndim:
            obs = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[None], obs
            )
            return obs, 1, False
        if ndim == spec_ndim + 1:
            obs = jax.tree_util.tree_map(np.asarray, obs)
            return obs, int(obs_leaves[0].shape[0]), True
        raise ValueError(
            f"observation rank {ndim} matches neither the spec rank "
            f"{spec_ndim} (single) nor {spec_ndim + 1} (batched)"
        )

    # ----------------------------------------------------------- dispatch

    def _dispatch_loop(self):
        while True:
            group = self._collect_group()
            if group is None:
                return
            self._run_group(group)

    def _collect_group(self) -> t.List[_Request] | None:
        """Block for the next same-``(slot, deterministic)`` run of
        queued requests: up to ``max_batch`` rows, or whatever is
        queued when the oldest request's deadline expires. ``None``
        means shutdown with an empty queue."""
        with self._nonempty:
            while not self._queue:
                if not self._running:
                    return None
                self._nonempty.wait(timeout=0.05)
            head = self._queue[0]
            deadline = head.t_enq + self.max_wait_s

            def ready_rows():
                rows = 0
                for r in self._queue:
                    if (r.slot, r.deterministic) != (
                        head.slot, head.deterministic
                    ):
                        break
                    rows += r.rows
                return rows

            # A single oversized request flushes immediately (it fills
            # max_batch on its own); otherwise wait for more rows until
            # the head's deadline.
            while self._running and ready_rows() < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._nonempty.wait(timeout=remaining)
            group: t.List[_Request] = []
            rows = 0
            while self._queue:
                r = self._queue[0]
                if (r.slot, r.deterministic) != (head.slot, head.deterministic):
                    break
                if group and rows + r.rows > self.max_batch:
                    break  # next group picks it up (oversized head is
                    # taken alone and chunked by _run_group)
                group.append(self._queue.popleft())
                rows += r.rows
                if rows >= self.max_batch:
                    break
            return group

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _run_group(self, group: t.List[_Request]):
        try:
            engine, params, generation = self.registry.acquire(group[0].slot)
            det = group[0].deterministic
            obs = group[0].obs
            if len(group) > 1:
                obs = jax.tree_util.tree_map(
                    lambda *xs: np.concatenate(xs, axis=0),
                    *[r.obs for r in group],
                )
            total = sum(r.rows for r in group)
            # Chunk and run one padded forward per chunk. The chunk
            # size honors BOTH ceilings: the batcher's max_batch (only
            # an oversized single request exceeds it) and the engine's
            # own max_batch — a slot may be registered with a smaller
            # bucket ladder than the server-wide batcher, and chunks
            # larger than its top bucket would make bucket_for raise.
            chunk_rows = min(self.max_batch, engine.max_batch)
            outs = []
            for lo in range(0, total, chunk_rows):
                chunk = jax.tree_util.tree_map(
                    lambda x, lo=lo: x[lo:lo + chunk_rows], obs
                )
                n = min(chunk_rows, total - lo)
                outs.append(engine.act(
                    params, chunk,
                    None if det else self._next_key(),
                    deterministic=det,
                ))
                self.metrics.record_batch(
                    rows=n, bucket=engine.bucket_for(n)
                )
            action = outs[0] if len(outs) == 1 else np.concatenate(outs, 0)
            done_t = time.perf_counter()
            lo = 0
            for r in group:
                r.future.set_result(
                    ActResult(action[lo:lo + r.rows], generation)
                )
                self.metrics.record_done((done_t - r.t_enq) * 1e3)
                lo += r.rows
        except Exception as e:  # noqa: BLE001 — the dispatcher must
            # survive a bad request/params; every caller sees the error.
            for r in group:
                if not r.future.done():
                    r.future.set_exception(e)
                self.metrics.record_error()

    # -------------------------------------------------------------- admin

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def close(self, timeout: float = 10.0):
        """Stop accepting work, flush everything queued, join the
        dispatcher. Queued requests are answered, never dropped."""
        with self._nonempty:
            self._running = False
            self._nonempty.notify_all()
        self._thread.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
