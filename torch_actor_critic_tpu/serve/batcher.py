"""Thread-safe micro-batching queue in front of the policy engine.

The server-side dynamic-batching pattern (TorchBeast, arXiv:1910.03552;
Podracer, arXiv:2104.06272): concurrent ``act(obs)`` calls land in one
queue, and a single dispatcher thread coalesces them into engine
forwards of up to ``max_batch`` rows — waiting at most ``max_wait_ms``
past the oldest queued request before flushing whatever it has. One
forward per coalesced group amortizes dispatch latency across every
caller in it; the engine pads the group to its bucket shape
(:mod:`~torch_actor_critic_tpu.serve.engine`), and responses are
sliced back per request, so callers never observe the batching.

Grouping rules:

- only requests with the same ``(slot, deterministic)`` share a
  forward (different slots are different params; the deterministic
  flag is a static compile argument);
- a request with more rows than ``max_batch`` is **split** into
  max_batch-sized engine calls and its rows reassembled in order;
- queue order is preserved within a group, and every request —
  including ones drained during shutdown — gets its future resolved:
  nothing is dropped.

Two collection **modes** (docs/SERVING.md "Continuous batching"):

- ``"continuous"`` (default) — admit-into-next-dispatch: whenever the
  engine is free, everything queued is dispatched immediately, up to
  the bucket ladder's top. The *forward itself* is the batching
  window: rows arriving while the engine runs the previous group form
  the next one, so sustained load still fills buckets while a lone
  request at low load pays zero coalescing wait (p50 drops by
  ``max_wait_ms``). Selection is priority-ordered off the PR-5
  deadline metadata — the request nearest its deadline picks the
  ``(slot, deterministic)`` class and orders the group, so
  near-deadline rows preempt batch-filling instead of aging out
  behind deadline-free traffic.
- ``"group"`` — the original boundary-waiting semantics, kept as a
  compat mode and pinned by tests: the dispatcher holds the forming
  group up to ``max_wait_ms`` past the oldest request hoping to fill
  ``max_batch`` rows, strict FIFO within a class.

Responses are **bitwise identical across modes** for deterministic
requests: grouping only changes which padded forward a row rides in,
and the engine's row-wise/batch-shape-invariance guarantee
(:mod:`~torch_actor_critic_tpu.serve.engine`) makes that invisible
(pinned by tests/test_fleet.py).

Each response carries the model **generation** it was computed under
(:mod:`~torch_actor_critic_tpu.serve.registry`): the dispatcher
captures ``(engine, params, generation)`` once per group, so a
hot-reload swap mid-group simply means the group finishes on the old
weights and the next group picks up the new ones.

Admission control (docs/SERVING.md "Overload & degradation"): the
queue is **bounded** (``capacity``) and every request may carry a
deadline. Submit-time rejection — queue full, deadline provably
infeasible at the measured service rate, draining, or the slot's
circuit breaker open — raises a structured
:class:`~torch_actor_critic_tpu.serve.admission.ShedError` instead of
queueing work that cannot be served in time; requests whose deadline
expires *while queued* are purged at group-collection time (futures
failed, never dispatched), so the accelerator only ever runs live
work. The circuit breaker
(:mod:`~torch_actor_critic_tpu.serve.breaker`) is consulted once per
group: open means the whole group fails fast with 503-semantics, and
engine outcomes (success / raised / non-finite actions) feed back into
it.
"""

from __future__ import annotations

import collections
import threading
import time
import typing as t
from concurrent.futures import Future

import jax
import numpy as np

from torch_actor_critic_tpu.serve.admission import (
    BreakerOpenError,
    ShedError,
)
from torch_actor_critic_tpu.serve.metrics import ServeMetrics

__all__ = ["MicroBatcher", "ActResult"]


class ActResult(t.NamedTuple):
    """One resolved ``act`` call: the action rows (leading axis matches
    the request's), the model generation that computed them, and the
    training epoch those params were published at (``None`` for params
    that never came from a checkpoint/publish — e.g. directly-seeded
    test slots). Decoupled actors stamp every transition with these two
    (docs/RESILIENCE.md "Decoupled-plane failure modes"): the epoch is
    the durable staleness key (it survives a serving-worker restart,
    which resets the per-process generation counter)."""

    action: np.ndarray
    generation: int
    epoch: int | None = None


class _Request:
    __slots__ = (
        "obs", "rows", "slot", "deterministic", "future", "t_enq",
        "deadline", "request_id", "t_collect",
    )

    def __init__(
        self, obs, rows, slot, deterministic, deadline_s=None,
        request_id=None,
    ):
        self.obs = obs
        self.rows = rows
        self.slot = slot
        self.deterministic = deterministic
        self.future: Future = Future()
        self.t_enq = time.perf_counter()
        # Correlation id for the per-request trace span and the shed/
        # breaker log lines (the HTTP frontend's X-Request-Id).
        self.request_id = request_id
        self.t_collect: float | None = None
        # Absolute perf_counter deadline; None = the caller will wait
        # forever, so the request can never expire in the queue.
        self.deadline = (
            self.t_enq + deadline_s if deadline_s is not None else None
        )


class MicroBatcher:
    """Coalesces concurrent policy requests into bucketed forwards.

    ``registry`` resolves slot names to ``(engine, params, generation)``
    (:class:`~torch_actor_critic_tpu.serve.registry.ModelRegistry`).
    ``max_batch`` bounds rows per engine call; ``max_wait_ms`` bounds
    the queueing latency added to the OLDEST request in a group (a lone
    request never waits longer than the deadline) — ``"group"`` mode
    only; ``"continuous"`` mode (the default, see the module docstring)
    never waits on a non-empty queue. ``seed`` keys the sampled-action
    PRNG stream. ``capacity`` bounds the number of QUEUED requests —
    the overload backstop: submit past it raises
    :class:`~torch_actor_critic_tpu.serve.admission.ShedError`
    (``queue_full``) instead of growing host memory without bound.
    """

    def __init__(
        self,
        registry,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        metrics: ServeMetrics | None = None,
        seed: int = 0,
        capacity: int = 1024,
        span_log=None,
        mode: str = "continuous",
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if mode not in ("continuous", "group"):
            raise ValueError(
                f"mode must be 'continuous' or 'group', got {mode!r}"
            )
        self.registry = registry
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.capacity = int(capacity)
        self.mode = mode
        self.metrics = metrics if metrics is not None else ServeMetrics()
        # Optional per-request span recording
        # (telemetry.traceview.RequestSpanLog) for the cross-plane
        # trace export: every instrumentation point below is a single
        # `is not None` check when detached — the serving twin of the
        # trainer's telemetry=None contract.
        self.span_log = span_log
        self._key = jax.random.key(seed)  # guarded-by: _lock
        self._queue: collections.deque[_Request] = (  # guarded-by: _lock
            collections.deque()
        )
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        # Measured service rate (EMA of seconds per dispatched row),
        # written by the dispatcher after each group, read under the
        # lock by submit-time deadline-feasibility checks.
        self._ema_row_s: float | None = None  # guarded-by: _lock
        self._ema_samples = 0  # guarded-by: _lock
        # Rows popped off the queue but not yet resolved (the group
        # currently inside the engine). The fleet's least-loaded
        # dispatcher reads load_rows() = queued + in-flight: a replica
        # mid-forward with an empty queue is NOT idle.
        self._inflight_rows = 0  # guarded-by: _lock
        self._running = True  # guarded-by: _lock
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="micro-batcher", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- submit

    def submit(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
        deadline_s: float | None = None,
        request_id: str | None = None,
    ) -> Future:
        """Enqueue one request; returns a Future resolving to
        :class:`ActResult`. ``obs`` is a single observation pytree or a
        batch of them (leading axis); the response's leading axis
        matches the request's.

        ``deadline_s`` is the caller's patience: past it the request is
        worthless, so it is rejected up front when provably infeasible
        at the measured service rate, and purged (future failed, never
        dispatched) if it expires while queued. Admission failures
        raise :class:`~torch_actor_critic_tpu.serve.admission.ShedError`
        with a machine-readable reason. ``request_id`` threads through
        the per-request trace span and shed records so a 429/503 can
        be correlated with its timeline."""
        engine, _, _ = self.registry.acquire(slot)  # validates slot name
        breaker = self.registry.breaker(slot)
        if breaker is not None and not breaker.admits():
            # Fail fast while the slot's engine is tripped open: no
            # queue slot, no accelerator work, a concrete retry hint.
            self.metrics.record_shed("breaker_open")
            self._note_shed(request_id, slot, "breaker_open")
            raise BreakerOpenError(
                slot, breaker.retry_after_s(), breaker.state
            )
        obs, rows, batched = self._ensure_batched(engine, obs)
        req = _Request(
            obs, rows, slot, bool(deterministic), deadline_s,
            request_id=request_id,
        )
        outer: Future = Future()

        def _copy(f: Future):
            err = f.exception()
            if err is not None:
                outer.set_exception(err)
                return
            res: ActResult = f.result()
            action = res.action if batched else res.action[0]
            outer.set_result(ActResult(action, res.generation, res.epoch))

        req.future.add_done_callback(_copy)
        with self._nonempty:
            # Checked under the lock: a request enqueued after close()
            # flipped the flag would never be drained.
            if not self._running:
                raise ShedError(
                    "draining",
                    "MicroBatcher is closed (draining); not accepting "
                    "new requests",
                )
            if len(self._queue) >= self.capacity:
                self.metrics.record_shed("queue_full")
                self._note_shed(request_id, slot, "queue_full")
                raise ShedError(
                    "queue_full",
                    f"admission queue is at capacity "
                    f"({self.capacity} requests); retry with backoff",
                    retry_after_s=self._est_backlog_wait_locked() or 1.0,
                    detail={
                        "queue_depth": len(self._queue),
                        "capacity": self.capacity,
                    },
                )
            if deadline_s is not None and self._ema_samples >= 3:
                est_wait = (
                    sum(r.rows for r in self._queue) + rows
                ) * self._ema_row_s
                if est_wait > deadline_s:
                    self.metrics.record_shed("deadline_infeasible")
                    self._note_shed(request_id, slot, "deadline_infeasible")
                    raise ShedError(
                        "deadline_infeasible",
                        f"deadline of {deadline_s:.3f}s cannot be met: "
                        f"estimated completion {est_wait:.3f}s at the "
                        "current service rate; shedding instead of "
                        "serving a dead request",
                        retry_after_s=est_wait,
                        detail={"estimated_wait_s": round(est_wait, 4)},
                    )
            self._queue.append(req)
            self.metrics.record_enqueue(len(self._queue))
            self._nonempty.notify()
        return outer

    def act(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
        timeout: float | None = 30.0,
        request_id: str | None = None,
    ) -> ActResult:
        """Blocking :meth:`submit`. The timeout doubles as the request
        deadline: a caller that stops waiting leaves no orphan behind —
        its queued request is purged at group-collection time instead
        of burning a forward on an answer nobody reads."""
        return self.submit(
            obs, deterministic, slot, deadline_s=timeout,
            request_id=request_id,
        ).result(timeout=timeout)

    def _note_shed(self, request_id, slot, reason):
        """One submit-time shed into the span log (when attached): the
        rejection appears on the same timeline as the requests that
        were served, under its correlation id."""
        if self.span_log is None:
            return
        now = time.perf_counter()
        self.span_log.record({
            "request_id": request_id, "slot": slot, "rows": 0,
            "t_enq": now, "t_done": now, "outcome": reason,
        })

    def _est_backlog_wait_locked(self) -> float | None:
        """Estimated seconds to drain the current queue (None until the
        service-rate EMA has warmed up). Callers hold ``self._lock``."""
        if self._ema_row_s is None:
            return None
        return sum(r.rows for r in self._queue) * self._ema_row_s

    def _ensure_batched(self, engine, obs):
        """(batched_obs, n_rows, was_batched) — unbatched observations
        (leaf ndim == spec ndim) gain a leading axis of 1."""
        spec_leaves = jax.tree_util.tree_leaves(engine.obs_spec)
        obs_leaves = jax.tree_util.tree_leaves(obs)
        if len(obs_leaves) != len(spec_leaves):
            raise ValueError(
                f"observation pytree has {len(obs_leaves)} leaves, "
                f"slot expects {len(spec_leaves)}"
            )
        ndim = np.ndim(obs_leaves[0])
        spec_ndim = len(spec_leaves[0].shape)
        if ndim == spec_ndim:
            obs = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[None], obs
            )
            return obs, 1, False
        if ndim == spec_ndim + 1:
            obs = jax.tree_util.tree_map(np.asarray, obs)
            return obs, int(obs_leaves[0].shape[0]), True
        raise ValueError(
            f"observation rank {ndim} matches neither the spec rank "
            f"{spec_ndim} (single) nor {spec_ndim + 1} (batched)"
        )

    # ----------------------------------------------------------- dispatch

    def _dispatch_loop(self):
        while True:
            group = self._collect_group()
            if group is None:
                return
            if group:  # may be empty when every queued request expired
                try:
                    self._run_group(group)
                finally:
                    with self._lock:
                        self._inflight_rows -= sum(r.rows for r in group)

    def _purge_expired_locked(self) -> None:
        """Fail and drop every queued request whose deadline has
        passed — the satellite fix for the timed-out-client leak: an
        abandoned ``act()`` used to stay queued and still burn a TPU
        forward on an answer nobody reads. Purged requests never reach
        the engine; counted as ``shed_expired_total``. Callers hold
        ``self._lock``."""
        if not any(r.deadline is not None for r in self._queue):
            return
        now = time.perf_counter()
        expired = [
            r for r in self._queue
            if r.deadline is not None and now >= r.deadline
        ]
        if not expired:
            return
        live = [r for r in self._queue if r not in expired]
        self._queue.clear()
        self._queue.extend(live)
        self.metrics.record_expired(len(expired))
        for r in expired:
            if self.span_log is not None:
                self.span_log.record({
                    "request_id": r.request_id, "slot": r.slot,
                    "rows": r.rows, "t_enq": r.t_enq, "t_done": now,
                    "outcome": "expired",
                })
            if not r.future.done():
                r.future.set_exception(ShedError(
                    "expired",
                    f"request deadline passed after "
                    f"{now - r.t_enq:.3f}s in queue; purged before "
                    "dispatch",
                ))

    def _collect_group(self) -> t.List[_Request] | None:
        """Block for the next dispatchable same-``(slot,
        deterministic)`` group of queued requests — boundary-waiting in
        ``"group"`` mode, immediate in ``"continuous"`` mode. Expired
        requests are purged here — group-collection time — so the
        engine only ever runs live work. ``None`` means shutdown with
        an empty queue; an empty list means everything queued had
        expired."""
        with self._nonempty:
            while True:
                self._purge_expired_locked()
                if self._queue:
                    break
                if not self._running:
                    return None
                self._nonempty.wait(timeout=0.05)
            if self.mode == "continuous":
                group = self._collect_continuous_locked()
            else:
                group = self._collect_boundary_locked()
            if group:
                self._inflight_rows += sum(r.rows for r in group)
                if self.span_log is not None:
                    t_collect = time.perf_counter()
                    for r in group:
                        r.t_collect = t_collect
            return group

    @staticmethod
    def _urgency(r: _Request) -> t.Tuple[bool, float, float]:
        """Priority key: earliest deadline first; deadline-free
        requests after every deadlined one, FIFO among themselves."""
        return (r.deadline is None, r.deadline or 0.0, r.t_enq)

    def _collect_continuous_locked(self) -> t.List[_Request]:
        """Admit-into-next-dispatch: take everything queued for the
        most urgent request's ``(slot, deterministic)`` class — most
        urgent first — up to ``max_batch`` rows, with NO coalescing
        wait. The engine's forward time is the batching window: rows
        that arrived while the previous group ran ride this one, a
        lone request at low load dispatches immediately, and a
        near-deadline request preempts batch-filling by deadline-free
        traffic. Callers hold ``self._lock``."""
        head = min(self._queue, key=self._urgency)
        cls = (head.slot, head.deterministic)
        candidates = sorted(
            (r for r in self._queue
             if (r.slot, r.deterministic) == cls),
            key=self._urgency,
        )
        group: t.List[_Request] = []
        rows = 0
        for r in candidates:
            if group and rows + r.rows > self.max_batch:
                break  # a later dispatch picks it up (an oversized
                # head is taken alone and chunked by _run_group)
            group.append(r)
            rows += r.rows
            if rows >= self.max_batch:
                break
        taken = {id(r) for r in group}
        live = [r for r in self._queue if id(r) not in taken]
        self._queue.clear()
        self._queue.extend(live)
        return group

    def _collect_boundary_locked(self) -> t.List[_Request]:
        """The compat ``"group"`` mode: hold the forming group up to
        ``max_wait_ms`` past the oldest request hoping to fill
        ``max_batch`` rows; strict FIFO within the head's class.
        Callers hold ``self._lock``."""
        head = self._queue[0]
        deadline = head.t_enq + self.max_wait_s

        def ready_rows():
            rows = 0
            for r in self._queue:
                if (r.slot, r.deterministic) != (
                    head.slot, head.deterministic
                ):
                    break
                rows += r.rows
            return rows

        # A single oversized request flushes immediately (it fills
        # max_batch on its own); otherwise wait for more rows until
        # the head's deadline.
        while self._running and ready_rows() < self.max_batch:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            self._nonempty.wait(timeout=remaining)
        # Final purge before dispatch: whatever expired during the
        # coalescing wait is failed now, never forwarded.
        self._purge_expired_locked()
        if not self._queue:
            return []
        head = self._queue[0]  # the purge may have changed the head
        group: t.List[_Request] = []
        rows = 0
        while self._queue:
            r = self._queue[0]
            if (r.slot, r.deterministic) != (head.slot, head.deterministic):
                break
            if group and rows + r.rows > self.max_batch:
                break  # next group picks it up (oversized head is
                # taken alone and chunked by _run_group)
            group.append(self._queue.popleft())
            rows += r.rows
            if rows >= self.max_batch:
                break
        return group

    def _next_key(self):
        # Under the lock: the dispatcher splitting here races
        # import_key() restoring a checkpointed key on the learner
        # thread (decoupled resume) — an unlocked split could clobber
        # the restored stream. Found by tac-lint (unguarded-shared-attr).
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub

    def _slot_epoch(self, slot_name: str) -> int | None:
        """The slot's published training epoch, when the registry
        exposes one (``ModelRegistry.epoch_of``). Read next to
        ``acquire`` rather than inside it so the registry interface
        stays duck-type compatible with older views; a swap landing
        between the two reads can mis-stamp at most one group by one
        publish — and the decoupled driver acts and publishes on one
        thread, where the race cannot occur."""
        epoch_of = getattr(self.registry, "epoch_of", None)
        if epoch_of is None:
            return None
        try:
            return epoch_of(slot_name)
        except Exception:  # noqa: BLE001 — stamping must never fail a group
            return None

    # --------------------------------------------------- sampled-key state

    def export_key(self) -> list:
        """The sampled-action PRNG key as raw uint32 data (JSON-ready).
        The decoupled learner checkpoints this next to the trainer's
        acting key so a resumed run's exploration stream continues
        bitwise through the serving plane (docs/RESILIENCE.md)."""
        with self._lock:
            return (
                np.asarray(jax.random.key_data(self._key))
                .astype(np.uint32).tolist()
            )

    def import_key(self, data) -> None:
        """Restore the sampled-action PRNG key from :meth:`export_key`
        output."""
        key = jax.random.wrap_key_data(np.asarray(data, dtype=np.uint32))
        with self._lock:
            self._key = key

    def _run_group(self, group: t.List[_Request]):
        slot_name = group[0].slot
        breaker = self.registry.breaker(slot_name)
        if breaker is not None and not breaker.allow():
            # Tripped (or half-open past its probe quota): queued
            # requests for the slot fail fast — no engine work at all.
            err = BreakerOpenError(
                slot_name, breaker.retry_after_s(), breaker.state
            )
            now = time.perf_counter()
            for r in group:
                if not r.future.done():
                    r.future.set_exception(err)
                self.metrics.record_shed("breaker_open")
                if self.span_log is not None:
                    self.span_log.record({
                        "request_id": r.request_id, "slot": r.slot,
                        "rows": r.rows, "t_enq": r.t_enq,
                        "t_collect": r.t_collect, "t_done": now,
                        "outcome": "breaker_open",
                    })
            return
        try:
            engine, params, generation = self.registry.acquire(slot_name)
            epoch = self._slot_epoch(slot_name)
            det = group[0].deterministic
            obs = group[0].obs
            if len(group) > 1:
                obs = jax.tree_util.tree_map(
                    lambda *xs: np.concatenate(xs, axis=0),
                    *[r.obs for r in group],
                )
            total = sum(r.rows for r in group)
            # Chunk and run one padded forward per chunk. The chunk
            # size honors BOTH ceilings: the batcher's max_batch (only
            # an oversized single request exceeds it) and the engine's
            # own max_batch — a slot may be registered with a smaller
            # bucket ladder than the server-wide batcher, and chunks
            # larger than its top bucket would make bucket_for raise.
            chunk_rows = min(self.max_batch, engine.max_batch)
            outs = []
            group_bucket = engine.bucket_for(min(chunk_rows, total))
            t_fwd = time.perf_counter()
            for lo in range(0, total, chunk_rows):
                chunk = jax.tree_util.tree_map(
                    lambda x, lo=lo: x[lo:lo + chunk_rows], obs
                )
                n = min(chunk_rows, total - lo)
                t_chunk = time.perf_counter()
                outs.append(engine.act(
                    params, chunk,
                    None if det else self._next_key(),
                    deterministic=det,
                ))
                # The measured duration feeds the per-bucket roofline
                # on /metrics `costs` (serve/metrics.cost_snapshot).
                self.metrics.record_batch(
                    rows=n, bucket=engine.bucket_for(n),
                    dur_s=time.perf_counter() - t_chunk,
                )
            t_fwd_end = time.perf_counter()
            self._note_service_rate(t_fwd_end - t_fwd, total)
            action = outs[0] if len(outs) == 1 else np.concatenate(outs, 0)
            done_t = time.perf_counter()
            lo = 0
            for r in group:
                r.future.set_result(
                    ActResult(action[lo:lo + r.rows], generation, epoch)
                )
                self.metrics.record_done((done_t - r.t_enq) * 1e3)
                lo += r.rows
                if self.span_log is not None:
                    self.span_log.record({
                        "request_id": r.request_id, "slot": r.slot,
                        "rows": r.rows, "bucket": group_bucket,
                        "generation": generation, "t_enq": r.t_enq,
                        "t_collect": r.t_collect, "t_dispatch": t_fwd,
                        "t_forward_end": t_fwd_end, "t_done": done_t,
                        "outcome": "ok",
                    })
            if breaker is not None:
                breaker.record_success()
        except Exception as e:  # noqa: BLE001 — the dispatcher must
            # survive a bad request/params; every caller sees the error.
            if breaker is not None and not isinstance(
                e, (KeyError, ValueError, TypeError)
            ):
                # Engine health, not request shape: forwards that raise
                # and non-finite action outputs count toward the trip
                # threshold; malformed requests / unknown slots do not.
                breaker.record_failure(e)
            now = time.perf_counter()
            for r in group:
                if not r.future.done():
                    r.future.set_exception(e)
                self.metrics.record_error()
                if self.span_log is not None:
                    self.span_log.record({
                        "request_id": r.request_id, "slot": r.slot,
                        "rows": r.rows, "t_enq": r.t_enq,
                        "t_collect": r.t_collect, "t_done": now,
                        "outcome": "error",
                    })

    def _note_service_rate(self, dt_s: float, rows: int):
        """Fold one group's measured seconds-per-row into the EMA the
        submit-time deadline-feasibility check reads."""
        if rows <= 0 or dt_s <= 0:
            return
        per_row = dt_s / rows
        with self._lock:
            self._ema_row_s = (
                per_row if self._ema_row_s is None
                else 0.8 * self._ema_row_s + 0.2 * per_row
            )
            self._ema_samples += 1

    # -------------------------------------------------------------- admin

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def load_rows(self) -> int:
        """Queued + in-flight rows — the backlog the engine still owes.
        The fleet's least-loaded dispatcher scores replicas by
        ``load_rows() x ema_row_s`` (estimated seconds to clear)."""
        with self._lock:
            return sum(r.rows for r in self._queue) + self._inflight_rows

    @property
    def ema_row_s(self) -> float | None:
        """Measured seconds-per-row EMA (None until the first group)."""
        with self._lock:
            return self._ema_row_s

    def close(self, timeout: float = 10.0):
        """Stop accepting work, flush everything queued, join the
        dispatcher. Queued requests are answered, never dropped."""
        with self._nonempty:
            self._running = False
            self._nonempty.notify_all()
        self._thread.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
