"""Engine-per-device replication behind one shared admission layer.

One serving process used to drive ONE device: the batcher's single
dispatcher thread fed a single :class:`PolicyEngine`, and every other
local chip idled. :class:`EngineFleet` closes that gap (ROADMAP item 3a,
the Sebulba/TorchBeast decoupling applied to inference): it builds one
engine **replica per local device** — its own bucketed jit cache, its
own params copy placed on that device, its own dispatcher thread — and
routes every submit through a **least-loaded dispatcher** so all
devices stay saturated under concurrent traffic.

Layering (everything below the fleet is the existing single-device
stack, unchanged):

- **Replica** = ``(device, per-device registry view, MicroBatcher)``.
  The registry view (:class:`_ReplicaRegistry`) satisfies the exact
  interface the batcher already consumes (``acquire``/``breaker``), so
  each replica IS a complete single-device serving stack; the fleet
  only decides which one a request joins.
- **Params placement is generation-keyed**: ``acquire`` compares the
  shared registry's generation against the replica's cached copy and
  re-places on change — a hot-reload swap in the shared registry
  propagates to every device on its next dispatch, no fleet-aware
  reload plumbing needed.
- **Least-loaded dispatch**: score = ``load_rows() x ema_row_s`` —
  queued + in-flight rows times the replica's own measured
  seconds-per-row EMA, i.e. estimated seconds until the replica could
  run the new request. Ties (all idle) break round-robin so bursts
  spread instead of piling on replica 0.
- **Health gating**: each replica owns its OWN per-slot circuit
  breaker (a device can fail alone); the dispatcher skips replicas
  whose breaker for the requested slot does not admit, which ejects a
  sick device from rotation and re-admits it when its half-open probe
  succeeds. Only when EVERY replica is open does the fleet shed with
  :class:`~torch_actor_critic_tpu.serve.admission.BreakerOpenError`.
- **Shared admission**: one fleet-wide ``capacity`` bound over the sum
  of replica queues (checked atomically with routing under the fleet
  lock), one shared :class:`ServeMetrics`, one deadline vocabulary —
  clients observe a single service, N times wider.

Provable on CPU: tests force ``--xla_force_host_platform_device_count``
so replicas land on distinct (virtual) devices and XLA runs each
replica's forwards on its own device buffers (docs/SERVING.md "Fleet").
"""

from __future__ import annotations

import threading
import typing as t
from concurrent.futures import Future

import jax

from torch_actor_critic_tpu.serve.admission import (
    BreakerOpenError,
    ShedError,
)
from torch_actor_critic_tpu.serve.batcher import ActResult, MicroBatcher
from torch_actor_critic_tpu.serve.breaker import CircuitBreaker
from torch_actor_critic_tpu.serve.engine import PolicyEngine
from torch_actor_critic_tpu.serve.metrics import ServeMetrics

__all__ = ["EngineFleet"]

# Pessimistic seconds-per-row placeholder while a replica's EMA warms
# up (first group not yet measured). Deliberately LARGE: a replica
# with backlog whose service rate is unknown (its first group never
# came back — possibly wedged) yields to any idle or measured-fast
# peer, while a fully idle cold fleet still spreads round-robin
# (0 rows x anything = 0).
_DEFAULT_ROW_S = 1.0


class _ReplicaRegistry:
    """A per-device view over the shared :class:`ModelRegistry`.

    Presents the registry interface the batcher consumes, but
    ``acquire`` answers with THIS device's engine replica and a
    device-placed params copy (cached, re-placed when the shared
    slot's generation moves), and ``breaker`` answers with this
    replica's own per-slot breaker. Slot validation, hot-reload and
    checkpoint plumbing all stay in the one shared registry.
    """

    def __init__(self, base, device, index: int):
        self._base = base
        self.device = device
        self.index = index
        self._engines: t.Dict[str, PolicyEngine] = {}  # guarded-by: _lock
        self._params: t.Dict[str, t.Tuple[int, t.Any]] = (  # guarded-by: _lock
            {}
        )
        self._breakers: t.Dict[str, CircuitBreaker] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def acquire(self, name: str = "default"):
        base_engine, params, generation = self._base.acquire(name)
        with self._lock:
            engine = self._engines.get(name)
            if engine is None:
                engine = base_engine.replicate()
                self._engines[name] = engine
            cached = self._params.get(name)
            if cached is None or cached[0] != generation:
                # One transfer per hot-reload per device, performed
                # lazily on the replica's next dispatch — never on the
                # reload path itself (reload latency stays O(1 restore),
                # not O(devices)).
                placed = jax.device_put(params, self.device)
                self._params[name] = (generation, placed)
            return engine, self._params[name][1], generation

    def epoch_of(self, name: str = "default") -> int | None:
        """Epoch stamping delegates to the shared registry — every
        replica serves the same published params, so they share one
        staleness key."""
        return self._base.epoch_of(name)

    def breaker(self, name: str = "default") -> CircuitBreaker | None:
        base = self._base.breaker(name)
        if base is None:
            return None
        with self._lock:
            b = self._breakers.get(name)
            if b is None:
                # Same thresholds/clock as the slot's shared breaker,
                # but per-replica state: one sick device trips ITS
                # breaker and leaves the others serving. Events route
                # into the shared registry's bounded log, tagged with
                # the replica.
                b = CircuitBreaker(
                    fail_threshold=base.fail_threshold,
                    cooldown_s=base.cooldown_s,
                    probe_quota=base.probe_quota,
                    clock=base._clock,
                    name=f"{name}@r{self.index}",
                )
                b.on_event = lambda ev: self._base.note_breaker_event(
                    dict(ev, slot=name, replica=self.index)
                )
                self._breakers[name] = b
            return b

    def warmup(self, name: str = "default", **kwargs) -> list:
        engine, params, _ = self.acquire(name)
        return engine.warmup(params, **kwargs)

    def breaker_stats(self) -> dict:
        with self._lock:
            return {
                name: b.snapshot() for name, b in self._breakers.items()
            }

    def compile_stats(self) -> dict:
        with self._lock:
            engines = dict(self._engines)
        return {name: e.compile_stats() for name, e in engines.items()}


class _Replica:
    __slots__ = ("index", "device", "registry", "batcher", "dispatched")

    def __init__(self, index, device, registry, batcher):
        self.index = index
        self.device = device
        self.registry = registry
        self.batcher = batcher
        self.dispatched = 0  # requests routed here (fleet-lock guarded)


class EngineFleet:
    """N single-device serving stacks behind one admission layer.

    Duck-types the :class:`MicroBatcher` surface the server and
    clients consume (``submit``/``act``/``queue_depth``/``close``/
    ``capacity``/``metrics``/``mode``), so
    :class:`~torch_actor_critic_tpu.serve.server.PolicyServer` drives
    a fleet exactly as it drives one batcher.

    ``devices`` defaults to every local device; pass an explicit list
    (tests pin replicas to forced CPU devices) or an int to take the
    first N. ``capacity`` is fleet-wide: the bound applies to the SUM
    of replica queues, checked atomically with routing.
    """

    def __init__(
        self,
        registry,
        devices: t.Sequence | int | None = None,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        metrics: ServeMetrics | None = None,
        seed: int = 0,
        capacity: int = 1024,
        span_log=None,
        mode: str = "continuous",
    ):
        if isinstance(devices, int):
            devices = jax.local_devices()[:devices]
        devices = list(devices if devices is not None else jax.local_devices())
        if not devices:
            raise ValueError("EngineFleet needs at least one device")
        self.registry = registry
        self.capacity = int(capacity)
        self.max_batch = int(max_batch)
        self.mode = mode
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.span_log = span_log
        self._lock = threading.Lock()
        self._rr = 0  # round-robin cursor for idle ties; guarded-by: _lock
        self._running = True  # guarded-by: _lock
        # _replicas is append-only during __init__ and immutable after
        # (replica-internal state has its own locks), so reads are safe
        # anywhere.
        self._replicas = []
        for i, dev in enumerate(devices):
            view = _ReplicaRegistry(registry, dev, i)
            batcher = MicroBatcher(
                view, max_batch=max_batch, max_wait_ms=max_wait_ms,
                metrics=self.metrics, seed=seed * 7919 + i,
                capacity=capacity, span_log=span_log, mode=mode,
            )
            self._replicas.append(_Replica(i, dev, view, batcher))

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def warmup(
        self, slots: t.Sequence[str] | None = None, **kwargs
    ) -> dict:
        """Compile every replica's buckets for ``slots`` (default: all
        registered) so no live request pays a per-device compile."""
        if slots is None:
            slots = list(self.registry.slots())
        out = {}
        for rep in self._replicas:
            out[f"r{rep.index}"] = {
                s: len(rep.registry.warmup(s, **kwargs)) for s in slots
            }
        return out

    # ------------------------------------------------------------- routing

    def _pick_locked(self, slot: str):
        """Least-loaded admitting replica, or None when every
        replica's breaker for ``slot`` is refusing traffic."""
        n = len(self._replicas)
        best, best_score = None, None
        for off in range(n):
            rep = self._replicas[(self._rr + off) % n]
            br = rep.registry.breaker(slot)
            if br is not None and not br.admits():
                continue  # health gate: breaker-open replica is out
                # of rotation until its half-open probe re-admits it
            ema = rep.batcher.ema_row_s
            score = rep.batcher.load_rows() * (
                ema if ema is not None else _DEFAULT_ROW_S
            )
            if best_score is None or score < best_score:
                best, best_score = rep, score
        if best is not None:
            self._rr = (best.index + 1) % n
        return best

    def submit(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
        deadline_s: float | None = None,
        request_id: str | None = None,
    ) -> Future:
        """Route one request to the least-loaded healthy replica;
        returns that replica's batcher Future. Admission failures
        raise the same structured
        :class:`~torch_actor_critic_tpu.serve.admission.ShedError`
        vocabulary as the single-device batcher."""
        with self._lock:
            if not self._running:
                raise ShedError(
                    "draining",
                    "EngineFleet is closed (draining); not accepting "
                    "new requests",
                )
            total = sum(
                rep.batcher.queue_depth() for rep in self._replicas
            )
            if total >= self.capacity:
                self.metrics.record_shed("queue_full")
                raise ShedError(
                    "queue_full",
                    f"fleet admission queue is at capacity "
                    f"({self.capacity} requests across "
                    f"{len(self._replicas)} replicas); retry with "
                    "backoff",
                    retry_after_s=1.0,
                    detail={
                        "queue_depth": total, "capacity": self.capacity,
                    },
                )
            rep = self._pick_locked(slot)
            if rep is None:
                # Every replica's breaker is open: the fleet-level 503.
                brs = [
                    r.registry.breaker(slot) for r in self._replicas
                ]
                retry = min(
                    (b.retry_after_s() for b in brs if b is not None),
                    default=1.0,
                )
                self.metrics.record_shed("breaker_open")
                raise BreakerOpenError(slot, retry, "open")
            rep.dispatched += 1
            # Submit under the fleet lock so capacity-check + route +
            # enqueue are atomic (an enqueue is cheap; forwards happen
            # on the replicas' own dispatcher threads).
            return rep.batcher.submit(
                obs, deterministic, slot, deadline_s=deadline_s,
                request_id=request_id,
            )

    def act(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
        timeout: float | None = 30.0,
        request_id: str | None = None,
    ) -> ActResult:
        """Blocking :meth:`submit`; the timeout doubles as the request
        deadline, exactly as the single-device batcher."""
        return self.submit(
            obs, deterministic, slot, deadline_s=timeout,
            request_id=request_id,
        ).result(timeout=timeout)

    # --------------------------------------------------------------- admin

    def queue_depth(self) -> int:
        return sum(rep.batcher.queue_depth() for rep in self._replicas)

    def load_rows(self) -> int:
        return sum(rep.batcher.load_rows() for rep in self._replicas)

    def replica_stats(self) -> t.List[dict]:
        """Per-replica view for ``/metrics`` ``fleet``: device, load,
        measured service rate, routed-request share, breaker states."""
        out = []
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            ema = rep.batcher.ema_row_s
            out.append({
                "replica": rep.index,
                "device": str(rep.device),
                "queue_depth": rep.batcher.queue_depth(),
                "load_rows": rep.batcher.load_rows(),
                "ema_row_s": round(ema, 6) if ema is not None else None,
                "dispatched_total": rep.dispatched,
                "breakers": {
                    name: s["state"]
                    for name, s in rep.registry.breaker_stats().items()
                },
            })
        return out

    def compile_stats(self) -> dict:
        """Per-replica engine compile accounting (the fleet twin of
        ``ModelRegistry.compile_stats``)."""
        reps = {
            f"r{rep.index}": rep.registry.compile_stats()
            for rep in self._replicas
        }
        totals = [
            s for per in reps.values() for s in per.values()
        ]
        return {
            "compiles_total": sum(s["compiles_total"] for s in totals),
            "live_compiles": sum(s["live_compiles"] for s in totals),
            "replicas": reps,
        }

    def close(self, timeout: float = 10.0):
        """Stop admitting, then flush every replica's queue through
        its engine (the batcher close contract, N times)."""
        with self._lock:
            self._running = False
        for rep in self._replicas:
            rep.batcher.close(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
