"""Engine-per-device replication behind one shared admission layer.

One serving process used to drive ONE device: the batcher's single
dispatcher thread fed a single :class:`PolicyEngine`, and every other
local chip idled. :class:`EngineFleet` closes that gap (ROADMAP item 3a,
the Sebulba/TorchBeast decoupling applied to inference): it builds one
engine **replica per local device** — its own bucketed jit cache, its
own params copy placed on that device, its own dispatcher thread — and
routes every submit through a **least-loaded dispatcher** so all
devices stay saturated under concurrent traffic.

Layering (everything below the fleet is the existing single-device
stack, unchanged):

- **Replica** = ``(device, per-device registry view, MicroBatcher)``.
  The registry view (:class:`_ReplicaRegistry`) satisfies the exact
  interface the batcher already consumes (``acquire``/``breaker``), so
  each replica IS a complete single-device serving stack; the fleet
  only decides which one a request joins.
- **Params placement is generation-keyed**: ``acquire`` compares the
  shared registry's generation against the replica's cached copy and
  re-places on change — a hot-reload swap in the shared registry
  propagates to every device on its next dispatch, no fleet-aware
  reload plumbing needed.
- **Least-loaded dispatch**: score = ``load_rows() x ema_row_s`` —
  queued + in-flight rows times the replica's own measured
  seconds-per-row EMA, i.e. estimated seconds until the replica could
  run the new request. Ties (all idle) break round-robin so bursts
  spread instead of piling on replica 0.
- **Health gating**: each replica owns its OWN per-slot circuit
  breaker (a device can fail alone); the dispatcher skips replicas
  whose breaker for the requested slot does not admit, which ejects a
  sick device from rotation and re-admits it when its half-open probe
  succeeds. Only when EVERY replica is open does the fleet shed with
  :class:`~torch_actor_critic_tpu.serve.admission.BreakerOpenError`.
- **Shared admission**: one fleet-wide ``capacity`` bound over the sum
  of replica queues (checked atomically with routing under the fleet
  lock), one shared :class:`ServeMetrics`, one deadline vocabulary —
  clients observe a single service, N times wider.

Provable on CPU: tests force ``--xla_force_host_platform_device_count``
so replicas land on distinct (virtual) devices and XLA runs each
replica's forwards on its own device buffers (docs/SERVING.md "Fleet").
"""

from __future__ import annotations

import threading
import typing as t
from concurrent.futures import Future

import jax

from torch_actor_critic_tpu.serve.admission import (
    BreakerOpenError,
    ShedError,
)
from torch_actor_critic_tpu.serve.batcher import ActResult, MicroBatcher
from torch_actor_critic_tpu.serve.breaker import CircuitBreaker
from torch_actor_critic_tpu.serve.engine import PolicyEngine
from torch_actor_critic_tpu.serve.metrics import ServeMetrics

__all__ = ["EngineFleet"]

# Pessimistic seconds-per-row placeholder while a replica's EMA warms
# up (first group not yet measured). Deliberately LARGE: a replica
# with backlog whose service rate is unknown (its first group never
# came back — possibly wedged) yields to any idle or measured-fast
# peer, while a fully idle cold fleet still spreads round-robin
# (0 rows x anything = 0).
_DEFAULT_ROW_S = 1.0


class _ReplicaRegistry:
    """A per-device view over the shared :class:`ModelRegistry`.

    Presents the registry interface the batcher consumes, but
    ``acquire`` answers with THIS device's engine replica and a
    device-placed params copy (cached, re-placed when the shared
    slot's generation moves), and ``breaker`` answers with this
    replica's own per-slot breaker. Slot validation, hot-reload and
    checkpoint plumbing all stay in the one shared registry.
    """

    def __init__(self, base, device, index: int, metrics=None):
        self._base = base
        self.device = device
        self.index = index
        self.metrics = metrics
        self._engines: t.Dict[str, PolicyEngine] = {}  # guarded-by: _lock
        # name -> (generation, precision, placed): keyed on BOTH so a
        # precision-tier change invalidates cached placements instead
        # of serving stale-dtype params (a reload bumps the generation,
        # a tier flip bumps the precision — either way the cache
        # misses and the params are re-prepared + re-placed).
        self._params: t.Dict[
            str, t.Tuple[int, str, t.Any]
        ] = {}  # guarded-by: _lock
        self._breakers: t.Dict[str, CircuitBreaker] = {}  # guarded-by: _lock
        # Placement accounting for /metrics `sharding`: every
        # device_put's actual bytes, totalled per replica.
        self.transfer_bytes_total = 0  # guarded-by: _lock
        self.last_transfer_bytes = 0  # guarded-by: _lock
        self.placements_total = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    def _new_engine(self, base_engine: PolicyEngine) -> PolicyEngine:
        """This replica's engine for a slot — a fresh single-device
        twin of the shared slot engine (the sub-mesh view overrides
        with a :class:`ShardedPolicyEngine` on its mesh)."""
        return base_engine.replicate()

    def _place(self, engine: PolicyEngine, params) -> t.Tuple[t.Any, int]:
        """Place one slot's params for this replica; returns
        ``(placed, transferred_bytes)``."""
        placed = jax.device_put(params, self.device)
        nbytes = int(sum(
            getattr(leaf, "nbytes", 0)
            for leaf in jax.tree_util.tree_leaves(placed)
        ))
        return placed, nbytes

    def acquire(self, name: str = "default"):
        base_engine, params, generation = self._base.acquire(name)
        with self._lock:
            engine = self._engines.get(name)
            if engine is None:
                engine = self._new_engine(base_engine)
                self._engines[name] = engine
            cached = self._params.get(name)
            if cached is None or cached[:2] != (generation, engine.precision):
                # One transfer per hot-reload per device, performed
                # lazily on the replica's next dispatch — never on the
                # reload path itself (reload latency stays O(1 restore),
                # not O(devices)).
                placed, nbytes = self._place(engine, params)
                self._params[name] = (generation, engine.precision, placed)
                self.transfer_bytes_total += nbytes
                self.last_transfer_bytes = nbytes
                self.placements_total += 1
                if self.metrics is not None:
                    self.metrics.record_transfer(nbytes)
            return engine, self._params[name][2], generation

    def epoch_of(self, name: str = "default") -> int | None:
        """Epoch stamping delegates to the shared registry — every
        replica serves the same published params, so they share one
        staleness key."""
        return self._base.epoch_of(name)

    def breaker(self, name: str = "default") -> CircuitBreaker | None:
        base = self._base.breaker(name)
        if base is None:
            return None
        with self._lock:
            b = self._breakers.get(name)
            if b is None:
                # Same thresholds/clock as the slot's shared breaker,
                # but per-replica state: one sick device trips ITS
                # breaker and leaves the others serving. Events route
                # into the shared registry's bounded log, tagged with
                # the replica.
                b = CircuitBreaker(
                    fail_threshold=base.fail_threshold,
                    cooldown_s=base.cooldown_s,
                    probe_quota=base.probe_quota,
                    clock=base._clock,
                    name=f"{name}@r{self.index}",
                )
                b.on_event = lambda ev: self._base.note_breaker_event(
                    dict(ev, slot=name, replica=self.index)
                )
                self._breakers[name] = b
            return b

    def warmup(self, name: str = "default", **kwargs) -> list:
        engine, params, _ = self.acquire(name)
        return engine.warmup(params, **kwargs)

    def breaker_stats(self) -> dict:
        with self._lock:
            return {
                name: b.snapshot() for name, b in self._breakers.items()
            }

    def compile_stats(self) -> dict:
        with self._lock:
            engines = dict(self._engines)
        return {name: e.compile_stats() for name, e in engines.items()}

    def transfer_stats(self) -> dict:
        """Placement accounting for the ``/metrics`` ``sharding``
        section: cumulative + last-reload transfer bytes and how many
        placements (generation or precision changes) this replica has
        performed."""
        with self._lock:
            return {
                "transfer_bytes_total": self.transfer_bytes_total,
                "last_transfer_bytes": self.last_transfer_bytes,
                "placements_total": self.placements_total,
            }


class _SubmeshReplicaRegistry(_ReplicaRegistry):
    """A per-SUB-MESH view over the shared registry: the replica's
    engine is a :class:`~torch_actor_critic_tpu.serve.sharded.
    ShardedPolicyEngine` over its own ``(tp, fsdp)`` mesh, and params
    placement is the engine's prepare (int8 quantization at reload
    time) + sharded ``device_put`` — each device of the sub-mesh
    receives exactly its shards, still one transfer per device per
    generation."""

    def __init__(
        self, base, mesh, index: int, precision: str = "f32",
        fsdp_min_bytes: int | None = None, metrics=None,
    ):
        super().__init__(base, device=mesh, index=index, metrics=metrics)
        self.mesh = mesh
        self.precision = precision
        self.fsdp_min_bytes = fsdp_min_bytes

    def _new_engine(self, base_engine: PolicyEngine) -> PolicyEngine:
        from torch_actor_critic_tpu.parallel.sharding import FSDP_MIN_BYTES
        from torch_actor_critic_tpu.serve.sharded import ShardedPolicyEngine

        return ShardedPolicyEngine(
            base_engine.actor_def, base_engine.obs_spec, self.mesh,
            precision=self.precision, max_batch=base_engine.max_batch,
            buckets=base_engine.buckets,
            fsdp_min_bytes=(
                self.fsdp_min_bytes if self.fsdp_min_bytes is not None
                else FSDP_MIN_BYTES
            ),
            sanitize=base_engine.sanitize,
        )

    def _place(self, engine, params) -> t.Tuple[t.Any, int]:
        return engine.place_params(params)


class _Replica:
    __slots__ = ("index", "device", "registry", "batcher", "dispatched")

    def __init__(self, index, device, registry, batcher):
        self.index = index
        self.device = device
        self.registry = registry
        self.batcher = batcher
        self.dispatched = 0  # requests routed here (fleet-lock guarded)


class EngineFleet:
    """N single-device serving stacks behind one admission layer.

    Duck-types the :class:`MicroBatcher` surface the server and
    clients consume (``submit``/``act``/``queue_depth``/``close``/
    ``capacity``/``metrics``/``mode``), so
    :class:`~torch_actor_critic_tpu.serve.server.PolicyServer` drives
    a fleet exactly as it drives one batcher.

    ``devices`` defaults to every local device; pass an explicit list
    (tests pin replicas to forced CPU devices) or an int to take the
    first N. ``capacity`` is fleet-wide: the bound applies to the SUM
    of replica queues, checked atomically with routing.

    ``submesh=(tp, fsdp)`` switches the fleet to **sub-mesh replicas**
    (docs/SERVING.md "Sharded serving & precision tiers"): the device
    list is partitioned into disjoint ``tp*fsdp``-device groups, each
    hosting ONE :class:`~torch_actor_critic_tpu.serve.sharded.
    ShardedPolicyEngine` with GSPMD-sharded params — the route to
    serving a model too big for a single chip's HBM. ``precision``
    picks the numeric tier (``f32`` bitwise-pinned / ``bf16`` /
    ``int8`` weight-quantized); a non-f32 tier without an explicit
    submesh runs on ``(1, 1)`` sub-meshes (one device each, sharded
    machinery engaged for the tier alone). Admission, least-loaded
    scoring, breakers and continuous batching are UNCHANGED — a
    sub-mesh is just a wider replica.
    """

    def __init__(
        self,
        registry,
        devices: t.Sequence | int | None = None,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        metrics: ServeMetrics | None = None,
        seed: int = 0,
        capacity: int = 1024,
        span_log=None,
        mode: str = "continuous",
        submesh: t.Tuple[int, int] | None = None,
        precision: str = "f32",
        fsdp_min_bytes: int | None = None,
    ):
        if isinstance(devices, int):
            devices = jax.local_devices()[:devices]
        devices = list(devices if devices is not None else jax.local_devices())
        if not devices:
            raise ValueError("EngineFleet needs at least one device")
        if precision not in ("f32", "bf16", "int8"):
            raise ValueError(
                f"precision must be f32/bf16/int8, got {precision!r}"
            )
        self.registry = registry
        self.capacity = int(capacity)
        self.max_batch = int(max_batch)
        self.mode = mode
        self.metrics = metrics if metrics is not None else ServeMetrics()
        self.span_log = span_log
        self.precision = precision
        self.submesh = tuple(submesh) if submesh is not None else None
        if self.submesh is None and precision != "f32":
            # A precision tier is a sharded-engine feature; (1,1)
            # sub-meshes give every device the tier without changing
            # the replica count.
            self.submesh = (1, 1)
        self._lock = threading.Lock()
        self._rr = 0  # round-robin cursor for idle ties; guarded-by: _lock
        self._running = True  # guarded-by: _lock
        # _replicas is append-only during __init__ and immutable after
        # (replica-internal state has its own locks), so reads are safe
        # anywhere.
        self._replicas = []
        if self.submesh is not None:
            from torch_actor_critic_tpu.parallel.sharding import (
                partition_submeshes,
            )

            tp, fsdp = self.submesh
            meshes = partition_submeshes(devices, tp, fsdp)
            self.metrics.cost_prefix = "serve/sharded_forward"
            for i, mesh in enumerate(meshes):
                view = _SubmeshReplicaRegistry(
                    registry, mesh, i, precision=precision,
                    fsdp_min_bytes=fsdp_min_bytes, metrics=self.metrics,
                )
                batcher = MicroBatcher(
                    view, max_batch=max_batch, max_wait_ms=max_wait_ms,
                    metrics=self.metrics, seed=seed * 7919 + i,
                    capacity=capacity, span_log=span_log, mode=mode,
                )
                self._replicas.append(_Replica(i, mesh, view, batcher))
        else:
            for i, dev in enumerate(devices):
                view = _ReplicaRegistry(
                    registry, dev, i, metrics=self.metrics
                )
                batcher = MicroBatcher(
                    view, max_batch=max_batch, max_wait_ms=max_wait_ms,
                    metrics=self.metrics, seed=seed * 7919 + i,
                    capacity=capacity, span_log=span_log, mode=mode,
                )
                self._replicas.append(_Replica(i, dev, view, batcher))

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def warmup(
        self, slots: t.Sequence[str] | None = None, **kwargs
    ) -> dict:
        """Compile every replica's buckets for ``slots`` (default: all
        registered) so no live request pays a per-device compile."""
        if slots is None:
            slots = list(self.registry.slots())
        out = {}
        for rep in self._replicas:
            out[f"r{rep.index}"] = {
                s: len(rep.registry.warmup(s, **kwargs)) for s in slots
            }
        return out

    # ------------------------------------------------------------- routing

    def _pick_locked(self, slot: str):
        """Least-loaded admitting replica, or None when every
        replica's breaker for ``slot`` is refusing traffic."""
        n = len(self._replicas)
        best, best_score = None, None
        for off in range(n):
            rep = self._replicas[(self._rr + off) % n]
            br = rep.registry.breaker(slot)
            if br is not None and not br.admits():
                continue  # health gate: breaker-open replica is out
                # of rotation until its half-open probe re-admits it
            ema = rep.batcher.ema_row_s
            score = rep.batcher.load_rows() * (
                ema if ema is not None else _DEFAULT_ROW_S
            )
            if best_score is None or score < best_score:
                best, best_score = rep, score
        if best is not None:
            self._rr = (best.index + 1) % n
        return best

    def submit(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
        deadline_s: float | None = None,
        request_id: str | None = None,
    ) -> Future:
        """Route one request to the least-loaded healthy replica;
        returns that replica's batcher Future. Admission failures
        raise the same structured
        :class:`~torch_actor_critic_tpu.serve.admission.ShedError`
        vocabulary as the single-device batcher."""
        with self._lock:
            if not self._running:
                raise ShedError(
                    "draining",
                    "EngineFleet is closed (draining); not accepting "
                    "new requests",
                )
            total = sum(
                rep.batcher.queue_depth() for rep in self._replicas
            )
            if total >= self.capacity:
                self.metrics.record_shed("queue_full")
                raise ShedError(
                    "queue_full",
                    f"fleet admission queue is at capacity "
                    f"({self.capacity} requests across "
                    f"{len(self._replicas)} replicas); retry with "
                    "backoff",
                    retry_after_s=1.0,
                    detail={
                        "queue_depth": total, "capacity": self.capacity,
                    },
                )
            rep = self._pick_locked(slot)
            if rep is None:
                # Every replica's breaker is open: the fleet-level 503.
                brs = [
                    r.registry.breaker(slot) for r in self._replicas
                ]
                retry = min(
                    (b.retry_after_s() for b in brs if b is not None),
                    default=1.0,
                )
                self.metrics.record_shed("breaker_open")
                raise BreakerOpenError(slot, retry, "open")
            rep.dispatched += 1
            # Submit under the fleet lock so capacity-check + route +
            # enqueue are atomic (an enqueue is cheap; forwards happen
            # on the replicas' own dispatcher threads).
            return rep.batcher.submit(
                obs, deterministic, slot, deadline_s=deadline_s,
                request_id=request_id,
            )

    def act(
        self,
        obs: t.Any,
        deterministic: bool = True,
        slot: str = "default",
        timeout: float | None = 30.0,
        request_id: str | None = None,
    ) -> ActResult:
        """Blocking :meth:`submit`; the timeout doubles as the request
        deadline, exactly as the single-device batcher."""
        return self.submit(
            obs, deterministic, slot, deadline_s=timeout,
            request_id=request_id,
        ).result(timeout=timeout)

    # --------------------------------------------------------------- admin

    def queue_depth(self) -> int:
        return sum(rep.batcher.queue_depth() for rep in self._replicas)

    def load_rows(self) -> int:
        return sum(rep.batcher.load_rows() for rep in self._replicas)

    def replica_stats(self) -> t.List[dict]:
        """Per-replica view for ``/metrics`` ``fleet``: device, load,
        measured service rate, routed-request share, breaker states."""
        out = []
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            ema = rep.batcher.ema_row_s
            device = rep.device
            if hasattr(device, "devices"):  # a sub-mesh replica
                device = ",".join(
                    str(d) for d in device.devices.flatten()
                )
            out.append({
                "replica": rep.index,
                "device": str(device),
                "queue_depth": rep.batcher.queue_depth(),
                "load_rows": rep.batcher.load_rows(),
                "ema_row_s": round(ema, 6) if ema is not None else None,
                "dispatched_total": rep.dispatched,
                "breakers": {
                    name: s["state"]
                    for name, s in rep.registry.breaker_stats().items()
                },
            })
        return out

    def sharding_stats(self) -> dict | None:
        """The ``/metrics`` ``sharding`` section: sub-mesh shape,
        precision tier and per-replica params-transfer accounting
        (bytes actually moved at the last reload + lifetime totals).
        ``None`` for a plain per-device fleet — the section only
        appears when sub-mesh serving is on."""
        if self.submesh is None:
            return None
        tp, fsdp = self.submesh
        per_replica = []
        for rep in self._replicas:
            entry = {"replica": rep.index}
            entry.update(rep.registry.transfer_stats())
            entry["devices"] = [
                str(d) for d in rep.device.devices.flatten()
            ]
            per_replica.append(entry)
        return {
            "submesh": {"tp": tp, "fsdp": fsdp},
            "devices_per_replica": tp * fsdp,
            "replicas": len(self._replicas),
            "precision": self.precision,
            "per_replica": per_replica,
        }

    def compile_stats(self) -> dict:
        """Per-replica engine compile accounting (the fleet twin of
        ``ModelRegistry.compile_stats``)."""
        reps = {
            f"r{rep.index}": rep.registry.compile_stats()
            for rep in self._replicas
        }
        totals = [
            s for per in reps.values() for s in per.values()
        ]
        return {
            "compiles_total": sum(s["compiles_total"] for s in totals),
            "live_compiles": sum(s["live_compiles"] for s in totals),
            "replicas": reps,
        }

    def close(self, timeout: float = 10.0):
        """Stop admitting, then flush every replica's queue through
        its engine (the batcher close contract, N times)."""
        with self._lock:
            self._running = False
        for rep in self._replicas:
            rep.batcher.close(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
