"""Per-task striped ring replay: one HBM ring stripe per task.

Multi-task training (``scenarios/multitask.py``) needs replay that
stays balanced across tasks even when the collected stream does not:
exploration collapsing onto one task's envs must not starve the other
tasks' gradient signal. The uniform ring (``buffer/replay.py``) cannot
express that — a uniform draw over one ring samples tasks at whatever
ratio they were pushed.

The striped ring partitions the capacity into ``n_stripes`` independent
sub-rings (one leading stripe axis on every data leaf, per-stripe
``ptr``/``size`` cursors). Everything stays jit-pure and shape-static:

- :func:`push_striped` routes each transition of a chunk to its task's
  stripe in ONE scatter — the task id is recovered from the task
  one-hot that (by the scenarios/ convention) occupies the trailing
  ``n_stripes`` dims of the flat observation, the within-chunk write
  ranks come from a cumulative-sum over the one-hot matrix, and the
  write indices are ``(task, (ptr[task] + rank) % capacity)``. No
  data-dependent shapes anywhere.
- :func:`sample_striped` draws ``batch_size / n_stripes`` rows from
  every stripe (remainder spread over the first stripes) — per-task
  replay striping: every gradient step sees every task.

The generic :func:`buffer.replay.push`/``sample`` entry points
dispatch here on the state type, so the fused epoch program, SAC/TD3
bursts and the population loop all ride the striped ring with zero
call-site changes.

HBM budget: a striped ring occupies exactly what a uniform ring of the
same total capacity would (`capacity` here is PER STRIPE; total rows =
``n_stripes * capacity``) — see docs/SCENARIOS.md.
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
from flax import struct

from torch_actor_critic_tpu.core.types import Batch


@struct.dataclass
class StripedBufferState:
    """Functional striped-ring state: ``data`` leaves carry a leading
    ``(n_stripes, capacity)`` pair of axes; ``ptr``/``size`` are
    per-stripe ``(n_stripes,)`` cursors."""

    data: Batch
    ptr: jax.Array  # (n_stripes,) int32: next write slot per stripe
    size: jax.Array  # (n_stripes,) int32: valid rows per stripe

    @property
    def n_stripes(self) -> int:
        return jax.tree_util.tree_leaves(self.data)[0].shape[0]

    @property
    def capacity(self) -> int:
        """Per-stripe capacity (total rows = n_stripes * capacity)."""
        return jax.tree_util.tree_leaves(self.data)[0].shape[1]


def init_striped_replay_buffer(
    capacity: int,
    obs_spec: t.Any,
    act_dim: int,
    n_stripes: int,
    act_dtype=jnp.float32,
) -> StripedBufferState:
    """Preallocate an empty striped ring. ``capacity`` is the TOTAL
    row budget (matching :func:`buffer.replay.init_replay_buffer`'s
    meaning so config ``buffer_size`` keeps its HBM semantics); it is
    split evenly into ``n_stripes`` sub-rings."""
    if n_stripes < 2:
        raise ValueError(
            f"striped replay needs >= 2 stripes, got {n_stripes}"
        )
    per_stripe = capacity // n_stripes
    if per_stripe < 1:
        raise ValueError(
            f"capacity {capacity} cannot cover {n_stripes} stripes"
        )

    def zeros(spec):
        return jnp.zeros(
            (n_stripes, per_stripe) + tuple(spec.shape), spec.dtype
        )

    data = Batch(
        states=jax.tree_util.tree_map(zeros, obs_spec),
        actions=jnp.zeros((n_stripes, per_stripe, act_dim), act_dtype),
        rewards=jnp.zeros((n_stripes, per_stripe), jnp.float32),
        next_states=jax.tree_util.tree_map(zeros, obs_spec),
        done=jnp.zeros((n_stripes, per_stripe), jnp.float32),
    )
    return StripedBufferState(
        data=data,
        ptr=jnp.zeros(n_stripes, jnp.int32),
        size=jnp.zeros(n_stripes, jnp.int32),
    )


def _chunk_task_ids(chunk: Batch, n_stripes: int) -> jax.Array:
    """Recover per-row task ids from the task one-hot in the trailing
    ``n_stripes`` dims of the flat observation (newest frame when the
    obs is a history window)."""
    oh = chunk.states[..., -n_stripes:]
    # (n, ..., T) -> (n, T): a history window repeats the one-hot in
    # every frame; read it from the newest one.
    oh = oh.reshape(oh.shape[0], -1, n_stripes)[:, -1, :]
    return jnp.argmax(oh, axis=-1).astype(jnp.int32)


def push_striped(state: StripedBufferState, chunk: Batch) -> StripedBufferState:
    """Append a chunk, routing every transition to its task's stripe.

    Equivalent of per-stripe :func:`buffer.replay.push` calls fused
    into one scatter: row ``i`` with task ``s_i`` lands at
    ``(s_i, (ptr[s_i] + rank_i) % capacity)`` where ``rank_i`` counts
    the chunk's earlier rows of the same task — so write slots are
    unique by construction and each stripe wraps independently.
    """
    capacity = state.capacity
    n_stripes = state.n_stripes
    n = jax.tree_util.tree_leaves(chunk)[0].shape[0]
    if n > capacity:
        # Worst case (every row one task) would scatter duplicate
        # slots, overwriting in unspecified order — same guard as the
        # uniform ring's push.
        raise ValueError(
            f"push_striped: chunk of {n} transitions exceeds per-stripe "
            f"capacity {capacity}; use a larger buffer or smaller chunks."
        )
    task = _chunk_task_ids(chunk, n_stripes)
    onehot = jax.nn.one_hot(task, n_stripes, dtype=jnp.int32)  # (n, T)
    counts = jnp.sum(onehot, axis=0)  # (T,)
    # Exclusive running count of same-task rows before each row.
    rank = (jnp.cumsum(onehot, axis=0) - onehot)[jnp.arange(n), task]
    slot = (state.ptr[task] + rank) % capacity

    data = jax.tree_util.tree_map(
        lambda ring, new: ring.at[task, slot].set(new), state.data, chunk
    )
    return StripedBufferState(
        data=data,
        ptr=(state.ptr + counts) % capacity,
        size=jnp.minimum(state.size + counts, capacity),
    )


# ----------------------------------------------- host-side tier routing


def rows_task_ids(
    rows: t.Mapping[str, t.Any], n_stripes: int
) -> "np.ndarray":
    """Host-side (numpy) twin of :func:`_chunk_task_ids` over flat-key
    spill rows (the ``replay/`` tier row format): recover each row's
    task id from the one-hot in the trailing ``n_stripes`` dims of the
    flat observation. Used by the tiered store's stripe→tier routing
    (``replay/tiers.py``) so rows that fall off a striped HBM ring keep
    their task identity on the way down the waterfall — never called
    from traced code."""
    import numpy as np

    states = np.asarray(rows["states"])
    oh = states[..., -n_stripes:]
    oh = oh.reshape(oh.shape[0], -1, n_stripes)[:, -1, :]
    return np.argmax(oh, axis=-1).astype(np.int32)


def route_rows_to_stripes(
    rows: t.Mapping[str, t.Any], n_stripes: int
) -> t.List[t.Optional[t.Dict[str, t.Any]]]:
    """Partition flat-key rows by task stripe: returns one row dict per
    stripe (``None`` where the stripe got nothing), preserving within-
    stripe row order. Host-side numpy only — the jit push/sample path
    (:func:`push_striped`/:func:`sample_striped`) is untouched."""
    import numpy as np

    task = rows_task_ids(rows, n_stripes)
    out: t.List[t.Optional[t.Dict[str, t.Any]]] = []
    for stripe in range(n_stripes):
        mask = task == stripe
        if not mask.any():
            out.append(None)
            continue
        out.append({k: np.asarray(v)[mask] for k, v in rows.items()})
    return out


def sample_striped(
    state: StripedBufferState, key: jax.Array, batch_size: int
) -> Batch:
    """Draw a task-balanced batch: ``batch_size // n_stripes`` rows per
    stripe (remainder to the first stripes), uniform with replacement
    within each stripe's valid region — the per-task replay striping
    guarantee. Row draws use per-stripe ``fold_in`` keys (a new
    subsystem: no bitwise-parity constraint against the uniform ring).

    An unfilled stripe samples its zero rows until its task's envs
    push (the warmup phase covers this exactly like the uniform ring's
    ``size > 0`` gate); a concretely all-empty ring raises eagerly.
    """
    if not isinstance(state.size, jax.core.Tracer) and (
        int(jnp.sum(state.size)) == 0
    ):
        raise ValueError("sample_striped: replay buffer is empty.")
    n_stripes = state.n_stripes
    base, rem = divmod(batch_size, n_stripes)
    parts = []
    for stripe in range(n_stripes):
        n_rows = base + (1 if stripe < rem else 0)
        if n_rows == 0:
            continue
        idx = jax.random.randint(
            jax.random.fold_in(key, stripe),
            (n_rows,), 0, jnp.maximum(state.size[stripe], 1),
        )

        def take(ring, stripe=stripe, idx=idx):
            return jnp.take(ring[stripe], idx, axis=0)

        parts.append(jax.tree_util.tree_map(take, state.data))
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *parts
    )
