"""HBM-resident uniform-sampling ring replay buffer.

Capability twin of the reference's host-side NumPy ring buffers
(``ReplayBuffer``, ref ``buffer/replay_buffer.py:17-54``, and
``VisualReplayBuffer``, ref ``buffer/visual_replay_buffer.py:21-66``),
re-designed for TPU:

- **Device-resident**: the ring lives in HBM as preallocated
  ``jax.Array`` leaves of a :class:`~torch_actor_critic_tpu.core.types.BufferState`;
  ``push``/``sample`` are pure jittable functions, so sampling happens
  *inside* the fused SAC update step with zero host<->device traffic per
  gradient step (the reference converts NumPy->torch on every sample,
  ref ``replay_buffer.py:47-54``).
- **One generic implementation**: observations are pytrees, so the
  visual buffer is the same code over ``MultiObservation`` leaves —
  no subclass that overrides everything (ref
  ``visual_replay_buffer.py:21``: "subclasses ReplayBuffer but
  overrides everything"). Frames are stored **uint8** (4x less HBM than
  the reference's float object-arrays; 1e6 64x64x3 frames = 12 GB fp32
  vs 3 GB u8) and cast to float inside the model.
- **Chunked stores**: the host env loop accumulates ``update_every``
  transitions and pushes them in one call (one dispatch per burst
  instead of the reference's per-step ``store``,
  ref ``sac/algorithm.py:249``). Wraparound handled with modular
  scatter indices — compiler-friendly, no data-dependent shapes.
- **Sampling is uniform with replacement** (``randint`` + ``take``).
  The reference samples *without* replacement via ``random.sample``
  (ref ``replay_buffer.py:46``); at 1e6-slot buffers and batch 64 the
  collision probability per batch is ~2e-3, a deliberate,
  XLA-friendly deviation (SURVEY.md §7 item 3). Before the buffer is
  full, indices are drawn over ``[0, size)`` exactly like the
  reference's ``range(self.size)``.

Donation: callers should jit ``push`` with ``donate_argnums=(0,)`` (the
trainer does) so XLA updates the ring in place instead of copying the
full 1e6-slot arrays per store.
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
import numpy as np

from torch_actor_critic_tpu.buffer.striped import (
    StripedBufferState,
    push_striped,
    sample_striped,
)
from torch_actor_critic_tpu.core.types import Batch, BufferState, MultiObservation


def _zeros_like_spec(capacity: int, spec: t.Any) -> t.Any:
    """Build zeroed ring arrays from a pytree of (shape, dtype) specs.

    A spec leaf is anything with ``.shape`` and ``.dtype`` (e.g. a
    ``jax.ShapeDtypeStruct`` or a concrete example array).
    """
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros((capacity,) + tuple(s.shape), s.dtype), spec
    )


def estimate_buffer_bytes(capacity: int, obs_spec: t.Any, act_dim: int) -> int:
    """HBM bytes one replay shard of ``capacity`` transitions occupies.

    Two observation copies (state, next_state) + action + reward + done
    per row — the planning number behind the trainer's HBM-budget
    warning (1e6 visual transitions at the wall-runner geometry come to
    ~26 GB — two uint8 frame copies plus features per row — which no
    single v5e's 16 GB can hold).
    """
    obs_bytes = sum(
        int(np.prod(s.shape)) * jnp.dtype(s.dtype).itemsize
        for s in jax.tree_util.tree_leaves(obs_spec)
    )
    row = 2 * obs_bytes + act_dim * 4 + 2 * 4
    return capacity * row


def nbytes(state: t.Any) -> int:
    """MEASURED bytes of a live buffer state's array leaves — the
    as-allocated companion to :func:`estimate_buffer_bytes`'s planning
    estimate (which knows nothing about striping, sequence-axis
    sharding or the vmapped device axis). Works on any buffer state
    pytree — ``BufferState``, ``StripedBufferState``, the dp-sharded
    per-device tree — and on abstract ``ShapeDtypeStruct`` leaves
    (shape x itemsize, no device query). Surfaced per epoch as
    ``replay/hbm_bytes`` when tiers are on (metrics.jsonl, next to the
    telemetry HBM watermarks).
    """
    total = 0
    for leaf in jax.tree_util.tree_leaves(state):
        n = getattr(leaf, "nbytes", None)
        if n is None:
            n = int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        total += int(n)
    return total


def warn_if_buffer_exceeds_hbm(
    capacity: int,
    obs_spec: t.Any,
    act_dim: int,
    sp: int = 1,
    advice: str = "reduce buffer capacity or history_len",
) -> None:
    """Warn when one replay shard would crowd out update intermediates.

    The HBM-resident buffer is the design's core trade (zero
    host<->device replay traffic); an oversized capacity otherwise fails
    as an opaque allocator OOM mid-run. Shared by the host Trainer and
    the fused on-device loop so the device lookup / ``memory_stats``
    fallback / threshold logic cannot drift between them. ``sp`` > 1
    discounts sequence-history leaves whose T axis is sharded over the
    ring (``init_sharded_buffer``). No-op on CPU backends (host RAM,
    like the reference's buffer, ref ``buffer/replay_buffer.py``).

    ``advice`` names the caller's actual knobs: the host Trainer's
    per-device shard shrinks with dp, but the fused on-device loop
    broadcasts the FULL capacity to every dp slice — telling its users
    to "raise dp" would not reduce residency.
    """
    import logging

    dev = jax.local_devices()[0]
    if dev.platform == "cpu":
        return
    stats = getattr(dev, "memory_stats", lambda: None)() or {}
    hbm = stats.get("bytes_limit", 16 * 1024**3)
    need = estimate_buffer_bytes(capacity, obs_spec, act_dim) // max(sp, 1)
    if need > 0.5 * hbm:
        logging.getLogger(__name__).warning(
            "replay shard needs ~%.1f GB of ~%.1f GB device memory; "
            "params, optimizer state and update intermediates share the "
            "rest — %s if allocation fails",
            need / 1024**3, hbm / 1024**3, advice,
        )


def init_replay_buffer(
    capacity: int,
    obs_spec: t.Any,
    act_dim: int,
    act_dtype=jnp.float32,
) -> BufferState:
    """Preallocate an empty ring buffer.

    ``obs_spec`` is a pytree of ``jax.ShapeDtypeStruct`` (or example
    arrays) describing ONE observation — a flat vector for MLP envs
    (ref ``replay_buffer.py:19-23``) or a ``MultiObservation`` spec for
    pixel envs.
    """
    data = Batch(
        states=_zeros_like_spec(capacity, obs_spec),
        actions=jnp.zeros((capacity, act_dim), act_dtype),
        rewards=jnp.zeros((capacity,), jnp.float32),
        next_states=_zeros_like_spec(capacity, obs_spec),
        done=jnp.zeros((capacity,), jnp.float32),
    )
    return BufferState(data=data, ptr=jnp.int32(0), size=jnp.int32(0))


def init_visual_replay_buffer(
    capacity: int,
    feature_dim: int,
    frame_shape: t.Tuple[int, int, int],
    act_dim: int,
) -> BufferState:
    """Convenience constructor for the mixed-observation buffer.

    Counterpart of the reference ``VisualReplayBuffer`` constructor
    (ref ``visual_replay_buffer.py:22-31``) with uint8 HWC frames.
    """
    obs_spec = MultiObservation(
        features=jax.ShapeDtypeStruct((feature_dim,), jnp.float32),
        frame=jax.ShapeDtypeStruct(tuple(frame_shape), jnp.uint8),
    )
    return init_replay_buffer(capacity, obs_spec, act_dim)


def push(state: BufferState, chunk: Batch) -> BufferState:
    """Append a chunk of ``n`` transitions, overwriting oldest on wrap.

    Equivalent of ``n`` reference ``store`` calls
    (ref ``replay_buffer.py:29-43``): writes at
    ``(ptr + arange(n)) % capacity``, then advances ``ptr`` and
    saturates ``size`` at capacity. ``n`` must be static (it is: the
    trainer always pushes ``update_every``-sized chunks).

    A striped (per-task) ring dispatches to
    :func:`~torch_actor_critic_tpu.buffer.striped.push_striped` — the
    one integration point that lets the fused burst/epoch programs ride
    either ring unchanged.
    """
    if isinstance(state, StripedBufferState):
        return push_striped(state, chunk)
    capacity = state.capacity
    n = jax.tree_util.tree_leaves(chunk)[0].shape[0]
    if n > capacity:
        # Duplicate scatter indices would overwrite in unspecified order.
        raise ValueError(
            f"push: chunk of {n} transitions exceeds buffer capacity "
            f"{capacity}; use a larger buffer or smaller chunks."
        )
    idx = (state.ptr + jnp.arange(n)) % capacity

    data = jax.tree_util.tree_map(
        lambda ring, new: ring.at[idx].set(new), state.data, chunk
    )
    return BufferState(
        data=data,
        ptr=(state.ptr + n) % capacity,
        size=jnp.minimum(state.size + n, capacity),
    )


def sample(state: BufferState, key: jax.Array, batch_size: int) -> Batch:
    """Draw a uniform batch over the valid region ``[0, size)``.

    With replacement (deliberate deviation from ref
    ``replay_buffer.py:46``, see module docstring). Gathers are plain
    ``jnp.take`` so XLA lowers them to efficient dynamic-gathers; a
    Pallas gather path can slot in here if profiles demand it.

    An empty buffer raises eagerly; under ``jit`` the size is traced and
    cannot be checked, so the index range is clamped to ``[0, 1)`` —
    callers must gate on ``size > 0`` (the trainer's ``update_after``
    warmup guarantees this, ref ``sac/algorithm.py:273``).

    A striped (per-task) ring dispatches to
    :func:`~torch_actor_critic_tpu.buffer.striped.sample_striped`
    (task-balanced draws), mirroring :func:`push`.
    """
    if isinstance(state, StripedBufferState):
        return sample_striped(state, key, batch_size)
    if not isinstance(state.size, jax.core.Tracer) and int(state.size) == 0:
        raise ValueError("sample: replay buffer is empty (size == 0).")
    idx = jax.random.randint(key, (batch_size,), 0, jnp.maximum(state.size, 1))
    return jax.tree_util.tree_map(lambda ring: jnp.take(ring, idx, axis=0), state.data)


def sample_fused_visual(
    state: BufferState,
    key: jax.Array,
    batch_size: int,
    out_dtype,
    augment: str = "none",
    pad: int = 4,
    normalize: bool = False,
    impl: str = "auto",
    interpret: bool = False,
) -> Batch:
    """:func:`sample` for visual batches through the fused pixel
    pipeline (``ops/pixels.py``): non-frame leaves gather exactly like
    :func:`sample`; the two frame leaves decode, DrQ-shift and cast to
    ``out_dtype`` inside the fused gather, so the sampled frame batch
    never materializes as float32 in HBM (bf16 halves its footprint
    besides).

    Key discipline: with ``augment="none"`` the row draw consumes
    ``key`` exactly as :func:`sample` does, so at ``out_dtype=float32``
    this path is bitwise-identical to sample-then-decode-in-model —
    the ``pixel_pipeline="fused"`` f32 equivalence tests pin it. With
    ``augment="shift"`` the key splits three ways (rows, state shift,
    next-state shift): augmentation keys are consumed at sample time
    instead of inside the learner update (DrQ's independent
    per-example, per-use draws preserved).
    """
    from torch_actor_critic_tpu.ops.augment import shift_offsets
    from torch_actor_critic_tpu.ops.pixels import fused_frame_gather

    if not isinstance(state.data.states, MultiObservation):
        raise ValueError(
            "sample_fused_visual needs a MultiObservation (frame) "
            f"buffer; got {type(state.data.states).__name__}"
        )
    if not isinstance(state.size, jax.core.Tracer) and int(state.size) == 0:
        raise ValueError("sample: replay buffer is empty (size == 0).")
    if augment == "shift":
        k_idx, k_s, k_n = jax.random.split(key, 3)
        offs_s = shift_offsets(k_s, batch_size, pad)
        offs_n = shift_offsets(k_n, batch_size, pad)
    elif augment == "none":
        k_idx, offs_s, offs_n = key, None, None
    else:
        raise ValueError(f"unknown frame_augment mode {augment!r}")
    idx = jax.random.randint(
        k_idx, (batch_size,), 0, jnp.maximum(state.size, 1)
    )
    take = lambda ring: jnp.take(ring, idx, axis=0)  # noqa: E731
    gather = lambda ring, offs: fused_frame_gather(  # noqa: E731
        ring, idx, offsets=offs, pad=pad, normalize=normalize,
        out_dtype=out_dtype, impl=impl, interpret=interpret,
    )
    d = state.data
    return Batch(
        states=MultiObservation(
            features=take(d.states.features),
            frame=gather(d.states.frame, offs_s),
        ),
        actions=take(d.actions),
        rewards=take(d.rewards),
        next_states=MultiObservation(
            features=take(d.next_states.features),
            frame=gather(d.next_states.frame, offs_n),
        ),
        done=take(d.done),
    )
