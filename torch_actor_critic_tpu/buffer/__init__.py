from torch_actor_critic_tpu.buffer.replay import (  # noqa: F401
    init_replay_buffer,
    init_visual_replay_buffer,
    push,
    sample,
    sample_fused_visual,
)
from torch_actor_critic_tpu.buffer.striped import (  # noqa: F401
    StripedBufferState,
    init_striped_replay_buffer,
    push_striped,
    sample_striped,
)
