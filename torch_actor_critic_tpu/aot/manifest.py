"""The AOT pre-compile manifest, derived from the checked tables.

There is deliberately **no third list** of "programs to pre-compile":
the set is derived from ``reachability.ENTRY_POINTS`` (which the
static analyzer pins against the code, PR 7) joined with the
``bundleable`` column of ``contracts.ENTRY_POINT_CONTRACTS`` (which the
``stale-bundle-manifest`` lint rule requires to be an explicit literal
on every row, PR 15). A new jit entry point therefore cannot ship
without declaring whether it is AOT-bundled, and a bundleability claim
cannot outlive the entry point it describes — :func:`entry_point_table`
fails loudly on any divergence between the two tables.

Bundled programs are the *serve* plane's bucket ladder: one program per
``(bucket, deterministic)`` pair (exactly the jit-cache keys
``PolicyEngine.warmup`` populates). Train-plane entry points are
``bundleable=False`` — their shapes depend on run config rather than a
fixed ladder, so they ride the shared persistent compilation cache
(:mod:`~torch_actor_critic_tpu.aot.cache`) instead of serialized
executables.
"""

from __future__ import annotations

import typing as t

from torch_actor_critic_tpu.analysis.contracts import (
    ENTRY_POINT_CONTRACTS,
)
from torch_actor_critic_tpu.analysis.reachability import ENTRY_POINTS

__all__ = [
    "ManifestError",
    "ProgramSpec",
    "bundled_entry_points",
    "entry_point_table",
    "program_filename",
    "program_name",
    "serve_programs",
]


class ManifestError(RuntimeError):
    """The checked tables disagree — the manifest cannot be derived."""


class ProgramSpec(t.NamedTuple):
    """One program the bundle serializes: the watchdog/cost identity
    specialized to a concrete ``(bucket, deterministic)`` shape."""

    name: str           # e.g. "serve/forward[b4].sampled"
    identity: str       # ENTRY_POINTS key, e.g. "serve/forward"
    bucket: int
    deterministic: bool


def entry_point_table() -> t.Dict[str, bool]:
    """``{identity: bundleable}`` over every checked entry point.

    Raises :class:`ManifestError` unless ``ENTRY_POINTS`` and
    ``ENTRY_POINT_CONTRACTS`` cover exactly the same identities — the
    same invariant the ``stale-contract`` lint enforces, re-checked
    here at runtime because the bundle builder must not silently skip
    an entry point the tables disagree about.
    """
    entry_keys = set(ENTRY_POINTS)
    table_keys = set(ENTRY_POINT_CONTRACTS)
    if entry_keys != table_keys:
        missing = sorted(entry_keys - table_keys)
        extra = sorted(table_keys - entry_keys)
        raise ManifestError(
            "ENTRY_POINTS and ENTRY_POINT_CONTRACTS diverge — "
            f"missing contract rows: {missing}; rows with no entry "
            f"point: {extra}. Fix analysis/contracts.py (the "
            "stale-contract lint flags this too)."
        )
    return {
        identity: bool(ENTRY_POINT_CONTRACTS[identity].bundleable)
        for identity in sorted(entry_keys)
    }


def bundled_entry_points() -> t.Tuple[str, ...]:
    """The identities whose programs go into the warm-start bundle."""
    return tuple(
        identity
        for identity, bundleable in entry_point_table().items()
        if bundleable
    )


def program_name(identity: str, bucket: int, deterministic: bool) -> str:
    """The bundle-internal program key: the per-bucket watchdog label
    (``serve/forward[b4]``) plus which half of the jit pair."""
    mode = "det" if deterministic else "sampled"
    return f"{identity}[b{int(bucket)}].{mode}"


def program_filename(name: str) -> str:
    """Filesystem-safe serialized-program file name for ``name``."""
    safe = name.replace("/", "__").replace("[b", "-b").replace("]", "")
    return f"{safe}.jexp"


def serve_programs(
    buckets: t.Sequence[int],
    deterministic_only: bool = False,
) -> t.List[ProgramSpec]:
    """Every program a serve worker's warmup will dispatch for the
    given bucket ladder: the bundled identities x buckets x
    (deterministic, sampled) — the exact jit-cache keys
    ``PolicyEngine.warmup`` populates, in warmup order."""
    specs: t.List[ProgramSpec] = []
    for identity in bundled_entry_points():
        for bucket in buckets:
            for det in (True,) if deterministic_only else (True, False):
                specs.append(ProgramSpec(
                    name=program_name(identity, bucket, det),
                    identity=identity,
                    bucket=int(bucket),
                    deterministic=det,
                ))
    return specs
