"""Persistent XLA compilation cache shared across processes.

JAX's persistent compilation cache keys a compiled executable on the
program HLO + compile options + backend identity, so two *processes*
compiling the same jit program share one cache entry: a fleet worker
spawned after the first one — or a learner restarted after preemption
— retrieves the executable from disk instead of re-running XLA. This
module is the one place that cache gets configured, for three reasons:

- **Key stability.** Any cache-affecting config knob that differs
  between the process that wrote an entry and the process reading it
  silently changes the cache key (measured: toggling
  ``jax_persistent_cache_enable_xla_caches`` alone forks the keyspace).
  Funneling every enable through :func:`enable_persistent_cache` keeps
  the builder (``--emit-bundle``, bundle build) and every consumer
  (serve workers, restarted learners, respawned actors) on identical
  settings.
- **Unthresholded writes.** The jax defaults only persist compiles
  slower than ~1s / larger than a floor — on the CPU tier-1 shim most
  serve-bucket programs compile faster than that and would never be
  written, making the cold-start win unprovable. We persist
  everything; the cache is per-run-scoped, not a global grow-forever
  directory.
- **Inheritance.** The chosen directory is exported as
  :data:`CACHE_ENV_VAR` so *spawned children* (fleet actor processes,
  ``serve.py --fleet`` workers) join the same cache via
  :func:`enable_cache_from_env` without any extra plumbing.

Hit/miss counters ride the watchdog
(:mod:`~torch_actor_critic_tpu.diagnostics.watchdog` listens for the
``/jax/compilation_cache/cache_{hits,misses}`` monitoring events) onto
``/metrics`` and metrics.jsonl.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import threading

logger = logging.getLogger(__name__)

__all__ = [
    "CACHE_ENV_VAR",
    "enable_persistent_cache",
    "enable_cache_from_env",
    "disable_persistent_cache",
    "current_cache_dir",
    "cache_entries",
    "cache_excluded",
    "exclude_from_cache",
]

# Spawned children (multiprocessing actors, fleet worker subprocesses)
# inherit the cache through this env var (enable_cache_from_env).
CACHE_ENV_VAR = "TAC_COMPILE_CACHE"

# cache_excluded() nesting state — shared across threads on purpose:
# the flag it toggles is process-global, so the exclusion must be too.
_exclusion_lock = threading.Lock()
_exclusion_depth = [0]
_exclusion_prev = True


def _reset_backend_cache() -> None:
    """Make a cache-dir change take effect in an already-initialized
    process: jax memoizes the cache object on first use, so switching
    directories (the bundle builder does, mid-run) needs a reset."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — best-effort: on jax versions
        # without reset_cache the dir is simply fixed at first use
        logger.debug("compilation-cache reset unavailable", exc_info=True)


def enable_persistent_cache(
    cache_dir: str, export_env: bool = True
) -> str:
    """Point this process's persistent compilation cache at
    ``cache_dir`` (created if absent) and arm the watchdog's hit/miss
    counters. Returns the absolute directory. With ``export_env``
    (default) the directory is published to :data:`CACHE_ENV_VAR` so
    spawned children join the same cache."""
    import jax

    from torch_actor_critic_tpu.diagnostics.watchdog import get_watchdog

    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Persist EVERY compile: the defaults skip fast/small programs,
    # which on the CPU shim is most of them (see module docstring).
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _reset_backend_cache()
    if export_env:
        os.environ[CACHE_ENV_VAR] = cache_dir
    # Counters must be live before the first compile probes the cache.
    get_watchdog().install()
    logger.info("persistent compilation cache: %s", cache_dir)
    return cache_dir


def enable_cache_from_env() -> str | None:
    """Join the cache a parent process published via
    :data:`CACHE_ENV_VAR` (the respawned-actor / spawned-worker path).
    No-op returning None when the variable is unset or empty."""
    cache_dir = os.environ.get(CACHE_ENV_VAR, "")
    if not cache_dir:
        return None
    return enable_persistent_cache(cache_dir, export_env=False)


def disable_persistent_cache() -> None:
    """Turn the persistent cache back off (test isolation: a test that
    enabled a tmpdir cache must not leak it into later tests)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _reset_backend_cache()
    os.environ.pop(CACHE_ENV_VAR, None)


@contextlib.contextmanager
def cache_excluded():
    """Bypass the persistent cache (read AND write) for the compiles
    dispatched inside this context.

    Exists because of a measured jaxlib 0.4.36 XLA:CPU defect: when
    BOTH of the train plane's big donated+sharded executables (the
    buffer ``push`` and the ``burst``, whose donated replay-buffer
    pytree flows from one into the other) are *deserialized* from the
    persistent cache instead of freshly compiled, executing them
    corrupts memory — non-finite training state on a good day, a
    segfault on a bad one. Bisected to exactly that entry pair:
    evicting either one makes the restarted learner bitwise-clean, so
    the train plane's donated programs opt out of the cache wholesale
    (:func:`exclude_from_cache`) and always compile live. The serve
    plane — where the cold-start win lives — keeps riding the cache;
    its bundle-armed zero-live-compile pin is verified bitwise by
    tests/test_aot.py and the coldstart smoke.

    Toggling ``jax_enable_compilation_cache`` does not retrace and is
    microseconds per call — noise against a burst dispatch. The
    ``reset_cache()`` on each side is load-bearing: jax memoizes the
    cache-used decision ONCE globally (``_cache_checked``), so a bare
    flag flip after the first compile in the process is silently
    ignored; the reset forces re-evaluation under the flipped flag
    (and again under the restored one).
    """
    import jax

    global _exclusion_prev
    # Depth-counted so overlapping exclusions from different threads
    # (the prefetch thread's push racing the main thread's burst) keep
    # the flag off until the LAST one exits — an early restore would
    # let the other thread's compile probe the cache mid-exclusion.
    with _exclusion_lock:
        _exclusion_depth[0] += 1
        if _exclusion_depth[0] == 1:
            _exclusion_prev = jax.config.jax_enable_compilation_cache
            jax.config.update("jax_enable_compilation_cache", False)
            _reset_backend_cache()
    try:
        yield
    finally:
        with _exclusion_lock:
            _exclusion_depth[0] -= 1
            if _exclusion_depth[0] == 0:
                jax.config.update(
                    "jax_enable_compilation_cache", _exclusion_prev
                )
                _reset_backend_cache()


def exclude_from_cache(fn):
    """Wrap a (jitted) callable so every compile it triggers bypasses
    the persistent cache — see :func:`cache_excluded` for why the
    donated train-plane programs need this."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with cache_excluded():
            return fn(*args, **kwargs)

    return wrapper


def current_cache_dir() -> str | None:
    """The directory this process's persistent cache points at (None
    when disabled)."""
    import jax

    return jax.config.jax_compilation_cache_dir


def cache_entries(cache_dir: str) -> int:
    """Number of persisted executables under ``cache_dir`` (0 for a
    missing directory) — the bundle builder's sanity check and the
    coldstart bench's evidence that the cache actually populated."""
    if not os.path.isdir(cache_dir):
        return 0
    return sum(
        1 for name in os.listdir(cache_dir)
        if os.path.isfile(os.path.join(cache_dir, name))
    )
