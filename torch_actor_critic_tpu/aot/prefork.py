"""Pre-forked warm worker pool: pay the spawn before you need it.

Even with a warm-start bundle, a fleet scale-up or kill-replacement
still pays process spawn + import + bundle-load wall-clock *on the
serving path*. The :class:`WarmPool` moves that cost off-path: a
background thread keeps ``size`` spare workers booted (from the bundle,
so they are compile-free AND warm), and the router draws an
already-listening process in O(queue-pop) when it needs one.

The pool is deliberately generic over a ``spawn`` callable returning
``(handle, address)`` and a ``kill`` callable taking the handle — in
``serve.py --warm-pool N`` these wrap the real worker-subprocess
launcher; in tests they can be in-process fakes. The pool never
inspects the handle.
"""

from __future__ import annotations

import logging
import threading
import time
import typing as t

logger = logging.getLogger(__name__)

__all__ = ["WarmPool", "WarmWorker"]

# Back off after a failed spawn so a persistently-broken launcher logs
# a complaint per attempt instead of busy-spinning the thread.
_SPAWN_RETRY_DELAY_S = 1.0


class WarmWorker(t.NamedTuple):
    """One spare: the launcher's opaque handle plus where it listens."""

    handle: t.Any
    address: str


class WarmPool:
    """Keep ``size`` pre-spawned warm workers ready to draw.

    ``spawn()`` must return ``(handle, address)`` for a worker that is
    READY (listening, warmed) — the pool counts readiness as the
    launcher's problem, which is what makes the draw O(1).
    ``kill(handle)`` tears one down (shutdown path and unclaimed
    spares).
    """

    def __init__(
        self,
        spawn: t.Callable[[], t.Tuple[t.Any, str]],
        kill: t.Callable[[t.Any], None],
        size: int,
        name: str = "warm-pool",
    ):
        if size < 0:
            raise ValueError(f"pool size must be >= 0, got {size}")
        self._spawn = spawn
        self._kill = kill
        self.size = int(size)
        self.name = name
        self._cv = threading.Condition()
        self._spares: t.List[WarmWorker] = []  # guarded-by: _cv
        self._stopped = False  # guarded-by: _cv
        self.spawned = 0  # guarded-by: _cv
        self.drawn = 0  # guarded-by: _cv
        self.spawn_failures = 0  # guarded-by: _cv
        # Last refill attempt, for the router's fleet /metrics section:
        # did it succeed, when (monotonic), and the failure detail if
        # not — so "the pool is quietly broken" is visible to the
        # elastic controller's operators, not just this process's log.
        self.last_refill_ok: bool | None = None  # guarded-by: _cv
        self.last_refill_at: float | None = None  # guarded-by: _cv
        self.last_refill_error: str | None = None  # guarded-by: _cv
        self._thread: threading.Thread | None = None
        if self.size > 0:
            self._thread = threading.Thread(
                target=self._refill_loop, name=name, daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------ refill

    def _refill_loop(self) -> None:
        while True:
            with self._cv:
                while not self._stopped and len(self._spares) >= self.size:
                    self._cv.wait()
                if self._stopped:
                    return
            # Spawn OUTSIDE the lock: a worker boot takes seconds and
            # draw() must stay responsive for already-ready spares.
            try:
                handle, address = self._spawn()
            except Exception as e:  # noqa: BLE001 — launcher owns the detail
                logger.exception("%s: spare worker spawn failed", self.name)
                with self._cv:
                    self.spawn_failures += 1
                    self.last_refill_ok = False
                    self.last_refill_at = time.monotonic()
                    self.last_refill_error = (
                        f"{type(e).__name__}: {e}"[:200]
                    )
                    if self._stopped:
                        return
                # Plain sleep (not cv.wait): back off even when draws
                # keep notifying.
                threading.Event().wait(_SPAWN_RETRY_DELAY_S)
                continue
            with self._cv:
                if self._stopped:
                    break
                self._spares.append(WarmWorker(handle, address))
                self.spawned += 1
                self.last_refill_ok = True
                self.last_refill_at = time.monotonic()
                self.last_refill_error = None
                self._cv.notify_all()
        # Stopped mid-spawn: the fresh worker is ours to reap.
        try:
            self._kill(handle)
        except Exception:  # noqa: BLE001
            logger.exception("%s: kill of orphan spare failed", self.name)

    # ------------------------------------------------------------- draws

    def draw(self, timeout: float | None = None) -> WarmWorker | None:
        """Pop a ready spare (blocking up to ``timeout`` for the refill
        thread if none is ready). Returns None on timeout, on a
        zero-size pool, or after shutdown. The caller owns the worker
        from here — the pool immediately begins spawning a
        replacement."""
        if self.size == 0:
            return None
        with self._cv:
            if not self._spares and not self._stopped:
                self._cv.wait(timeout)
            if self._stopped or not self._spares:
                return None
            worker = self._spares.pop(0)
            self.drawn += 1
            self._cv.notify_all()  # wake the refill thread
            return worker

    def stats(self) -> dict:
        """Pool counters for /metrics: ready spares, lifetime spawns /
        draws / spawn failures, and the last refill attempt's status
        (ok flag, age in seconds, error detail if it failed)."""
        with self._cv:
            age = (
                None if self.last_refill_at is None
                else round(time.monotonic() - self.last_refill_at, 3)
            )
            return {
                "size": self.size,
                "ready": len(self._spares),
                "spawned": self.spawned,
                "drawn": self.drawn,
                "spawn_failures": self.spawn_failures,
                "last_refill_ok": self.last_refill_ok,
                "last_refill_age_s": age,
                "last_refill_error": self.last_refill_error,
            }

    # ---------------------------------------------------------- shutdown

    def shutdown(self, join_timeout: float = 10.0) -> None:
        """Stop refilling and kill every unclaimed spare."""
        with self._cv:
            if self._stopped:
                return
            self._stopped = True
            spares, self._spares = self._spares, []
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(join_timeout)
        for worker in spares:
            try:
                self._kill(worker.handle)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "%s: kill of spare %s failed", self.name, worker.address
                )
