"""aot/: kill the cold start — compilation as a build artifact.

Every spawned serve worker, hot-reload to a new bucket, restarted
learner and respawned actor used to pay live XLA compiles (the
diagnostics/ watchdog counts them; serve warmup only hides them behind
wall-clock). This subsystem makes compilation a **build artifact**:

- :mod:`~torch_actor_critic_tpu.aot.manifest` — the set of programs to
  pre-compile, derived from the checked
  ``reachability.ENTRY_POINTS`` / ``contracts.ENTRY_POINT_CONTRACTS``
  tables plus the serve bucket ladder. The tables ARE the manifest; a
  new entry point cannot ship without declaring its bundleability
  (`stale-bundle-manifest` lint).
- :mod:`~torch_actor_critic_tpu.aot.bundle` — a ``warm_start`` bundle
  next to the Orbax checkpoint: ``jax.export``-serialized programs +
  a pre-populated persistent compilation cache, stamped with a
  compatibility fingerprint. A mismatched bundle is rejected loudly
  and counted; serving falls back to live compile.
- :mod:`~torch_actor_critic_tpu.aot.cache` — the persistent
  compilation cache shared by fleet workers and restarted learners,
  hit/miss counters surfaced through the watchdog onto ``/metrics``
  and metrics.jsonl.
- :mod:`~torch_actor_critic_tpu.aot.prefork` — a pre-forked warm
  worker pool for the fleet router (``serve.py --warm-pool N``):
  scale-up and kill-replacement draw an already-warm process instead
  of paying spawn+compile.

Success metric: time-to-first-act for a fresh worker with vs without
a bundle (``bench.py --stage=coldstart``), and ``live_compiles == 0``
through a full chaos flood (docs/SERVING.md "Cold start & warm-start
bundles").
"""

from torch_actor_critic_tpu.aot.bundle import (
    BundleMismatchError,
    WarmStartBundle,
    build_bundle,
    default_bundle_dir,
    emit_bundle,
    load_bundle,
)
from torch_actor_critic_tpu.aot.cache import (
    CACHE_ENV_VAR,
    enable_cache_from_env,
    enable_persistent_cache,
)
from torch_actor_critic_tpu.aot.manifest import (
    ManifestError,
    bundled_entry_points,
    entry_point_table,
    serve_programs,
)
from torch_actor_critic_tpu.aot.prefork import WarmPool

__all__ = [
    "BundleMismatchError",
    "WarmStartBundle",
    "build_bundle",
    "default_bundle_dir",
    "emit_bundle",
    "load_bundle",
    "CACHE_ENV_VAR",
    "enable_cache_from_env",
    "enable_persistent_cache",
    "ManifestError",
    "bundled_entry_points",
    "entry_point_table",
    "serve_programs",
    "WarmPool",
]
