"""warm_start bundles: compilation as a checkpoint-adjacent artifact.

A bundle is a directory shipped **next to the Orbax checkpoint**
(``<run>/checkpoints`` -> ``<run>/warm_start``) holding everything a
fresh worker needs to answer its first request without a live compile:

``MANIFEST.json``
    Format version, the environment **fingerprint** (jaxlib version,
    backend, device kind/count, mesh shape), the bucket ladder, and a
    per-program index with each program's abstract input avals.
``programs/*.jexp``
    One ``jax.export``-serialized program per manifest entry — the
    portable, *verifiable* half of the bundle. Consumers do not serve
    through ``Exported.call`` (that would re-trace a second program and
    break the bitwise pin between warmup and live dispatch); they
    deserialize to check avals against their own jit programs, and the
    round-trip test proves bitwise agreement with the live compile.
``xla_cache/``
    A persistent compilation cache pre-populated by running the REAL
    ``PolicyEngine`` warmup at build time. Because cache keys cover the
    HLO + compile options + backend, a consumer pointing its cache here
    and dispatching the same jit programs gets disk hits instead of XLA
    runs — this is the mechanism that actually delivers
    ``live_compiles == 0``.

A bundle whose fingerprint or avals disagree with the consuming
process is **rejected loudly** (:class:`BundleMismatchError`), counted
on the watchdog (``bundle_rejected``), and the worker falls back to a
plain live-compile warmup — a stale bundle may cost the cold start
back, never correctness.
"""

from __future__ import annotations

import json
import logging
import os
import pathlib
import typing as t

logger = logging.getLogger(__name__)

__all__ = [
    "BUNDLE_FORMAT",
    "BundleMismatchError",
    "WarmStartBundle",
    "build_bundle",
    "default_bundle_dir",
    "emit_bundle",
    "environment_fingerprint",
    "load_bundle",
]

BUNDLE_FORMAT = 1

_MANIFEST = "MANIFEST.json"
_PROGRAMS = "programs"
_XLA_CACHE = "xla_cache"


class BundleMismatchError(RuntimeError):
    """The bundle does not fit this process (wrong jaxlib / devices /
    avals / missing program). Callers catch this, count it on the
    watchdog, and fall back to live compile."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def environment_fingerprint(
    mesh_shape: t.Sequence[int] | None = None,
) -> t.Dict[str, t.Any]:
    """What must match between the process that built a bundle and the
    process consuming it for the serialized programs (and the
    persistent-cache keys behind them) to be valid."""
    import jax
    import jaxlib

    return {
        "format": BUNDLE_FORMAT,
        "jax": jax.__version__,
        "jaxlib": getattr(jaxlib, "__version__", "unknown"),
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "device_count": jax.device_count(),
        "mesh_shape": list(mesh_shape) if mesh_shape else None,
    }


def check_fingerprint(
    stored: t.Mapping[str, t.Any],
    mesh_shape: t.Sequence[int] | None = None,
) -> None:
    """Raise :class:`BundleMismatchError` naming every field on which
    ``stored`` disagrees with this process's fingerprint."""
    current = environment_fingerprint(mesh_shape)
    mismatched = [
        f"{key}: bundle={stored.get(key)!r} here={current[key]!r}"
        for key in current
        if stored.get(key) != current[key]
    ]
    if mismatched:
        raise BundleMismatchError(
            "warm-start bundle fingerprint mismatch — "
            + "; ".join(mismatched)
        )


def default_bundle_dir(ckpt_dir: str | os.PathLike) -> pathlib.Path:
    """Where a bundle lives relative to its Orbax checkpoint directory:
    a ``warm_start/`` sibling (``<run>/checkpoints`` ->
    ``<run>/warm_start``)."""
    return pathlib.Path(ckpt_dir).absolute().parent / "warm_start"


def _aval_sig(x: t.Any) -> t.List[t.Any]:
    """JSON-able (shape, dtype) signature of one abstract value."""
    return [list(int(d) for d in x.shape), str(x.dtype)]


def _flat_avals(*args: t.Any) -> t.List[t.List[t.Any]]:
    """Flattened (shape, dtype) signatures of a call's arguments, in
    ``jax.export`` flattening order (tree_leaves of the args tuple)."""
    import jax

    return [_aval_sig(leaf) for leaf in jax.tree_util.tree_leaves(args)]


class WarmStartBundle:
    """A loaded (but not yet verified) bundle directory."""

    def __init__(self, root: pathlib.Path, manifest: t.Dict[str, t.Any]):
        self.root = pathlib.Path(root)
        self.manifest = manifest

    # ----------------------------------------------------------- layout

    @property
    def cache_dir(self) -> str:
        """The pre-populated persistent compilation cache — consumers
        point :func:`~torch_actor_critic_tpu.aot.cache
        .enable_persistent_cache` here."""
        return str(self.root / _XLA_CACHE)

    @property
    def fingerprint(self) -> t.Dict[str, t.Any]:
        return dict(self.manifest.get("fingerprint", {}))

    @property
    def buckets(self) -> t.Tuple[int, ...]:
        return tuple(int(b) for b in self.manifest.get("buckets", ()))

    @property
    def deterministic_only(self) -> bool:
        return bool(self.manifest.get("deterministic_only", False))

    def programs(self) -> t.Dict[str, t.Dict[str, t.Any]]:
        return dict(self.manifest.get("programs", {}))

    # ------------------------------------------------------------ checks

    def check(self, mesh_shape: t.Sequence[int] | None = None) -> None:
        """Environment-level compatibility gate (cheap, no
        deserialization). Per-program aval checks happen in the
        engine's bundle-armed warmup."""
        check_fingerprint(self.fingerprint, mesh_shape)

    def program_avals(self, name: str) -> t.List[t.List[t.Any]]:
        entry = self.manifest.get("programs", {}).get(name)
        if entry is None:
            raise BundleMismatchError(
                f"warm-start bundle has no program {name!r} "
                f"(bundled: {sorted(self.manifest.get('programs', {}))})"
            )
        return entry["in_avals"]

    def load_program(self, name: str):
        """Deserialize one program back to a ``jax.export.Exported``.
        Raises :class:`BundleMismatchError` for a missing or
        undeserializable entry."""
        from jax import export as jax_export

        entry = self.manifest.get("programs", {}).get(name)
        if entry is None:
            raise BundleMismatchError(
                f"warm-start bundle has no program {name!r} "
                f"(bundled: {sorted(self.manifest.get('programs', {}))})"
            )
        path = self.root / _PROGRAMS / entry["file"]
        try:
            data = path.read_bytes()
        except OSError as exc:
            raise BundleMismatchError(
                f"warm-start bundle program file missing: {path} ({exc})"
            ) from exc
        try:
            return jax_export.deserialize(data)
        except Exception as exc:  # noqa: BLE001 — any corruption shape
            raise BundleMismatchError(
                f"warm-start bundle program {name!r} failed to "
                f"deserialize ({type(exc).__name__}: {exc})"
            ) from exc

    def verify_program(
        self, name: str, *call_args: t.Any
    ):
        """Deserialize ``name`` and check its input avals against the
        avals of ``call_args`` (the exact arguments the consumer's jit
        program will be dispatched with). Returns the ``Exported`` on
        success; raises :class:`BundleMismatchError` otherwise."""
        exported = self.load_program(name)
        expected = _flat_avals(*call_args)
        got = [_aval_sig(a) for a in exported.in_avals]
        if got != expected:
            raise BundleMismatchError(
                f"warm-start bundle program {name!r} aval mismatch — "
                f"bundle={got} here={expected} (model/obs-spec/bucket "
                "drift since the bundle was built)"
            )
        return exported


def load_bundle(bundle_dir: str | os.PathLike) -> WarmStartBundle:
    """Read a bundle directory's manifest. Raises ``FileNotFoundError``
    when there is no bundle there, :class:`BundleMismatchError` when
    there is one but it is unreadable or a future format."""
    root = pathlib.Path(bundle_dir)
    manifest_path = root / _MANIFEST
    if not manifest_path.is_file():
        raise FileNotFoundError(
            f"no warm-start bundle at {root} (missing {_MANIFEST})"
        )
    try:
        manifest = json.loads(manifest_path.read_text())
    except (OSError, ValueError) as exc:
        raise BundleMismatchError(
            f"warm-start bundle manifest unreadable: {manifest_path} "
            f"({exc})"
        ) from exc
    fmt = manifest.get("format")
    if fmt != BUNDLE_FORMAT:
        raise BundleMismatchError(
            f"warm-start bundle format {fmt!r} != supported "
            f"{BUNDLE_FORMAT} — rebuild the bundle with this tree"
        )
    return WarmStartBundle(root, manifest)


def build_bundle(
    bundle_dir: str | os.PathLike,
    actor_def: t.Any,
    obs_spec: t.Any,
    params: t.Any,
    max_batch: int = 64,
    buckets: t.Sequence[int] | None = None,
    deterministic_only: bool = False,
) -> WarmStartBundle:
    """Build a warm-start bundle at ``bundle_dir``.

    Instantiates a real :class:`~torch_actor_critic_tpu.serve.engine
    .PolicyEngine`, points the persistent compilation cache at the
    bundle's ``xla_cache/`` and runs the engine's own warmup — so the
    cache entries are keyed by the *exact* jit programs every consumer
    dispatches — then ``jax.export``-serializes each manifest program
    for fingerprinting and bitwise verification. The builder's previous
    cache configuration is restored on exit.
    """
    import jax
    import numpy as np
    from jax import export as jax_export

    from torch_actor_critic_tpu.aot import cache as aot_cache
    from torch_actor_critic_tpu.aot.manifest import (
        entry_point_table,
        program_filename,
        serve_programs,
    )
    from torch_actor_critic_tpu.serve.engine import PolicyEngine

    root = pathlib.Path(bundle_dir)
    (root / _PROGRAMS).mkdir(parents=True, exist_ok=True)
    (root / _XLA_CACHE).mkdir(parents=True, exist_ok=True)

    engine = PolicyEngine(
        actor_def, obs_spec, max_batch=max_batch, buckets=buckets,
    )

    prev_cache = aot_cache.current_cache_dir()
    aot_cache.enable_persistent_cache(str(root / _XLA_CACHE), export_env=False)
    try:
        # The warmup below IS the cache-population pass: every
        # (bucket, deterministic) jit program compiles once and is
        # persisted unthresholded (aot/cache.py).
        engine.warmup(params, deterministic_only=deterministic_only)

        programs: t.Dict[str, t.Dict[str, t.Any]] = {}
        # jax.export cannot serialize typed-PRNG-key avals (no
        # flatbuffer dtype kind for key<fry>), so the sampled programs
        # are exported through a raw-uint32 wrapper: the serialized
        # program takes jax.random.key_data(key) and re-wraps inside.
        # Bitwise identical to the engine's typed-key program — only
        # the calling convention of the ARTIFACT differs (the engine's
        # own jit path, which the xla_cache serves, is untouched).
        key_data = jax.random.key_data(jax.random.key(0))

        def sampled_raw(params_, obs_, key_data_):
            return engine._fwd[False](
                params_, obs_, jax.random.wrap_key_data(key_data_)
            )

        sampled_raw_jit = jax.jit(sampled_raw)

        for spec in serve_programs(engine.buckets, deterministic_only):
            zero_obs = jax.tree_util.tree_map(
                lambda s: np.zeros(
                    (spec.bucket,) + tuple(s.shape), s.dtype
                ),
                obs_spec,
            )
            if spec.deterministic:
                call_args: t.Tuple[t.Any, ...] = (params, zero_obs)
                fn = engine._fwd[True]
            else:
                call_args = (params, zero_obs, key_data)
                fn = sampled_raw_jit
            exported = jax_export.export(fn)(*call_args)
            fname = program_filename(spec.name)
            (root / _PROGRAMS / fname).write_bytes(exported.serialize())
            programs[spec.name] = {
                "file": fname,
                "identity": spec.identity,
                "bucket": spec.bucket,
                "deterministic": spec.deterministic,
                "in_avals": _flat_avals(*call_args),
            }
    finally:
        # Restore without touching CACHE_ENV_VAR: the builder may run
        # inside a learner that already published a run-wide cache.
        if prev_cache:
            aot_cache.enable_persistent_cache(prev_cache, export_env=False)
        else:
            jax.config.update("jax_compilation_cache_dir", None)
            aot_cache._reset_backend_cache()

    entries = aot_cache.cache_entries(str(root / _XLA_CACHE))
    if entries == 0:
        logger.warning(
            "warm-start bundle %s: xla_cache is EMPTY after warmup — "
            "persistent-cache writes are being skipped on this "
            "backend; consumers will fall back to live compiles", root,
        )
    manifest = {
        "format": BUNDLE_FORMAT,
        "fingerprint": environment_fingerprint(),
        "buckets": [int(b) for b in engine.buckets],
        "max_batch": int(engine.max_batch),
        "deterministic_only": bool(deterministic_only),
        "entry_points": entry_point_table(),
        "cache_entries": entries,
        "programs": programs,
    }
    (root / _MANIFEST).write_text(json.dumps(manifest, indent=2))
    logger.info(
        "warm-start bundle built: %s (%d programs, %d cache entries)",
        root, len(programs), entries,
    )
    return WarmStartBundle(root, manifest)


def emit_bundle(
    ckpt_dir: str | os.PathLike,
    actor_def: t.Any,
    obs_spec: t.Any,
    params: t.Any,
    **kwargs: t.Any,
) -> WarmStartBundle:
    """Build the bundle at its checkpoint-adjacent default location
    (the learner's ``--emit-bundle`` path)."""
    return build_bundle(
        default_bundle_dir(ckpt_dir), actor_def, obs_spec, params,
        **kwargs,
    )
