"""TD3 loss functions (extension — the reference is SAC-only).

Twin Delayed DDPG (Fujimoto et al. 2018) over the same pure
(actor_apply, critic_apply) contract as
:mod:`torch_actor_critic_tpu.sac.losses`: the critic target uses a
smoothed target-policy action (clipped Gaussian noise on the target
actor's output), the policy maximizes the FIRST critic head only, and
both target networks update on the delayed-policy cadence (the delay
itself lives in :mod:`torch_actor_critic_tpu.td3.algorithm`).
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp

from torch_actor_critic_tpu.core.types import Batch


def critic_loss(
    critic_params: t.Any,
    *,
    actor_apply: t.Callable,
    critic_apply: t.Callable,
    target_actor_params: t.Any,
    target_critic_params: t.Any,
    batch: Batch,
    key: jax.Array,
    act_limit: float,
    target_noise: float,
    noise_clip: float,
    gamma: float,
    reward_scale: float,
    diagnostics: bool = False,
) -> t.Tuple[jax.Array, t.Dict[str, jax.Array]]:
    """Twin-critic Bellman MSE with target-policy smoothing.

    a' = clip(pi_targ(s') + clip(eps, +-noise_clip*act_limit),
    +-act_limit), eps ~ N(0, (target_noise*act_limit)^2);
    backup = reward_scale * r + gamma * (1 - done) * min_i Q_targ_i(s', a');
    loss = sum_i mean((Q_i(s, a) - backup)^2) — the same sum-of-head-MSEs
    shape as the SAC critic loss (and the reference's loss_q1 + loss_q2,
    ref ``sac/algorithm.py:69-74``), with the entropy term replaced by
    smoothing noise.
    """
    next_action, _ = actor_apply(
        target_actor_params, batch.next_states, None,
        deterministic=True, with_logprob=False,
    )
    noise = jnp.clip(
        target_noise * act_limit
        * jax.random.normal(key, next_action.shape),
        -noise_clip * act_limit,
        noise_clip * act_limit,
    )
    next_action = jnp.clip(next_action + noise, -act_limit, act_limit)
    q_target = critic_apply(target_critic_params, batch.next_states, next_action)
    backup = reward_scale * batch.rewards + gamma * (1.0 - batch.done) * jnp.min(
        q_target, axis=0
    )
    backup = jax.lax.stop_gradient(backup)

    q = critic_apply(critic_params, batch.states, batch.actions)  # (num_qs, B)
    loss = jnp.sum(jnp.mean((q - backup[None, :]) ** 2, axis=-1))
    aux = {"q_mean": jnp.mean(q), "backup_mean": jnp.mean(backup)}
    if diagnostics:
        # Raw surfaces for the in-graph Q/TD reductions (popped by the
        # learner before metrics; same contract as the SAC loss).
        aux["diag_q"] = jax.lax.stop_gradient(q)
        aux["diag_backup"] = backup
    return loss, aux


def actor_loss(
    actor_params: t.Any,
    *,
    actor_apply: t.Callable,
    critic_apply: t.Callable,
    critic_params: t.Any,
    batch: Batch,
    diagnostics: bool = False,
) -> t.Tuple[jax.Array, t.Dict[str, jax.Array]]:
    """Deterministic policy gradient loss: ``-mean(Q_1(s, pi(s)))``.

    TD3 deliberately uses only the first critic head here (not the min
    the SAC policy loss uses) — the twin exists to debias the BACKUP,
    not the policy objective. Critic params are not differentiated.
    """
    pi, _ = actor_apply(
        actor_params, batch.states, None,
        deterministic=True, with_logprob=False,
    )
    q_pi = critic_apply(critic_params, batch.states, pi)  # (num_qs, B)
    loss = -jnp.mean(q_pi[0])
    aux = {"q_pi_mean": jnp.mean(q_pi[0])}
    if diagnostics:
        aux["diag_pi"] = jax.lax.stop_gradient(pi)
    return loss, aux
