"""The TD3 learner: a second algorithm family over the same machinery.

Extension — the reference implements SAC only (ref ``sac/algorithm.py``)
despite its "actor-critic" name. TD3 (Fujimoto et al. 2018) reuses every
piece of this framework's infrastructure unchanged: the same
:class:`~torch_actor_critic_tpu.core.types.TrainState` pytree (its
``target_actor_params`` slot, ``None`` for SAC, holds the target
policy), the same HBM-resident replay, the same push-then-scan
``update_burst`` (:func:`torch_actor_critic_tpu.sac.algorithm.run_update_burst`),
the same ``DataParallelSAC`` mesh wrapper, Trainer host loop, Orbax
checkpointing and CLIs — algorithm choice is ``SACConfig.algorithm``.

The delayed policy/target update uses leafwise ``jnp.where`` selection
rather than ``lax.cond`` so the gradient ``pmean`` runs unconditionally
— collectives stay outside control flow, which every device must agree
on under ``shard_map``. The skipped steps freeze the policy optimizer
state too, matching the canonical algorithm (one Adam step per actual
policy update).
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from torch_actor_critic_tpu.ops.augment import augment_batch
from torch_actor_critic_tpu.core.types import Batch, BufferState, TrainState
from torch_actor_critic_tpu.diagnostics import ingraph as diag
from torch_actor_critic_tpu.ops.polyak import polyak_update
from torch_actor_critic_tpu.sac.algorithm import (
    Metrics,
    _shared_diagnostics,
    dynamic_lr_step,
    run_update_burst,
)
from torch_actor_critic_tpu.td3 import losses
from torch_actor_critic_tpu.utils.config import SACConfig


def _select_tree(pred: jax.Array, on_true: t.Any, on_false: t.Any) -> t.Any:
    """Leafwise ``where`` over matching pytrees (works across the mixed
    float/int leaves of optax states)."""
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), on_true, on_false
    )


class TD3:
    """TD3 learner over (actor_def, critic_def) Flax modules.

    Same contract as :class:`~torch_actor_critic_tpu.sac.algorithm.SAC`
    (``init_state`` / ``update`` / ``update_burst`` / ``select_action``),
    so everything that drives a SAC learner — the mesh wrapper, the
    Trainer, the bench — drives this one. ``actor_def`` must be a
    deterministic policy honoring the shared actor ``apply`` signature
    (:class:`~torch_actor_critic_tpu.models.actor.DeterministicActor`).
    """

    def __init__(
        self,
        config: SACConfig,
        actor_def: nn.Module,
        critic_def: nn.Module,
        act_dim: int,
    ):
        self.config = config
        self.actor_def = actor_def
        self.critic_def = critic_def
        self.act_dim = act_dim
        self.act_limit = float(getattr(actor_def, "act_limit", 1.0))
        self.pi_tx = optax.adam(config.lr)
        self.q_tx = optax.adam(config.lr)
        self._adam_core = optax.scale_by_adam()

    def default_hyperparams(self) -> t.Dict[str, jax.Array]:
        """PBT-perturbable hyperparameters (cf. SAC's): the two
        learning rates plus the target-policy smoothing noise std —
        TD3's temperature-analogue regularizer."""
        return {
            "actor_lr": jnp.float32(self.config.lr),
            "critic_lr": jnp.float32(self.config.lr),
            "target_noise": jnp.float32(self.config.target_noise),
        }

    # ------------------------------------------------------------------ init

    def init_state(self, key: jax.Array, example_obs: t.Any) -> TrainState:
        """Both target networks start as copies of their online nets
        (the TD3 analogue of the reference's ``deepcopy(critic)`` at
        train start, ref ``sac/algorithm.py:194-196``)."""
        k_actor, k_critic, k_sample, k_state = jax.random.split(key, 4)
        example_act = jnp.zeros((self.act_dim,))
        actor_params = self.actor_def.init(k_actor, example_obs, k_sample)
        critic_params = self.critic_def.init(k_critic, example_obs, example_act)
        copy = lambda p: jax.tree_util.tree_map(jnp.copy, p)  # noqa: E731
        return TrainState(
            step=jnp.int32(0),
            actor_params=actor_params,
            critic_params=critic_params,
            target_critic_params=copy(critic_params),
            target_actor_params=copy(actor_params),
            pi_opt_state=self.pi_tx.init(actor_params),
            q_opt_state=self.q_tx.init(critic_params),
            # TD3 has no entropy temperature; the TrainState slots hold
            # inert leaves so one state type serves both algorithms.
            log_alpha=jnp.float32(0.0),
            alpha_opt_state=optax.EmptyState(),
            rng=k_state,
        )

    # ----------------------------------------------------------- apply fns

    def _actor_apply(self, params, obs, key, **kw):
        return self.actor_def.apply(params, obs, key, **kw)

    def _critic_apply(self, params, obs, action):
        return self.critic_def.apply(params, obs, action)

    def select_action(
        self, params, obs, key: jax.Array | None = None, deterministic: bool = False
    ):
        """Exploration noise lives inside the actor module (clipped
        Gaussian, :class:`DeterministicActor`); ``deterministic=True``
        is the noiseless eval policy."""
        action, _ = self.actor_def.apply(
            params, obs, key, deterministic=deterministic, with_logprob=False
        )
        return action

    # -------------------------------------------------------------- update

    def update(
        self, state: TrainState, batch: Batch, axis_name: str | None = None
    ) -> t.Tuple[TrainState, Metrics]:
        """One TD3 gradient step: critic always; policy + BOTH target
        nets every ``policy_delay``-th step.

        The actor gradient is computed (and ``pmean``-averaged) every
        step but applied only on the delayed cadence — see the module
        docstring for why this beats ``lax.cond`` under ``shard_map``.

        Tier-gated diagnostics mirror the SAC learner's (same keys,
        same reductions — sac/algorithm.py), so the shared burst and
        the Trainer's epoch aggregation treat both algorithms alike.
        """
        cfg = self.config
        tier = cfg.diagnostics
        # Per-run hyperparameters (PBT) — see the matching note in
        # sac/algorithm.py.
        hp = state.hyperparams if state.hyperparams is not None else {}
        if cfg.frame_augment != "none" and cfg.pixel_pipeline != "fused":
            rng, key_q, key_aug = jax.random.split(state.rng, 3)
            batch = augment_batch(
                batch, key_aug, cfg.frame_augment, cfg.augment_pad
            )
        else:
            # Parity path: keep the historical 2-way split (see the
            # matching note in sac/algorithm.py; fused-pipeline frames
            # arrive pre-shifted, so no augmentation key here either).
            rng, key_q = jax.random.split(state.rng)

        # --- critic step (every step) ---
        (loss_q, q_aux), q_grads = jax.value_and_grad(
            losses.critic_loss, has_aux=True
        )(
            state.critic_params,
            actor_apply=self._actor_apply,
            critic_apply=self._critic_apply,
            target_actor_params=state.target_actor_params,
            target_critic_params=state.target_critic_params,
            batch=batch,
            key=key_q,
            act_limit=self.act_limit,
            target_noise=hp.get("target_noise", cfg.target_noise),
            noise_clip=cfg.noise_clip,
            gamma=cfg.gamma,
            reward_scale=cfg.reward_scale,
            diagnostics=tier != "off",
        )
        diag_q = q_aux.pop("diag_q", None)
        diag_backup = q_aux.pop("diag_backup", None)
        diag_metrics: Metrics = {}
        if tier != "off":
            diag_metrics["diag/grad_norm_q"] = diag.global_norm(q_grads)
        if axis_name is not None:
            q_grads = jax.lax.pmean(q_grads, axis_name)
        q_updates, q_opt_state = dynamic_lr_step(
            self._adam_core, self.q_tx, q_grads, state.q_opt_state,
            state.critic_params, hp.get("critic_lr"),
        )
        critic_params = optax.apply_updates(state.critic_params, q_updates)
        if tier != "off":
            diag_metrics["diag/update_ratio_q"] = diag.norm_ratio(
                q_updates, state.critic_params
            )

        # --- delayed policy + target updates ---
        # step is 0-based pre-increment: delay=d applies the policy on
        # the d-th, 2d-th, ... gradient step, like the canonical
        # "if it % policy_delay == 0" over a 0-based iteration counter
        # offset so the first burst ends on an applied update.
        do_pi = (state.step + 1) % cfg.policy_delay == 0
        (loss_pi, pi_aux), pi_grads = jax.value_and_grad(
            losses.actor_loss, has_aux=True
        )(
            state.actor_params,
            actor_apply=self._actor_apply,
            critic_apply=self._critic_apply,
            critic_params=critic_params,
            batch=batch,
            diagnostics=tier != "off",
        )
        diag_pi = pi_aux.pop("diag_pi", None)
        if tier != "off":
            diag_metrics["diag/grad_norm_pi"] = diag.global_norm(pi_grads)
        if axis_name is not None:
            pi_grads = jax.lax.pmean(pi_grads, axis_name)
        pi_updates, pi_opt_new = dynamic_lr_step(
            self._adam_core, self.pi_tx, pi_grads, state.pi_opt_state,
            state.actor_params, hp.get("actor_lr"),
        )
        actor_new = optax.apply_updates(state.actor_params, pi_updates)
        if tier != "off":
            # The ratio reports the CANDIDATE step; on skipped
            # (delayed) steps the applied update is zero by selection.
            diag_metrics["diag/update_ratio_pi"] = diag.norm_ratio(
                pi_updates, state.actor_params
            )

        actor_params = _select_tree(do_pi, actor_new, state.actor_params)
        pi_opt_state = _select_tree(do_pi, pi_opt_new, state.pi_opt_state)
        target_actor_params = _select_tree(
            do_pi,
            polyak_update(actor_params, state.target_actor_params, cfg.polyak),
            state.target_actor_params,
        )
        target_critic_params = _select_tree(
            do_pi,
            polyak_update(critic_params, state.target_critic_params, cfg.polyak),
            state.target_critic_params,
        )

        new_state = TrainState(
            step=state.step + 1,
            actor_params=actor_params,
            critic_params=critic_params,
            target_critic_params=target_critic_params,
            target_actor_params=target_actor_params,
            pi_opt_state=pi_opt_state,
            q_opt_state=q_opt_state,
            log_alpha=state.log_alpha,
            alpha_opt_state=state.alpha_opt_state,
            rng=rng,
            hyperparams=state.hyperparams,
        )
        metrics = {
            "loss_q": loss_q,
            "loss_pi": loss_pi,
            **q_aux,
            **pi_aux,
        }
        if tier != "off":
            metrics.update(diag_metrics)
            metrics.update(
                _shared_diagnostics(
                    cfg, loss_q, loss_pi, diag_q, diag_backup, diag_pi,
                    self.act_limit,
                )
            )
        return new_state, metrics

    # --------------------------------------------------------------- burst

    def update_burst(
        self,
        state: TrainState,
        buffer_state: BufferState,
        chunk: Batch,
        num_updates: int,
        axis_name: str | None = None,
    ) -> t.Tuple[TrainState, BufferState, Metrics]:
        """Same fused push-then-scan burst as SAC's (one device
        dispatch per ``update_every`` window)."""
        return run_update_burst(
            self.update, self.config, state, buffer_state, chunk,
            num_updates, axis_name,
        )
