"""TD3 (Twin Delayed DDPG) — the framework's second algorithm family.

Extension beyond the reference (which is SAC-only, ref
``sac/algorithm.py``): same TrainState/replay/burst/mesh/Trainer
machinery, selected with ``SACConfig.algorithm = "td3"`` (or
``--algorithm td3`` on the train CLI).
"""

from torch_actor_critic_tpu.td3.algorithm import TD3  # noqa: F401
from torch_actor_critic_tpu.td3 import losses  # noqa: F401
