"""Independent PyTorch SAC — the measured stand-in for the reference.

Same semantics and hyperparameter defaults as the reference run config
(ref ``main.py:147-160``: alpha=0.2 fixed, gamma=0.99, polyak=0.995,
batch 64, hidden [256,256], lr 3e-4), same squashed-Gaussian math (ref
``networks/linear.py:39-51``) and twin-critic Bellman update (ref
``sac/algorithm.py:30-74``), written functionally and shared by the
throughput benchmark (``bench.py``) and the return-parity runner
(``scripts/parity_run.py``) so the two baselines cannot drift.

This module shares NO code with ``/root/reference`` — it is the
project's own torch implementation of the published SAC equations.
"""

from __future__ import annotations

import typing as t


_MODS = None  # (np, torch, F) — imported once, on first use


def _mods():
    """Lazy module triple: torch stays un-imported until a baseline is
    actually built (same convention as the builders), but the per-step
    hot path pays one global check instead of three sys.modules
    lookups per call."""
    global _MODS
    if _MODS is None:
        import numpy as np
        import torch
        import torch.nn.functional as F

        _MODS = (np, torch, F)
    return _MODS


def _squashed_gaussian(mu, log_std, act_limit, deterministic):
    """Shared squashed-Gaussian sample + log-prob (ref
    ``networks/linear.py:39-51`` semantics) — one copy for the flat and
    visual actors so the distribution math cannot drift."""
    np, torch, F = _mods()

    log_std = torch.clip(log_std, -20, 2)
    std = torch.exp(log_std)
    u = mu if deterministic else mu + std * torch.randn_like(mu)
    a = torch.tanh(u) * act_limit
    logp = torch.distributions.Normal(mu, std).log_prob(u).sum(-1)
    logp = logp - (2 * (np.log(2) - u - F.softplus(-2 * u))).sum(-1)
    return a, logp


def _make_sac_update(actor, critics, targets, lr, alpha, gamma, polyak):
    """Shared SAC gradient step over tuple-observations.

    ``actor(*obs)`` -> (action, logp); ``critic(*obs, a)`` -> q. The
    flat and visual builders differ ONLY in network definitions and obs
    arity — the backup, twin-Q loss, frozen-critic policy step and
    polyak averaging live here once (the package docstring's 'cannot
    drift' contract, kept after the visual twin landed).
    Returns ``update(obs_tuple, a, r, obs2_tuple, d)``.
    """
    import torch

    for c, tgt in zip(critics, targets):
        tgt.load_state_dict(c.state_dict())
        for p in tgt.parameters():
            p.requires_grad_(False)
    pi_opt = torch.optim.Adam(actor.parameters(), lr=lr)
    q_opt = torch.optim.Adam(
        [p for c in critics for p in c.parameters()], lr=lr
    )

    def update(obs, a, r, obs2, d):
        with torch.no_grad():
            a2, logp2 = actor(*obs2)
            qt = torch.min(*(tg(*obs2, a2) for tg in targets))
            backup = r + gamma * (1 - d) * (qt - alpha * logp2)
        q1, q2 = (c(*obs, a) for c in critics)
        loss_q = ((q1 - backup) ** 2).mean() + ((q2 - backup) ** 2).mean()
        q_opt.zero_grad()
        loss_q.backward()
        q_opt.step()

        for c in critics:
            for p in c.parameters():
                p.requires_grad_(False)
        pi, logp = actor(*obs)
        loss_pi = (
            alpha * logp - torch.min(*(c(*obs, pi) for c in critics))
        ).mean()
        pi_opt.zero_grad()
        loss_pi.backward()
        pi_opt.step()
        for c in critics:
            for p in c.parameters():
                p.requires_grad_(True)

        with torch.no_grad():
            for c, tgt in zip(critics, targets):
                for pc, pt in zip(c.parameters(), tgt.parameters()):
                    pt.mul_(polyak).add_((1 - polyak) * pc)

    return update


def build_torch_sac(
    obs_dim: int,
    act_dim: int,
    act_limit: float = 1.0,
    hidden: t.Sequence[int] = (256, 256),
    lr: float = 3e-4,
    alpha: float = 0.2,
    gamma: float = 0.99,
    polyak: float = 0.995,
    num_threads: int = 2,
):
    """Build actor/critics and return ``(actor_fn, update_fn)``.

    - ``actor_fn(obs_batch, deterministic=False) -> (action, logp)``
      (torch tensors, no grad context managed by the caller);
    - ``update_fn(s, a, r, s2, d)`` runs one full SAC gradient step
      (critic, policy with frozen critic, polyak) on torch tensors.

    ``torch.set_num_threads(num_threads)`` mirrors ref ``main.py:130``.
    """
    import torch
    import torch.nn as nn

    torch.set_num_threads(num_threads)

    def mlp(sizes):
        layers = []
        for a, b in zip(sizes[:-1], sizes[1:]):
            layers += [nn.Linear(a, b), nn.ReLU()]
        return nn.Sequential(*layers)

    class Actor(nn.Module):
        def __init__(self):
            super().__init__()
            self.trunk = mlp([obs_dim, *hidden])
            self.mu = nn.Linear(hidden[-1], act_dim)
            self.log_std = nn.Linear(hidden[-1], act_dim)

        def forward(self, obs, deterministic=False):
            h = self.trunk(obs)
            return _squashed_gaussian(
                self.mu(h), self.log_std(h), act_limit, deterministic
            )

    class Critic(nn.Module):
        def __init__(self):
            super().__init__()
            net = mlp([obs_dim + act_dim, *hidden])
            net.append(nn.Linear(hidden[-1], 1))
            self.net = net

        def forward(self, s, a):
            return self.net(torch.cat([s, a], -1)).squeeze(-1)

    actor = Actor()
    critics = [Critic(), Critic()]
    targets = [Critic(), Critic()]
    inner = _make_sac_update(actor, critics, targets, lr, alpha, gamma, polyak)

    def update(s, a, r, s2, d):
        inner((s,), a, r, (s2,), d)

    return actor, update


def build_torch_visual_sac(
    feature_dim: int,
    frame_hw: t.Tuple[int, int],
    frame_channels: int,
    act_dim: int,
    act_limit: float = 1.0,
    hidden: t.Sequence[int] = (256, 256),
    cnn_features: int = 1,
    lr: float = 3e-4,
    alpha: float = 0.2,
    gamma: float = 0.99,
    polyak: float = 0.995,
    num_threads: int = 2,
):
    """Visual (CNN) twin of :func:`build_torch_sac` — the measured torch
    stand-in for the reference's pixel stack (BASELINE config 5).

    Same architecture semantics as the reference visual networks
    (ref ``networks/convolutional.py:30-183``): Atari-DQN conv trunk
    (filters [32,64,64], kernels [8,4,3], strides [4,2,1], VALID
    padding) -> Dense(512) -> Dense(``cnn_features``, default 1 — the
    scalar-vision bottleneck), concatenated with the proprioceptive MLP;
    the critic ReLUs through every MLP layer including the width-1
    output then applies the final ``Linear(1+cnn_features, 1)``. NCHW
    float frames, as the reference stores them. Shares no code with
    ``/root/reference``.

    Returns ``(actor_fn, update_fn)``; ``update_fn(feat, frame, a, r,
    feat2, frame2, d)`` runs one full SAC gradient step.
    """
    import torch
    import torch.nn as nn

    torch.set_num_threads(num_threads)

    def mlp(sizes, relu_final=False):
        layers = []
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            layers.append(nn.Linear(a, b))
            if relu_final or i < len(sizes) - 2:
                layers.append(nn.ReLU())
        return nn.Sequential(*layers)

    def cnn():
        h, w = frame_hw
        convs = []
        c = frame_channels
        for f, k, s in zip((32, 64, 64), (8, 4, 3), (4, 2, 1)):
            convs += [nn.Conv2d(c, f, k, s), nn.ReLU()]
            c = f
            h = (h - k) // s + 1
            w = (w - k) // s + 1
        return nn.Sequential(
            *convs, nn.Flatten(),
            nn.Linear(c * h * w, 512), nn.Linear(512, cnn_features),
        )

    class Actor(nn.Module):
        def __init__(self):
            super().__init__()
            self.trunk = mlp([feature_dim, *hidden], relu_final=True)
            self.vision = cnn()
            self.mu = nn.Linear(hidden[-1] + cnn_features, act_dim)
            self.log_std = nn.Linear(hidden[-1] + cnn_features, act_dim)

        def forward(self, feat, frame, deterministic=False):
            h = torch.cat([self.trunk(feat), self.vision(frame)], -1)
            return _squashed_gaussian(
                self.mu(h), self.log_std(h), act_limit, deterministic
            )

    class Critic(nn.Module):
        def __init__(self):
            super().__init__()
            # ReLU through every layer incl. the width-1 output — the
            # reference quirk (ref convolutional.py:156-158).
            self.trunk = mlp([feature_dim + act_dim, *hidden, 1], relu_final=True)
            self.vision = cnn()
            self.final = nn.Linear(1 + cnn_features, 1)

        def forward(self, feat, frame, act):
            x = self.trunk(torch.cat([feat, act], -1))
            x = torch.cat([x, self.vision(frame)], -1)
            return self.final(x).squeeze(-1)

    actor = Actor()
    critics = [Critic(), Critic()]
    targets = [Critic(), Critic()]
    inner = _make_sac_update(actor, critics, targets, lr, alpha, gamma, polyak)

    def update(feat, frame, a, r, feat2, frame2, d):
        inner((feat, frame), a, r, (feat2, frame2), d)

    return actor, update
