"""Independent PyTorch SAC — the measured stand-in for the reference.

Same semantics and hyperparameter defaults as the reference run config
(ref ``main.py:147-160``: alpha=0.2 fixed, gamma=0.99, polyak=0.995,
batch 64, hidden [256,256], lr 3e-4), same squashed-Gaussian math (ref
``networks/linear.py:39-51``) and twin-critic Bellman update (ref
``sac/algorithm.py:30-74``), written functionally and shared by the
throughput benchmark (``bench.py``) and the return-parity runner
(``scripts/parity_run.py``) so the two baselines cannot drift.

This module shares NO code with ``/root/reference`` — it is the
project's own torch implementation of the published SAC equations.
"""

from __future__ import annotations

import typing as t


def build_torch_sac(
    obs_dim: int,
    act_dim: int,
    act_limit: float = 1.0,
    hidden: t.Sequence[int] = (256, 256),
    lr: float = 3e-4,
    alpha: float = 0.2,
    gamma: float = 0.99,
    polyak: float = 0.995,
    num_threads: int = 2,
):
    """Build actor/critics and return ``(actor_fn, update_fn)``.

    - ``actor_fn(obs_batch, deterministic=False) -> (action, logp)``
      (torch tensors, no grad context managed by the caller);
    - ``update_fn(s, a, r, s2, d)`` runs one full SAC gradient step
      (critic, policy with frozen critic, polyak) on torch tensors.

    ``torch.set_num_threads(num_threads)`` mirrors ref ``main.py:130``.
    """
    import numpy as np
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    torch.set_num_threads(num_threads)

    def mlp(sizes):
        layers = []
        for a, b in zip(sizes[:-1], sizes[1:]):
            layers += [nn.Linear(a, b), nn.ReLU()]
        return nn.Sequential(*layers)

    class Actor(nn.Module):
        def __init__(self):
            super().__init__()
            self.trunk = mlp([obs_dim, *hidden])
            self.mu = nn.Linear(hidden[-1], act_dim)
            self.log_std = nn.Linear(hidden[-1], act_dim)

        def forward(self, obs, deterministic=False):
            h = self.trunk(obs)
            mu = self.mu(h)
            log_std = torch.clip(self.log_std(h), -20, 2)
            std = torch.exp(log_std)
            u = mu if deterministic else mu + std * torch.randn_like(mu)
            a = torch.tanh(u) * act_limit
            logp = torch.distributions.Normal(mu, std).log_prob(u).sum(-1)
            logp = logp - (2 * (np.log(2) - u - F.softplus(-2 * u))).sum(-1)
            return a, logp

    def critic():
        net = mlp([obs_dim + act_dim, *hidden])
        net.append(nn.Linear(hidden[-1], 1))
        return net

    actor = Actor()
    critics = [critic(), critic()]
    targets = [critic(), critic()]
    for c, tgt in zip(critics, targets):
        tgt.load_state_dict(c.state_dict())
        for p in tgt.parameters():
            p.requires_grad_(False)
    pi_opt = torch.optim.Adam(actor.parameters(), lr=lr)
    q_opt = torch.optim.Adam(
        [p for c in critics for p in c.parameters()], lr=lr
    )

    def q_of(nets, s, a):
        x = torch.cat([s, a], -1)
        return [net(x).squeeze(-1) for net in nets]

    def update(s, a, r, s2, d):
        with torch.no_grad():
            a2, logp2 = actor(s2)
            qt = torch.min(*q_of(targets, s2, a2))
            backup = r + gamma * (1 - d) * (qt - alpha * logp2)
        q1, q2 = q_of(critics, s, a)
        loss_q = ((q1 - backup) ** 2).mean() + ((q2 - backup) ** 2).mean()
        q_opt.zero_grad()
        loss_q.backward()
        q_opt.step()

        for c in critics:
            for p in c.parameters():
                p.requires_grad_(False)
        pi, logp = actor(s)
        loss_pi = (alpha * logp - torch.min(*q_of(critics, s, pi))).mean()
        pi_opt.zero_grad()
        loss_pi.backward()
        pi_opt.step()
        for c in critics:
            for p in c.parameters():
                p.requires_grad_(True)

        with torch.no_grad():
            for c, tgt in zip(critics, targets):
                for pc, pt in zip(c.parameters(), tgt.parameters()):
                    pt.mul_(polyak).add_((1 - polyak) * pc)

    return actor, update
