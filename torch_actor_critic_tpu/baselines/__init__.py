"""Measured-baseline implementations (the reference publishes no
numbers, BASELINE.md): independent PyTorch code used by ``bench.py``
(throughput baseline) and ``scripts/parity_run.py`` (return-parity
baseline). One implementation so the two comparisons can never drift
apart."""

from torch_actor_critic_tpu.baselines.torch_sac import (  # noqa: F401
    build_torch_sac,
    build_torch_visual_sac,
)
