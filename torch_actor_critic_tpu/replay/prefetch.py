"""Async double-buffered host→HBM refill for the tiered store.

The refill half of the waterfall (docs/REPLAY.md): a background thread
samples the host tier into ready-to-push ``(n_envs, refill_window)``
numpy chunks and parks them in a depth-2 queue, so when the train loop
reaches a window boundary the host→device copy is already staged and
rides the same async dispatch stream as the update burst — the copy
hides behind the burst instead of serializing after it (the
``ops/pixels.py`` scalar-prefetch gather is the in-kernel analogue of
the same idea, one level down).

The device push is its OWN jitted program (``replay/prefetch_push`` in
the checked ENTRY_POINTS table): ``jax.vmap`` of the single-ring
``push`` over the device axis, exactly like
:meth:`~torch_actor_critic_tpu.parallel.dp.DataParallelSAC.push_chunk`
but compiled for the refill chunk's shapes — reusing the warmup push's
cache entry would interleave two chunk geometries through one
dispatch site and re-trace on every boundary. Dispatch runs under the
recompilation watchdog's source scope and the program registers its
XLA cost analysis like every other entry point
(analysis/contracts.py).

With ``replay_prefetch=False`` the sampler runs synchronously at the
boundary (the stall the async path exists to hide — ``bench.py
--stage=replay`` measures the difference). Either way the TRAIN loop
performs the actual device push; the thread only ever touches host
memory.
"""

from __future__ import annotations

import queue
import threading
import time
import typing as t

import numpy as np

from torch_actor_critic_tpu.core.types import Batch, BufferState

if t.TYPE_CHECKING:
    from torch_actor_critic_tpu.replay.tiers import TieredReplay

__all__ = ["RefillPrefetcher"]


class RefillPrefetcher:
    """Samples the host tier into refill chunks ahead of the loop.

    ``refill_rows`` is rows per env per window (config
    ``replay_refill``); a refill chunk therefore has leading axes
    ``(n_envs, refill_rows)`` — same layout contract as the trainer's
    env chunk, so :func:`~torch_actor_critic_tpu.parallel.dp.
    shard_chunk_from_local` places it identically.
    """

    # The cost-registry/watchdog source name of the refill push program
    # (checked ENTRY_POINTS + contract tables, analysis/).
    push_cost_name = "replay/prefetch_push"

    def __init__(
        self,
        tiered: "TieredReplay",
        n_envs: int,
        refill_rows: int,
        async_prefetch: bool = True,
        depth: int = 2,
        idle_sleep_s: float = 0.005,
    ):
        if refill_rows < 1:
            raise ValueError(
                f"refill_rows must be >= 1, got {refill_rows}"
            )
        self.tiered = tiered
        self.n_envs = int(n_envs)
        self.refill_rows = int(refill_rows)
        self.async_prefetch = bool(async_prefetch)
        self._idle_sleep_s = float(idle_sleep_s)
        self._q: "queue.Queue[Batch]" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._push = None
        self._cost_registered = False
        self.refills_served = 0
        self.stalls_total = 0
        self.requests_total = 0
        if self.async_prefetch:
            self._thread = threading.Thread(
                target=self._run, name="replay-prefetch", daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------ sampling

    def _sample_local_chunk(self) -> Batch | None:
        """One ``(n_envs, refill_rows)`` numpy chunk off the host tier,
        or ``None`` while it is still empty."""
        import jax

        from torch_actor_critic_tpu.replay.diskstore import rows_to_batch

        rows = self.tiered.sample_refill(self.n_envs * self.refill_rows)
        if rows is None:
            return None
        flat = rows_to_batch(rows)
        lead = (self.n_envs, self.refill_rows)
        return jax.tree_util.tree_map(
            lambda x: np.asarray(x).reshape(lead + x.shape[1:]), flat
        )

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._q.full():
                time.sleep(self._idle_sleep_s)
                continue
            chunk = self._sample_local_chunk()
            if chunk is None:
                time.sleep(self._idle_sleep_s)
                continue
            try:
                self._q.put(chunk, timeout=0.1)
            except queue.Full:
                pass

    def poll_local_chunk(self) -> Batch | None:
        """The train loop's boundary call: the staged chunk if one is
        ready. Synchronous mode samples on demand (the measured stall);
        async mode never blocks — an empty queue after the host tier
        warmed up counts a prefetch stall and skips this boundary."""
        self.requests_total += 1
        if not self.async_prefetch:
            return self._sample_local_chunk()
        try:
            chunk = self._q.get_nowait()
        except queue.Empty:
            if self.tiered.host.size > 0:
                self.stalls_total += 1
            return None
        return chunk

    # -------------------------------------------------------- device push

    def _build_push(self, buf_shardings=None, chunk_shardings=None):
        """The ``replay/prefetch_push`` jit program: vmapped single-ring
        push over the device axis, donating the ring (in-place update,
        exactly the warmup-push donation contract)."""
        import jax

        from torch_actor_critic_tpu.buffer.replay import push

        def _vpush(buffer: BufferState, chunk: Batch) -> BufferState:
            return jax.vmap(push)(buffer, chunk)

        if buf_shardings is not None:
            return jax.jit(
                _vpush,
                donate_argnums=(0,),
                in_shardings=(buf_shardings, chunk_shardings),
                out_shardings=buf_shardings,
            )
        return jax.jit(_vpush, donate_argnums=(0,))

    def push_into(
        self,
        buffer: BufferState,
        chunk: Batch,
        buf_shardings=None,
        chunk_shardings=None,
    ) -> BufferState:
        """Push a placed refill chunk into the sharded ring under the
        watchdog's source scope (compiles here are attributed to
        ``replay/prefetch_push``; post-steady ones are anomalies)."""
        from torch_actor_critic_tpu.diagnostics.watchdog import get_watchdog

        from torch_actor_critic_tpu.aot.cache import cache_excluded

        if self._push is None:
            self._push = self._build_push(buf_shardings, chunk_shardings)
        # cache_excluded: donated train-plane executables are unsafe to
        # deserialize from the persistent compilation cache (see
        # aot/cache.py) — always compile live.
        with get_watchdog().source(self.push_cost_name), cache_excluded():
            out = self._push(buffer, chunk)
        self.refills_served += 1
        return out

    def maybe_register_cost(self, buffer_abstract, chunk_abstract,
                            devices: int = 1) -> None:
        """Register the push program's XLA cost analysis once (contract
        table: ``replay/prefetch_push`` cost registration). Abstract
        args only — the real buffers were donated."""
        if self._cost_registered or self._push is None:
            return
        self._cost_registered = True
        from torch_actor_critic_tpu.telemetry.costmodel import (
            get_cost_registry,
        )

        get_cost_registry().register_jit(
            self.push_cost_name, self._push, buffer_abstract,
            chunk_abstract, devices=devices,
        )

    # ------------------------------------------------------- observability

    def metrics(self) -> dict:
        served = max(self.requests_total, 1)
        return {
            "replay/refills_served": float(self.refills_served),
            "replay/prefetch_stalls_total": float(self.stalls_total),
            "replay/prefetch_hit_rate": float(
                1.0 - self.stalls_total / served
            ),
        }

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
