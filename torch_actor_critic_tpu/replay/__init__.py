"""Tiered experience store: HBM ring ↔ host RAM ↔ disk (docs/REPLAY.md).

The device ring (:mod:`torch_actor_critic_tpu.buffer.replay`) stays
tier 0, bitwise-untouched; this package adds the host-side hierarchy
underneath it — a host-RAM ring shadowing the device ring's eviction
stream (:class:`~.tiers.HostRing`), an append-only chunked disk tier
(:class:`~.diskstore.DiskTier`), counted spill/refill flows with a
per-tier conservation invariant (:class:`~.tiers.TieredReplay`), async
double-buffered host→HBM refill (:class:`~.prefetch.RefillPrefetcher`),
a serve-side transition logger in the same chunk format
(:class:`~.flywheel.TransitionLogger`), and ``train.py --offline``
(:mod:`~.offline`) which trains regularized SAC purely from a disk
tier. All of it default-off: ``replay_tiers="off"`` traces, samples
and logs bitwise-identically to a build without this package.
"""

from __future__ import annotations

import os
import typing as t

from torch_actor_critic_tpu.replay.diskstore import (
    DISK_EVICTION_POLICIES,
    DiskTier,
    batch_to_rows,
    concat_rows,
    obs_spec_from_json,
    obs_spec_to_json,
    rows_count,
    rows_nbytes,
    rows_to_batch,
    slice_rows,
)
from torch_actor_critic_tpu.replay.flywheel import TransitionLogger
from torch_actor_critic_tpu.replay.offline import (
    OFFLINE_REGULARIZERS,
    OfflineLearner,
    train_offline,
)
from torch_actor_critic_tpu.replay.prefetch import RefillPrefetcher
from torch_actor_critic_tpu.replay.tiers import (
    REPLAY_PRIORITIES,
    HostRing,
    StripedHostRing,
    TieredReplay,
)

__all__ = [
    "DISK_EVICTION_POLICIES",
    "DiskTier",
    "HostRing",
    "OFFLINE_REGULARIZERS",
    "OfflineLearner",
    "REPLAY_PRIORITIES",
    "RefillPrefetcher",
    "StripedHostRing",
    "TieredReplay",
    "TransitionLogger",
    "batch_to_rows",
    "build_tiered_replay",
    "concat_rows",
    "obs_spec_from_json",
    "obs_spec_to_json",
    "rows_count",
    "rows_nbytes",
    "rows_to_batch",
    "slice_rows",
    "train_offline",
]


def build_tiered_replay(
    config,
    obs_spec: t.Any,
    act_dim: int,
    hbm_capacity: int,
    act_limit: float = 1.0,
    run_dir: str | None = None,
    seed: int = 0,
    n_stripes: int = 0,
) -> TieredReplay:
    """Construct the tier stack the config asks for.

    ``replay_tiers="host"`` builds HBM+host only (spill past the host
    ring is counted ``dropped_nodisk_total``); ``"disk"`` adds the
    chunked disk tier at ``replay_dir`` (default: ``<run_dir>/replay``)
    and stamps its meta so ``--offline`` can later reconstruct models
    from the directory alone. ``n_stripes > 0`` gives the host tier
    per-task sub-rings (``buffer/striped.py`` routing) so refill stays
    task-balanced. Callers gate on ``config.replay_tiers != "off"`` —
    this factory assumes tiers are wanted.
    """
    disk = None
    if config.replay_tiers == "disk":
        directory = config.replay_dir
        if not directory:
            if not run_dir:
                raise ValueError(
                    "replay_tiers='disk' needs --replay-dir (no tracker "
                    "run dir to default under)"
                )
            directory = os.path.join(run_dir, "replay")
        disk = DiskTier(
            directory,
            max_bytes=config.replay_disk_bytes,
            policy=config.replay_disk_policy,
        )
        disk.ensure_meta({
            "obs": obs_spec_to_json(obs_spec),
            "act_dim": int(act_dim),
            "act_limit": float(act_limit),
            "source": "trainer",
        })
    host_capacity = config.replay_host_capacity or config.buffer_size
    return TieredReplay(
        hbm_capacity=hbm_capacity,
        host_capacity=host_capacity,
        disk=disk,
        priority=config.replay_priority,
        seed=seed,
        n_stripes=n_stripes,
    )
