"""Append-only chunked disk tier for the tiered experience store.

The coldest tier of :mod:`torch_actor_critic_tpu.replay` (docs/
REPLAY.md): transitions that fell off the host ring land here as
``chunk-NNNNNNNN.npz`` files plus one ``manifest.jsonl`` line per
append, under a directory with a ``meta.json`` schema descriptor. The
same format serves three producers —

- the training-side spill flow (:class:`~torch_actor_critic_tpu.replay.
  tiers.TieredReplay` with ``replay_tiers=disk``),
- the serve-side flywheel logger (:mod:`~torch_actor_critic_tpu.replay.
  flywheel`), and
- anything external that writes conforming chunks —

so ``train.py --offline`` reads one format regardless of where the
experience came from.

**Counters reconstruct from the manifest.** Eviction deletes a chunk's
*file* but never its manifest line; reopening a directory replays the
manifest in order and classifies every line: rows whose file still
exists are resident, rows whose file is gone were evicted, and
``{"event": "drop"}`` lines record rows the ``stop`` policy refused
(offered but never stored, so not part of ``received_total``). The
per-tier conservation invariant therefore survives process death::

    received_total == rows (resident) + evicted_rows_total

**Row format** (shared with the host tier): a *rows* value is a dict of
numpy arrays under flat keys — ``"states"``/``"next_states"`` for flat
observations or ``"states.features"``/``"states.frame"`` (dito
``next_states.*``) for :class:`~torch_actor_critic_tpu.core.types.
MultiObservation` — plus ``"actions"``, ``"rewards"``, ``"done"``; the
leading axis is the row count. :func:`batch_to_rows` /
:func:`rows_to_batch` convert to/from the device-facing ``Batch``
pytree.
"""

from __future__ import annotations

import json
import threading
import typing as t
from collections import OrderedDict
from pathlib import Path

import numpy as np

from torch_actor_critic_tpu.core.types import Batch, MultiObservation

__all__ = [
    "DiskTier",
    "batch_to_rows",
    "rows_to_batch",
    "rows_count",
    "rows_nbytes",
    "concat_rows",
    "slice_rows",
    "obs_spec_to_json",
    "obs_spec_from_json",
    "DISK_EVICTION_POLICIES",
]

DISK_EVICTION_POLICIES = ("fifo", "stop")

_OBS_KEYS = ("states", "next_states")


# ------------------------------------------------------------- row format


def _leading(x: np.ndarray, n_lead: int) -> np.ndarray:
    """Merge ``n_lead`` leading axes into one row axis."""
    x = np.asarray(x)
    if n_lead == 1:
        return x
    return x.reshape((-1,) + x.shape[n_lead:])


def batch_to_rows(chunk: Batch, n_lead: int = 1) -> t.Dict[str, np.ndarray]:
    """``Batch`` pytree -> flat-key host rows.

    ``n_lead=2`` merges the trainer's ``(n_envs, window)`` chunk axes
    into one row axis (row order: env-major, matching the device ring's
    vmapped per-shard push order within a shard).
    """
    rows: t.Dict[str, np.ndarray] = {}
    for key in _OBS_KEYS:
        obs = getattr(chunk, key)
        if isinstance(obs, MultiObservation):
            rows[f"{key}.features"] = _leading(obs.features, n_lead)
            rows[f"{key}.frame"] = _leading(obs.frame, n_lead)
        else:
            rows[key] = _leading(obs, n_lead)
    rows["actions"] = _leading(chunk.actions, n_lead)
    rows["rewards"] = _leading(chunk.rewards, n_lead)
    rows["done"] = _leading(chunk.done, n_lead)
    return rows


def rows_to_batch(rows: t.Mapping[str, np.ndarray]) -> Batch:
    """Flat-key host rows -> ``Batch`` (numpy leaves)."""

    def obs(key):
        if key in rows:
            return np.asarray(rows[key])
        return MultiObservation(
            features=np.asarray(rows[f"{key}.features"]),
            frame=np.asarray(rows[f"{key}.frame"]),
        )

    return Batch(
        states=obs("states"),
        actions=np.asarray(rows["actions"]),
        rewards=np.asarray(rows["rewards"]),
        next_states=obs("next_states"),
        done=np.asarray(rows["done"]),
    )


def rows_count(rows: t.Mapping[str, np.ndarray]) -> int:
    return int(next(iter(rows.values())).shape[0])


def rows_nbytes(rows: t.Mapping[str, np.ndarray]) -> int:
    return int(sum(np.asarray(v).nbytes for v in rows.values()))


def concat_rows(
    parts: t.Sequence[t.Mapping[str, np.ndarray]],
) -> t.Dict[str, np.ndarray]:
    if not parts:
        raise ValueError("concat_rows: empty sequence")
    return {
        k: np.concatenate([np.asarray(p[k]) for p in parts], axis=0)
        for k in parts[0]
    }


def slice_rows(
    rows: t.Mapping[str, np.ndarray], idx: t.Any
) -> t.Dict[str, np.ndarray]:
    """Gather rows at ``idx`` (an index array or slice)."""
    return {k: np.asarray(v)[idx] for k, v in rows.items()}


# --------------------------------------------------------- spec round-trip


def obs_spec_to_json(obs_spec: t.Any) -> dict:
    """Observation spec -> the ``meta.json`` descriptor."""
    if isinstance(obs_spec, MultiObservation):
        return {
            "kind": "multi",
            "features_shape": list(obs_spec.features.shape),
            "features_dtype": np.dtype(obs_spec.features.dtype).name,
            "frame_shape": list(obs_spec.frame.shape),
            "frame_dtype": np.dtype(obs_spec.frame.dtype).name,
        }
    return {
        "kind": "flat",
        "shape": list(obs_spec.shape),
        "dtype": np.dtype(obs_spec.dtype).name,
    }


def obs_spec_from_json(desc: t.Mapping[str, t.Any]) -> t.Any:
    import jax

    if desc["kind"] == "multi":
        return MultiObservation(
            features=jax.ShapeDtypeStruct(
                tuple(desc["features_shape"]), np.dtype(desc["features_dtype"])
            ),
            frame=jax.ShapeDtypeStruct(
                tuple(desc["frame_shape"]), np.dtype(desc["frame_dtype"])
            ),
        )
    return jax.ShapeDtypeStruct(tuple(desc["shape"]), np.dtype(desc["dtype"]))


# ---------------------------------------------------------------- the tier


class DiskTier:
    """One chunked on-disk transition store under ``directory``.

    Thread-safe (the flywheel appends from HTTP handler threads while
    ``/metrics`` snapshots). ``max_bytes=0`` means unbounded; with a
    bound, ``policy="fifo"`` deletes oldest chunk files (manifest lines
    stay — that IS the eviction record) and ``policy="stop"`` refuses
    new appends (counted ``dropped_rows_total``). At least one resident
    chunk is always kept under ``fifo`` so the tier cannot evict itself
    empty.
    """

    SCHEMA = 1

    def __init__(
        self,
        directory: str | Path,
        max_bytes: int = 0,
        policy: str = "fifo",
        cache_chunks: int = 4,
    ):
        if policy not in DISK_EVICTION_POLICIES:
            raise ValueError(
                f"disk policy must be one of {DISK_EVICTION_POLICIES}, "
                f"got {policy!r}"
            )
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_bytes = int(max_bytes)
        self.policy = policy
        self._lock = threading.Lock()
        # (seq, path, rows, nbytes) of RESIDENT chunks, oldest first.
        self._chunks: t.List[t.Tuple[int, Path, int, int]] = []
        self._cache: "OrderedDict[int, dict]" = OrderedDict()
        self._cache_chunks = max(1, int(cache_chunks))
        self._next_seq = 0  # guarded-by: _lock
        self.received_total = 0  # guarded-by: _lock
        self.evicted_rows_total = 0  # guarded-by: _lock
        self.evicted_files_total = 0  # guarded-by: _lock
        self.dropped_rows_total = 0  # guarded-by: _lock
        self._meta: dict | None = None  # guarded-by: _lock
        with self._lock:
            self._reopen_locked()

    # -------------------------------------------------------------- reopen

    @property
    def _meta_path(self) -> Path:
        return self.directory / "meta.json"

    @property
    def _manifest_path(self) -> Path:
        return self.directory / "manifest.jsonl"

    def _reopen_locked(self) -> None:
        """Reconstruct counters + the resident chunk list from the
        manifest (module docstring: eviction keeps manifest lines)."""
        if self._meta_path.exists():
            self._meta = json.loads(self._meta_path.read_text())
        if not self._manifest_path.exists():
            return
        for line in self._manifest_path.read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("event") == "drop":
                self.dropped_rows_total += int(rec["rows"])
                continue
            seq, rows = int(rec["seq"]), int(rec["rows"])
            self._next_seq = max(self._next_seq, seq + 1)
            self.received_total += rows
            path = self.directory / rec["file"]
            if path.exists():
                self._chunks.append(
                    (seq, path, rows, int(rec.get("nbytes", 0)))
                )
            else:
                self.evicted_rows_total += rows
                self.evicted_files_total += 1

    # ---------------------------------------------------------------- meta

    @property
    def meta(self) -> dict | None:
        with self._lock:
            return self._meta

    def ensure_meta(self, meta: t.Mapping[str, t.Any]) -> None:
        """Write ``meta.json`` on first use, validate on reopen — two
        writers with different geometry must fail loudly, not produce a
        dataset that silently mixes shapes."""
        with self._lock:
            meta = dict(meta, schema=self.SCHEMA)
            if self._meta is None:
                self._meta = meta
                self._meta_path.write_text(json.dumps(meta, indent=2))
                return
            for key in ("obs", "act_dim"):
                if key in meta and self._meta.get(key) != meta[key]:
                    raise ValueError(
                        f"disk tier at {self.directory} was written with "
                        f"{key}={self._meta.get(key)!r}; this writer has "
                        f"{key}={meta[key]!r}"
                    )

    # -------------------------------------------------------------- append

    def append(self, rows: t.Mapping[str, np.ndarray]) -> int:
        """Append one chunk of rows; returns the rows actually stored
        (0 when the ``stop`` policy refused them)."""
        n = rows_count(rows)
        if n == 0:
            return 0
        with self._lock:
            if (
                self.policy == "stop"
                and self.max_bytes
                and self._bytes_locked() + rows_nbytes(rows) > self.max_bytes
            ):
                self.dropped_rows_total += n
                self._manifest_append({"event": "drop", "rows": n})
                return 0
            seq = self._next_seq
            self._next_seq += 1
            path = self.directory / f"chunk-{seq:08d}.npz"
            # npz keys cannot hold dots; mangle and restore on load.
            np.savez(
                path, **{k.replace(".", "__"): v for k, v in rows.items()}
            )
            nbytes = path.stat().st_size
            self._chunks.append((seq, path, n, nbytes))
            self.received_total += n
            self._manifest_append(
                {"seq": seq, "file": path.name, "rows": n, "nbytes": nbytes}
            )
            if self.policy == "fifo" and self.max_bytes:
                self._evict_over_budget_locked()
            return n

    def _manifest_append(self, rec: dict) -> None:
        with self._manifest_path.open("a") as f:
            f.write(json.dumps(rec) + "\n")

    def _bytes_locked(self) -> int:
        return sum(c[3] for c in self._chunks)

    def _evict_over_budget_locked(self) -> None:
        while len(self._chunks) > 1 and self._bytes_locked() > self.max_bytes:
            seq, path, rows, _ = self._chunks.pop(0)
            path.unlink(missing_ok=True)
            self._cache.pop(seq, None)
            self.evicted_rows_total += rows
            self.evicted_files_total += 1

    # --------------------------------------------------------------- reads

    def _load_chunk_locked(self, seq: int, path: Path) -> dict:
        cached = self._cache.get(seq)
        if cached is not None:
            self._cache.move_to_end(seq)
            return cached
        with np.load(path) as z:
            rows = {k.replace("__", "."): z[k] for k in z.files}
        self._cache[seq] = rows
        while len(self._cache) > self._cache_chunks:
            self._cache.popitem(last=False)
        return rows

    def sample(self, rng: np.random.Generator, n: int) -> dict:
        """Uniform draw of ``n`` rows (with replacement) over every
        resident chunk, via one global row index per draw."""
        with self._lock:
            chunks = list(self._chunks)
            if not chunks:
                raise ValueError(
                    f"disk tier at {self.directory} holds no resident rows"
                )
            total = sum(c[2] for c in chunks)
            flat = rng.integers(0, total, size=n)
            starts = np.cumsum([0] + [c[2] for c in chunks])
            which = np.searchsorted(starts, flat, side="right") - 1
            parts = []
            for ci in np.unique(which):
                seq, path, _, _ = chunks[ci]
                local = flat[which == ci] - starts[ci]
                parts.append(
                    slice_rows(self._load_chunk_locked(seq, path), local)
                )
            out = concat_rows(parts)
        # Restore draw order (parts were grouped by chunk).
        order = np.argsort(np.argsort(which, kind="stable"), kind="stable")
        return slice_rows(out, order)

    def read_all(self, max_rows: int | None = None) -> dict:
        """Every resident row, manifest order (oldest first) — the
        ``--offline`` load path."""
        with self._lock:
            chunks = list(self._chunks)
            if not chunks:
                raise ValueError(
                    f"disk tier at {self.directory} holds no resident rows"
                )
            parts, got = [], 0
            for seq, path, rows, _ in chunks:
                parts.append(self._load_chunk_locked(seq, path))
                got += rows
                if max_rows is not None and got >= max_rows:
                    break
        out = concat_rows(parts)
        if max_rows is not None:
            out = slice_rows(out, slice(0, max_rows))
        return out

    # --------------------------------------------------------- accounting

    @property
    def rows(self) -> int:
        with self._lock:
            return sum(c[2] for c in self._chunks)

    @property
    def files(self) -> int:
        with self._lock:
            return len(self._chunks)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes_locked()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rows": sum(c[2] for c in self._chunks),
                "files": len(self._chunks),
                "bytes": self._bytes_locked(),
                "max_bytes": self.max_bytes,
                "policy": self.policy,
                "received_total": self.received_total,
                "evicted_rows_total": self.evicted_rows_total,
                "evicted_files_total": self.evicted_files_total,
                "dropped_rows_total": self.dropped_rows_total,
            }

    def conservation_holds(self) -> bool:
        with self._lock:
            return self.received_total == (
                sum(c[2] for c in self._chunks)
                + self.evicted_rows_total
            ) and self.dropped_rows_total >= 0

    def close(self) -> None:
        with self._lock:
            self._cache.clear()
