"""``train.py --offline``: regularized SAC from the disk tier, no env.

The flywheel's consuming end. A :class:`~torch_actor_critic_tpu.replay.
diskstore.DiskTier` written by either producer — the trainer's spill
path or the serve-side :class:`~torch_actor_critic_tpu.replay.flywheel.
TransitionLogger` — becomes the whole dataset: chunks load into host
RAM once, a host RNG draws index batches, and the update program is a
``lax.scan`` burst over :meth:`SAC.update`-shaped steps, exactly the
online burst minus the in-graph ring push/sample (there is no ring —
the dataset IS the buffer).

Naive SAC on a fixed dataset overestimates Q off-support (the policy
proposes actions the data never took; the critic, never corrected,
extrapolates optimistically). ``--offline-reg`` counters it:

- ``bc``: behavior-cloning anchor on the actor —
  ``weight * mean((pi(s) - a_data)^2)`` added to the policy loss.
- ``cql``: conservative penalty on the critic —
  ``weight * mean(logsumexp_a Q(s, a) - Q(s, a_data))`` over K uniform
  proposals plus one policy action, pushing down out-of-distribution
  Q while holding up in-distribution Q (CQL(H), simplified).
- ``none``: plain SAC steps (the ablation baseline).

The burst program ``train/offline_burst`` is a checked jit entry point
(analysis/: ENTRY_POINTS + contract tables) — watchdog-scoped dispatch,
XLA cost registration, like every other compiled program in the repo.
"""

from __future__ import annotations

import logging
import typing as t

import numpy as np

from torch_actor_critic_tpu.core.types import Batch, MultiObservation
from torch_actor_critic_tpu.utils.config import SACConfig

logger = logging.getLogger(__name__)

__all__ = ["OfflineLearner", "train_offline", "OFFLINE_REGULARIZERS"]

OFFLINE_REGULARIZERS = ("none", "bc", "cql")

# Uniform action proposals per state for the CQL logsumexp (plus one
# policy action). Small by design: the penalty needs a handful of
# off-support probes, not an integral.
_CQL_NUM_RANDOM = 4


def _zeros_obs(spec: t.Any):
    import jax.numpy as jnp

    if isinstance(spec, MultiObservation):
        return MultiObservation(
            features=jnp.zeros(spec.features.shape, spec.features.dtype),
            frame=jnp.zeros(spec.frame.shape, spec.frame.dtype),
        )
    return jnp.zeros(spec.shape, spec.dtype)


class _DatasetSpec:
    """``build_models`` env shim (the serve.py ``_resolve_model``
    pattern): the three attributes model construction reads, recovered
    from the disk tier's meta instead of a live env."""

    def __init__(self, obs_spec: t.Any, act_dim: int, act_limit: float):
        self.obs_spec = obs_spec
        self.act_dim = act_dim
        self.act_limit = act_limit


class OfflineLearner:
    """Regularized SAC over a fixed host-resident dataset."""

    # The cost-registry/watchdog source name of the offline burst
    # program (checked ENTRY_POINTS + contract tables, analysis/).
    burst_cost_name = "train/offline_burst"

    def __init__(
        self,
        config: SACConfig,
        obs_spec: t.Any,
        act_dim: int,
        act_limit: float = 1.0,
    ):
        from torch_actor_critic_tpu.sac.trainer import (
            build_models,
            make_learner,
        )

        if config.offline_reg not in OFFLINE_REGULARIZERS:
            raise ValueError(
                f"offline_reg must be one of {OFFLINE_REGULARIZERS}, "
                f"got {config.offline_reg!r}"
            )
        self.config = config
        self.obs_spec = obs_spec
        self.act_dim = int(act_dim)
        self.act_limit = float(act_limit)
        spec = _DatasetSpec(obs_spec, self.act_dim, self.act_limit)
        actor_def, critic_def = build_models(config, spec)
        self.sac = make_learner(config, actor_def, critic_def, self.act_dim)
        self._burst = None
        self._burst_len: int | None = None
        self._cost_registered = False

    def init_state(self, key):
        return self.sac.init_state(key, _zeros_obs(self.obs_spec))

    # ------------------------------------------------------------- update

    def _offline_update(self, state, batch: Batch):
        """One regularized SAC step: ``reg='none'`` delegates to the
        exact online :meth:`SAC.update` program; ``bc``/``cql`` run the
        same critic→actor→(alpha)→polyak sequence with the penalty
        folded into the regularized loss."""
        cfg = self.config
        if cfg.offline_reg == "none":
            return self.sac.update(state, batch)

        import jax
        import jax.numpy as jnp
        import optax

        from torch_actor_critic_tpu.ops.polyak import polyak_update
        from torch_actor_critic_tpu.sac import losses
        from torch_actor_critic_tpu.sac.algorithm import dynamic_lr_step

        sac = self.sac
        weight = float(cfg.offline_reg_weight)
        rng, key_q, key_pi, key_reg = jax.random.split(state.rng, 4)
        if cfg.learn_alpha:
            alpha = jnp.exp(jax.lax.stop_gradient(state.log_alpha))
        else:
            alpha = jnp.float32(cfg.alpha)

        # --- critic step (+ CQL gap) ---
        def critic_objective(critic_params):
            loss, aux = losses.critic_loss(
                critic_params,
                actor_apply=sac._actor_apply,
                critic_apply=sac._critic_apply,
                actor_params=state.actor_params,
                target_critic_params=state.target_critic_params,
                batch=batch,
                key=key_q,
                alpha=alpha,
                gamma=cfg.gamma,
                reward_scale=cfg.reward_scale,
            )
            if cfg.offline_reg == "cql":
                k_rand, k_pi_cql = jax.random.split(key_reg)
                B = batch.actions.shape[0]
                rand_actions = jax.random.uniform(
                    k_rand,
                    (_CQL_NUM_RANDOM, B, self.act_dim),
                    minval=-self.act_limit,
                    maxval=self.act_limit,
                )
                pi_actions, _ = sac._actor_apply(
                    state.actor_params, batch.states, k_pi_cql
                )
                cand = jnp.concatenate(
                    [rand_actions, jax.lax.stop_gradient(pi_actions)[None]],
                    axis=0,
                )  # (K+1, B, act_dim)
                q_cand = jax.vmap(
                    lambda a: sac._critic_apply(
                        critic_params, batch.states, a
                    )
                )(cand)  # (K+1, num_qs, B)
                lse = jax.scipy.special.logsumexp(q_cand, axis=0)
                q_data = sac._critic_apply(
                    critic_params, batch.states, batch.actions
                )
                gap = jnp.mean(lse - q_data)
                loss = loss + weight * gap
                aux["offline/cql_gap"] = gap
            return loss, aux

        (loss_q, q_aux), q_grads = jax.value_and_grad(
            critic_objective, has_aux=True
        )(state.critic_params)
        q_updates, q_opt_state = dynamic_lr_step(
            sac._adam_core, sac.q_tx, q_grads, state.q_opt_state,
            state.critic_params, None,
        )
        critic_params = optax.apply_updates(state.critic_params, q_updates)

        # --- actor step (+ BC anchor) ---
        def actor_objective(actor_params):
            pi_obs = (
                batch.next_states if cfg.parity_pi_obs else batch.states
            )
            pi, logp_pi = sac._actor_apply(actor_params, pi_obs, key_pi)
            q_pi = sac._critic_apply(critic_params, batch.states, pi)
            loss = jnp.mean(alpha * logp_pi - jnp.min(q_pi, axis=0))
            aux = {
                "logp_pi": jnp.mean(logp_pi),
                "entropy": -jnp.mean(logp_pi),
            }
            if cfg.offline_reg == "bc":
                bc = jnp.mean((pi - batch.actions) ** 2)
                loss = loss + weight * bc
                aux["offline/bc_mse"] = bc
            return loss, aux

        (loss_pi, pi_aux), pi_grads = jax.value_and_grad(
            actor_objective, has_aux=True
        )(state.actor_params)
        pi_updates, pi_opt_state = dynamic_lr_step(
            sac._adam_core, sac.pi_tx, pi_grads, state.pi_opt_state,
            state.actor_params, None,
        )
        actor_params = optax.apply_updates(state.actor_params, pi_updates)

        # --- temperature (same as online; no-op graph when fixed) ---
        log_alpha = state.log_alpha
        alpha_opt_state = state.alpha_opt_state
        if cfg.learn_alpha:
            a_grad = jax.grad(
                lambda la: losses.alpha_loss(
                    la, pi_aux["logp_pi"], sac.target_entropy
                )
            )(state.log_alpha)
            a_updates, alpha_opt_state = sac.alpha_tx.update(
                a_grad, state.alpha_opt_state, state.log_alpha
            )
            log_alpha = optax.apply_updates(state.log_alpha, a_updates)

        target_critic_params = polyak_update(
            critic_params, state.target_critic_params, cfg.polyak
        )
        new_state = state.replace(
            step=state.step + 1,
            actor_params=actor_params,
            critic_params=critic_params,
            target_critic_params=target_critic_params,
            pi_opt_state=pi_opt_state,
            q_opt_state=q_opt_state,
            log_alpha=log_alpha,
            alpha_opt_state=alpha_opt_state,
            rng=rng,
        )
        metrics = {
            "loss_q": loss_q,
            "loss_pi": loss_pi,
            "alpha": jnp.exp(log_alpha) if cfg.learn_alpha else alpha,
            **q_aux,
            **pi_aux,
        }
        return new_state, metrics

    # -------------------------------------------------------------- burst

    def _build_burst(self, num_updates: int):
        """The ``train/offline_burst`` jit program: scan ``num_updates``
        regularized steps over a pre-stacked ``(num_updates, B, ...)``
        batch tree, donating the train state."""
        import jax
        import jax.numpy as jnp

        def _offline_burst(state, batches: Batch):
            def body(st, batch):
                return self._offline_update(st, batch)

            state, metrics = jax.lax.scan(body, state, batches)
            return state, jax.tree_util.tree_map(jnp.mean, metrics)

        del num_updates  # geometry is carried by the batch tree
        return jax.jit(_offline_burst, donate_argnums=(0,))

    def burst(self, state, batches: Batch):
        """Dispatch one burst under the watchdog's source scope
        (compiles attribute to ``train/offline_burst``)."""
        from torch_actor_critic_tpu.diagnostics.watchdog import get_watchdog

        num_updates = int(batches.rewards.shape[0])
        from torch_actor_critic_tpu.aot.cache import cache_excluded

        if self._burst is None or self._burst_len != num_updates:
            self._burst = self._build_burst(num_updates)
            self._burst_len = num_updates
        # cache_excluded: donated train-plane executables are unsafe to
        # deserialize from the persistent compilation cache (see
        # aot/cache.py) — always compile live.
        with get_watchdog().source(self.burst_cost_name), cache_excluded():
            return self._burst(state, batches)

    def maybe_register_cost(self, state_abstract, batches_abstract) -> None:
        """Register the burst program's XLA cost analysis once
        (contract table: ``train/offline_burst`` cost registration)."""
        if self._cost_registered or self._burst is None:
            return
        self._cost_registered = True
        from torch_actor_critic_tpu.telemetry.costmodel import (
            get_cost_registry,
        )

        get_cost_registry().register_jit(
            self.burst_cost_name, self._burst, state_abstract,
            batches_abstract, devices=1,
        )


# ------------------------------------------------------------------- run


def _stack_batches(
    rows: t.Mapping[str, np.ndarray],
    sampler: np.random.Generator,
    num_updates: int,
    batch_size: int,
) -> Batch:
    """Draw ``num_updates`` independent uniform batches and stack them
    into one ``(num_updates, B, ...)`` scan tree (one host→device
    transfer per burst, like the online chunk placement)."""
    from torch_actor_critic_tpu.replay.diskstore import (
        rows_count,
        rows_to_batch,
        slice_rows,
    )

    import jax

    n = rows_count(rows)
    idx = sampler.integers(0, n, size=num_updates * batch_size)
    flat = rows_to_batch(slice_rows(rows, idx))
    lead = (num_updates, batch_size)
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x).reshape(lead + x.shape[1:]), flat
    )


def train_offline(
    config: SACConfig,
    tracker=None,
    checkpointer=None,
    seed: int = 0,
    telemetry=None,
) -> dict:
    """The ``train.py --offline`` entry: disk tier in, checkpoint out.

    Returns the final metrics dict (host floats) for smoke assertions.
    """
    import jax
    import jax.numpy as jnp

    from torch_actor_critic_tpu.replay.diskstore import (
        DiskTier,
        obs_spec_from_json,
        rows_count,
    )

    if not config.offline_dataset:
        raise ValueError("--offline requires --offline-dataset DIR")
    tier = DiskTier(config.offline_dataset)
    try:
        meta = tier.meta
        if meta is None:
            raise ValueError(
                f"offline dataset {config.offline_dataset!r} has no "
                "meta.json (not a replay disk tier?)"
            )
        obs_spec = obs_spec_from_json(meta["obs"])
        act_dim = int(meta["act_dim"])
        act_limit = float(meta.get("act_limit", 1.0))
        rows = tier.read_all()
        if rows is None or rows_count(rows) == 0:
            raise ValueError(
                f"offline dataset {config.offline_dataset!r} is empty"
            )
        n_rows = rows_count(rows)
    finally:
        tier.close()

    learner = OfflineLearner(config, obs_spec, act_dim, act_limit)
    key = jax.random.PRNGKey(seed)
    state = learner.init_state(key)
    # numpy generator (host batch sampling), NOT a jax key — named to
    # keep tac-lint's key-spelling heuristic out of the picture.
    sampler = np.random.default_rng(seed)

    burst_len = max(1, min(config.update_every, config.offline_steps))
    total = int(config.offline_steps)
    logger.info(
        "offline: %d rows, %d steps (bursts of %d), reg=%s(%.3g)",
        n_rows, total, burst_len, config.offline_reg,
        config.offline_reg_weight,
    )
    done_steps = 0
    last_metrics: dict = {}
    epoch = 0
    while done_steps < total:
        k = min(burst_len, total - done_steps)
        batches = _stack_batches(rows, sampler, k, config.batch_size)
        state, metrics = learner.burst(state, batches)
        learner.maybe_register_cost(
            jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state
            ),
            jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, jnp.asarray(x).dtype
                ),
                batches,
            ),
        )
        done_steps += k
        last_metrics = {
            m: float(v) for m, v in metrics.items()
            if np.ndim(v) == 0
        }
        last_metrics["offline/steps"] = float(done_steps)
        last_metrics["offline/dataset_rows"] = float(n_rows)
        if tracker is not None:
            tracker.log_metrics(last_metrics, epoch)
        if telemetry is not None:
            telemetry.event(
                "offline", epoch=epoch, steps=done_steps,
                loss_q=last_metrics.get("loss_q"),
                loss_pi=last_metrics.get("loss_pi"),
            )
        epoch += 1

    if checkpointer is not None:
        checkpointer.save(
            epoch, state, None,
            extra={
                "config": config.to_json(),
                "offline": {
                    "dataset": config.offline_dataset,
                    "steps": done_steps,
                    "reg": config.offline_reg,
                },
                "step": done_steps,
            },
            wait=True,
        )
    return last_metrics
