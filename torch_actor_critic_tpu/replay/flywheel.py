"""Serve-side transition logging — the data flywheel's intake.

A production policy fleet answers orders of magnitude more ``/act``
requests than any training run steps its envs; this module captures a
bounded, sampled slice of that traffic as training data in the SAME
disk-tier chunk format the trainer's spill path writes
(:mod:`~torch_actor_critic_tpu.replay.diskstore`), so ``train.py
--offline`` consumes fleet experience and trainer spill identically.

Placement: BEHIND the admission layer (serve/server.py wires
``note_act`` after a successful ``client.act`` only) — shed, expired
and breaker-refused requests never produce rows, so the dataset
reflects actions the policy actually served.

A transition needs two halves the HTTP plane sees at different times:
``note_act`` records (obs, action) under the request id at answer
time; ``note_outcome`` (the new ``POST /outcome`` route) completes it
with (reward, next_obs, done) when the caller reports what happened.
Pending halves live in a bounded FIFO map — a client that never
reports an outcome costs one slot until eviction (counted
``pending_evicted_total``), never unbounded host RAM. Completed
transitions batch into ``chunk_rows``-row files; ``sample_every=N``
keeps every Nth answered request (traffic downsampling).

Thread-safe throughout: the HTTP server handles requests on many
threads and ``/metrics`` snapshots concurrently.
"""

from __future__ import annotations

import threading
import typing as t
from collections import OrderedDict

import numpy as np

from torch_actor_critic_tpu.replay.diskstore import (
    DiskTier,
    obs_spec_to_json,
)

__all__ = ["TransitionLogger"]


def _obs_rows(prefix: str, obs: t.Any) -> t.Dict[str, np.ndarray]:
    """One observation (single row, no leading axis) -> flat row keys
    with a length-1 leading axis."""
    from torch_actor_critic_tpu.core.types import MultiObservation

    if isinstance(obs, MultiObservation):
        return {
            f"{prefix}.features": np.asarray(obs.features)[None],
            f"{prefix}.frame": np.asarray(obs.frame)[None],
        }
    return {prefix: np.asarray(obs)[None]}


class TransitionLogger:
    """Bounded, sampled (obs, action, outcome) logger over a DiskTier."""

    def __init__(
        self,
        directory: str,
        obs_spec: t.Any,
        act_dim: int,
        act_limit: float = 1.0,
        sample_every: int = 1,
        max_bytes: int = 0,
        max_pending: int = 1024,
        chunk_rows: int = 256,
    ):
        if sample_every < 1:
            raise ValueError(
                f"sample_every must be >= 1, got {sample_every}"
            )
        self._lock = threading.Lock()
        self.tier = DiskTier(directory, max_bytes=max_bytes, policy="fifo")
        self.tier.ensure_meta({
            "obs": obs_spec_to_json(obs_spec),
            "act_dim": int(act_dim),
            "act_limit": float(act_limit),
            "source": "flywheel",
        })
        self.sample_every = int(sample_every)
        self.max_pending = int(max_pending)
        self.chunk_rows = int(chunk_rows)
        # request_id -> (obs, action); FIFO-bounded.
        self._pending: "OrderedDict[str, tuple]" = OrderedDict()
        self._rows: t.List[t.Dict[str, np.ndarray]] = []
        self._seen = 0
        self.acts_seen_total = 0
        self.acts_sampled_total = 0
        self.outcomes_total = 0
        self.outcomes_unmatched_total = 0
        self.pending_evicted_total = 0
        self.logged_rows_total = 0

    # -------------------------------------------------------------- intake

    def note_act(self, request_id: str, obs: t.Any, action: t.Any) -> None:
        """Record the answered half of a transition (sampled)."""
        with self._lock:
            self.acts_seen_total += 1
            self._seen += 1
            if self._seen % self.sample_every != 0:
                return
            self.acts_sampled_total += 1
            self._pending[request_id] = (obs, np.asarray(action))
            while len(self._pending) > self.max_pending:
                self._pending.popitem(last=False)
                self.pending_evicted_total += 1

    def note_outcome(
        self,
        request_id: str,
        reward: float,
        next_obs: t.Any,
        done: bool,
    ) -> bool:
        """Complete a pending transition; returns True when the request
        id matched a sampled, still-pending act."""
        with self._lock:
            self.outcomes_total += 1
            pending = self._pending.pop(request_id, None)
            if pending is None:
                self.outcomes_unmatched_total += 1
                return False
            obs, action = pending
            row = dict(_obs_rows("states", obs))
            row.update(_obs_rows("next_states", next_obs))
            row["actions"] = np.asarray(action, np.float32).reshape(1, -1)
            row["rewards"] = np.asarray([reward], np.float32)
            row["done"] = np.asarray([float(bool(done))], np.float32)
            self._rows.append(row)
            self.logged_rows_total += 1
            flush_now = len(self._rows) >= self.chunk_rows
            if flush_now:
                rows, self._rows = self._rows, []
            else:
                rows = None
        if rows:
            self._append(rows)
        return True

    def _append(self, rows: t.List[t.Dict[str, np.ndarray]]) -> None:
        from torch_actor_critic_tpu.replay.diskstore import concat_rows

        self.tier.append(concat_rows(rows))

    def flush(self) -> int:
        """Write any buffered rows out as a (possibly short) chunk."""
        with self._lock:
            rows, self._rows = self._rows, []
        if rows:
            self._append(rows)
        return len(rows)

    # ------------------------------------------------------- observability

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "sample_every": self.sample_every,
                "acts_seen_total": self.acts_seen_total,
                "acts_sampled_total": self.acts_sampled_total,
                "outcomes_total": self.outcomes_total,
                "outcomes_unmatched_total": self.outcomes_unmatched_total,
                "pending": len(self._pending),
                "pending_evicted_total": self.pending_evicted_total,
                "logged_rows_total": self.logged_rows_total,
                "buffered_rows": len(self._rows),
            }
        out["disk"] = self.tier.snapshot()
        return out

    def close(self) -> None:
        self.flush()
        self.tier.close()
