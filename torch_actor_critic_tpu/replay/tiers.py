"""HBM↔host↔disk tiered experience store (docs/REPLAY.md).

The Reverb-shaped storage hierarchy behind the HBM ring: tier 0 is the
existing device-resident :class:`~torch_actor_critic_tpu.core.types.
BufferState` (``buffer/replay.py`` — untouched and bitwise-pinned);
this module adds the host-RAM tier and glues the disk tier
(:mod:`~torch_actor_critic_tpu.replay.diskstore`) underneath with
**counted waterfall spill**: every chunk the trainer stages is also
pushed through a host-side *shadow* of the HBM ring, rows the shadow
overwrites spill to the host ring, rows the host ring overwrites spill
to disk (or are counted dropped when no disk tier is attached). Refill
(:mod:`~torch_actor_critic_tpu.replay.prefetch`) draws from the host
tier back toward HBM and re-enters the same waterfall, so recirculated
rows stay accounted.

The shadow is the aggregate of the per-device ring shards (capacity =
``buffer_size`` rows total, the same rows the dp shards hold between
them) — it exists so spill is *what the HBM ring actually forgot*, not
a guess, without ever reading device memory back.

Conservation invariant, extending the StagingBuffer one
(docs/RESILIENCE.md) per tier and across tier boundaries::

    shadow.received == pushed_fresh + refill            (sources)
    ring.received   == ring.size + ring.evicted + ring.dropped_restart
    host.received   == shadow.evicted
    host.evicted    == disk.received_since_attach + dropped_nodisk

``dropped_restart`` counts rows resident at checkpoint time that a
restart cannot restore (host tiers are not checkpointed as arrays —
only counters ride the checkpoint, docs/REPLAY.md "Restart
semantics"); the invariant survives restarts because those rows are
moved from ``size`` to ``dropped_restart`` at restore.

Everything here is host-side numpy + a single lock (the prefetch
thread samples while the train loop ingests); nothing touches the jit
cache, so ``replay_tiers=off`` is exactly today's trainer.
"""

from __future__ import annotations

import threading
import typing as t

import numpy as np

from torch_actor_critic_tpu.replay.diskstore import (
    DiskTier,
    rows_count,
    slice_rows,
)

__all__ = [
    "HostRing",
    "StripedHostRing",
    "TieredReplay",
    "REPLAY_PRIORITIES",
]

REPLAY_PRIORITIES = ("uniform", "recent")


class HostRing:
    """Numpy ring over flat-key rows; ``push`` returns what it evicted.

    Pointer arithmetic mirrors ``buffer/replay.py push`` exactly
    (write at ``(ptr + arange(n)) % capacity``, advance, saturate) so
    the shadow instance tracks the HBM ring's overwrite behavior
    row-for-row. Arrays are allocated lazily from the first pushed
    chunk's shapes/dtypes.
    """

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._data: t.Dict[str, np.ndarray] | None = None
        self.ptr = 0
        self.size = 0
        self.received_total = 0
        self.evicted_total = 0
        self.dropped_restart_total = 0

    def _ensure(self, rows: t.Mapping[str, np.ndarray]) -> None:
        if self._data is None:
            self._data = {
                k: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in rows.items()
            }

    def _gather(self, idx: np.ndarray) -> t.Dict[str, np.ndarray]:
        assert self._data is not None
        return {k: v[idx] for k, v in self._data.items()}

    def push(
        self, rows: t.Mapping[str, np.ndarray]
    ) -> t.Dict[str, np.ndarray] | None:
        """Store ``rows``; returns the overwritten rows (oldest first)
        or ``None`` when nothing was evicted."""
        n = rows_count(rows)
        if n == 0:
            return None
        self._ensure(rows)
        assert self._data is not None
        self.received_total += n
        if n >= self.capacity:
            # The incoming chunk alone wraps the ring: everything
            # resident is lost, plus the first n-capacity incoming rows
            # (exactly what the modular scatter overwrites — later
            # duplicate indices win).
            evicted_parts = []
            if self.size:
                start = (self.ptr - self.size) % self.capacity
                valid = (start + np.arange(self.size)) % self.capacity
                evicted_parts.append(self._gather(valid))
            spill_in = n - self.capacity
            if spill_in:
                evicted_parts.append(slice_rows(rows, slice(0, spill_in)))
            kept = slice_rows(rows, slice(n - self.capacity, n))
            for k in self._data:
                self._data[k][...] = kept[k]
            self.ptr = 0
            self.size = self.capacity
            self.evicted_total += sum(
                rows_count(p) for p in evicted_parts
            )
            if not evicted_parts:
                return None
            from torch_actor_critic_tpu.replay.diskstore import concat_rows

            return (
                evicted_parts[0] if len(evicted_parts) == 1
                else concat_rows(evicted_parts)
            )
        overwritten = max(0, self.size + n - self.capacity)
        evicted = None
        if overwritten:
            start = (self.ptr - self.size) % self.capacity
            old_idx = (start + np.arange(overwritten)) % self.capacity
            evicted = self._gather(old_idx)
            self.evicted_total += overwritten
        idx = (self.ptr + np.arange(n)) % self.capacity
        for k, v in self._data.items():
            v[idx] = rows[k]
        self.ptr = (self.ptr + n) % self.capacity
        self.size = min(self.size + n, self.capacity)
        return evicted

    def sample(
        self,
        rng: np.random.Generator,
        n: int,
        priority: str = "uniform",
    ) -> t.Dict[str, np.ndarray]:
        """Draw ``n`` rows with replacement. ``priority="recent"``
        restricts the draw to the newest half of the valid region
        (freshest-data-wins refill for fast-moving policies)."""
        if self.size == 0 or self._data is None:
            raise ValueError("host ring is empty")
        if priority not in REPLAY_PRIORITIES:
            raise ValueError(
                f"priority must be one of {REPLAY_PRIORITIES}, got "
                f"{priority!r}"
            )
        window = self.size if priority == "uniform" else max(1, self.size // 2)
        # Offsets back from the newest row; the valid region ends at ptr.
        offs = rng.integers(0, window, size=n)
        idx = (self.ptr - 1 - offs) % self.capacity
        return self._gather(idx)

    def note_restart(self) -> None:
        """Resident rows did not survive a restart: move them from
        ``size`` into ``dropped_restart_total`` so conservation holds
        on the restored counters."""
        self.dropped_restart_total += self.size
        self.size = 0
        self.ptr = 0
        self._data = None

    def conservation_holds(self) -> bool:
        return self.received_total == (
            self.size + self.evicted_total + self.dropped_restart_total
        )

    def snapshot(self) -> dict:
        return {
            "rows": self.size,
            "capacity": self.capacity,
            "received_total": self.received_total,
            "evicted_total": self.evicted_total,
            "dropped_restart_total": self.dropped_restart_total,
        }

    def restore_counters(self, snap: t.Mapping[str, t.Any]) -> None:
        """Adopt a checkpointed :meth:`snapshot` (counters only) and
        declare the resident rows lost (:meth:`note_restart`) — the
        restart path of ``TieredReplay.load_meta``."""
        self.received_total = int(snap.get("received_total", 0))
        self.evicted_total = int(snap.get("evicted_total", 0))
        self.dropped_restart_total = int(
            snap.get("dropped_restart_total", 0)
        )
        self.size = int(snap.get("rows", 0))
        self.note_restart()


class StripedHostRing:
    """Per-task host tier: one :class:`HostRing` per stripe, rows routed
    by the task one-hot (``buffer/striped.py`` convention, trailing
    ``n_stripes`` dims of the flat observation).

    Same interface as :class:`HostRing`, so :class:`TieredReplay`'s
    waterfall and flow equations hold unchanged over the aggregate
    counters — the generalization is in ``push`` (stripe→tier routing:
    spilled rows land in *their task's* host ring) and ``sample``
    (task-balanced draw: ``n // n_stripes`` rows per non-empty stripe,
    remainder spread across the first ones), so refill keeps the
    per-task replay striping guarantee even when one stripe has spilled
    far more than the others.
    """

    def __init__(self, capacity: int, n_stripes: int):
        if n_stripes < 2:
            raise ValueError(
                f"striped host tier needs >= 2 stripes, got {n_stripes}"
            )
        per_stripe = max(1, int(capacity) // int(n_stripes))
        self.n_stripes = int(n_stripes)
        self.capacity = per_stripe * self.n_stripes
        self.stripes = [HostRing(per_stripe) for _ in range(self.n_stripes)]

    # Aggregate counters: TieredReplay's conservation equations are
    # over sums, so the single-ring algebra carries over verbatim.
    @property
    def size(self) -> int:
        return sum(r.size for r in self.stripes)

    @property
    def received_total(self) -> int:
        return sum(r.received_total for r in self.stripes)

    @property
    def evicted_total(self) -> int:
        return sum(r.evicted_total for r in self.stripes)

    @property
    def dropped_restart_total(self) -> int:
        return sum(r.dropped_restart_total for r in self.stripes)

    def push(
        self, rows: t.Mapping[str, np.ndarray]
    ) -> t.Dict[str, np.ndarray] | None:
        from torch_actor_critic_tpu.buffer.striped import (
            route_rows_to_stripes,
        )
        from torch_actor_critic_tpu.replay.diskstore import concat_rows

        evicted_parts = []
        for stripe, part in enumerate(
            route_rows_to_stripes(rows, self.n_stripes)
        ):
            if part is None:
                continue
            evicted = self.stripes[stripe].push(part)
            if evicted is not None:
                evicted_parts.append(evicted)
        if not evicted_parts:
            return None
        return (
            evicted_parts[0] if len(evicted_parts) == 1
            else concat_rows(evicted_parts)
        )

    def sample(
        self,
        rng: np.random.Generator,
        n: int,
        priority: str = "uniform",
    ) -> t.Dict[str, np.ndarray]:
        """Task-balanced draw over the non-empty stripes (an empty
        stripe's share is spread over the others — a task that never
        spilled cannot stall refill for the rest)."""
        from torch_actor_critic_tpu.replay.diskstore import concat_rows

        live = [r for r in self.stripes if r.size > 0]
        if not live:
            raise ValueError("striped host tier is empty")
        base, rem = divmod(n, len(live))
        parts = []
        for i, ring in enumerate(live):
            quota = base + (1 if i < rem else 0)
            if quota:
                parts.append(ring.sample(rng, quota, priority=priority))
        return concat_rows(parts)

    def note_restart(self) -> None:
        for ring in self.stripes:
            ring.note_restart()

    def conservation_holds(self) -> bool:
        return all(r.conservation_holds() for r in self.stripes)

    def snapshot(self) -> dict:
        return {
            "rows": self.size,
            "capacity": self.capacity,
            "received_total": self.received_total,
            "evicted_total": self.evicted_total,
            "dropped_restart_total": self.dropped_restart_total,
            "stripes": [r.snapshot() for r in self.stripes],
        }

    def restore_counters(self, snap: t.Mapping[str, t.Any]) -> None:
        """Adopt a checkpointed snapshot. Per-stripe splits restore
        exactly when present; an aggregate-only snapshot (or one from a
        different stripe count) lands whole on stripe 0 — the flow
        equations are over sums, so conservation is preserved either
        way."""
        per = snap.get("stripes")
        if isinstance(per, list) and len(per) == self.n_stripes:
            for ring, sub in zip(self.stripes, per):
                ring.restore_counters(dict(sub or {}))
            return
        self.stripes[0].restore_counters(snap)
        for ring in self.stripes[1:]:
            ring.restore_counters({})


class TieredReplay:
    """The tier stack + the counted spill/refill waterfall.

    ``hbm_capacity`` is the LOGICAL ring capacity (``buffer_size``
    rows = per-device shard capacity x dp); ``disk=None`` runs in
    host-only mode (``replay_tiers=host``) where rows falling off the
    host ring are counted ``dropped_nodisk_total`` instead of spilled.
    """

    def __init__(
        self,
        hbm_capacity: int,
        host_capacity: int,
        disk: DiskTier | None = None,
        priority: str = "uniform",
        seed: int = 0,
        n_stripes: int = 0,
    ):
        if priority not in REPLAY_PRIORITIES:
            raise ValueError(
                f"priority must be one of {REPLAY_PRIORITIES}, got "
                f"{priority!r}"
            )
        self._lock = threading.Lock()
        self.shadow = HostRing(hbm_capacity)
        # n_stripes > 0: the host tier keeps per-task sub-rings (rows
        # routed by the buffer/striped.py one-hot convention) so refill
        # sampling stays task-balanced even when one stripe spilled.
        self.host: HostRing | StripedHostRing = (
            StripedHostRing(host_capacity, n_stripes) if n_stripes
            else HostRing(host_capacity)
        )
        self.disk = disk
        self.priority = priority
        self._rng = np.random.default_rng(seed)
        self.pushed_total = 0  # fresh env rows entering the waterfall
        self.refill_total = 0  # recirculated rows re-entering it
        self.dropped_nodisk_total = 0
        # Disk rows present before this stack attached (a reopened
        # flywheel dir) are not part of THIS stack's flow equations.
        self._disk_received0 = disk.received_total if disk else 0

    # ------------------------------------------------------------ waterfall

    def _waterfall_locked(self, rows: t.Mapping[str, np.ndarray]) -> None:
        spilled = self.shadow.push(rows)
        if spilled is None:
            return
        to_disk = self.host.push(spilled)
        if to_disk is None:
            return
        if self.disk is not None:
            self.disk.append(to_disk)
        else:
            self.dropped_nodisk_total += rows_count(to_disk)

    def ingest_rows(self, rows: t.Mapping[str, np.ndarray]) -> int:
        """Fresh experience (the trainer's drained window, already in
        row form) enters the waterfall."""
        n = rows_count(rows)
        with self._lock:
            self.pushed_total += n
            self._waterfall_locked(rows)
        return n

    def ingest_chunk(self, chunk, n_lead: int = 2) -> int:
        """Fresh experience as a ``Batch`` chunk with ``n_lead``
        leading axes (the trainer's ``(n_envs, window)``)."""
        from torch_actor_critic_tpu.replay.diskstore import batch_to_rows

        return self.ingest_rows(batch_to_rows(chunk, n_lead=n_lead))

    def note_refill(self, rows: t.Mapping[str, np.ndarray]) -> int:
        """Rows the prefetcher pushed back into the HBM ring re-enter
        the waterfall (they now occupy ring slots and will overwrite
        older rows exactly like fresh ones)."""
        n = rows_count(rows)
        with self._lock:
            self.refill_total += n
            self._waterfall_locked(rows)
        return n

    def sample_refill(self, n: int) -> t.Dict[str, np.ndarray] | None:
        """Draw ``n`` rows from the host tier for refill, or ``None``
        while the host tier is still empty."""
        with self._lock:
            if self.host.size == 0:
                return None
            return self.host.sample(self._rng, n, priority=self.priority)

    # ----------------------------------------------------------- invariant

    def conservation_holds(self) -> bool:
        with self._lock:
            return self.conservation_locked()

    # ------------------------------------------------------- observability

    def metrics(self) -> dict:
        """metrics.jsonl columns (``replay/`` namespace)."""
        with self._lock:
            out = {
                "replay/hbm_rows": float(self.shadow.size),
                "replay/host_rows": float(self.host.size),
                "replay/pushed_total": float(self.pushed_total),
                "replay/refill_rows_total": float(self.refill_total),
                "replay/spilled_host_total": float(
                    self.shadow.evicted_total
                ),
                "replay/conservation_ok": float(self.conservation_locked()),
            }
            if self.disk is not None:
                out["replay/disk_rows"] = float(self.disk.rows)
                out["replay/disk_bytes"] = float(self.disk.bytes_used)
                out["replay/spilled_disk_total"] = float(
                    self.disk.received_total - self._disk_received0
                )
                out["replay/disk_evicted_rows_total"] = float(
                    self.disk.evicted_rows_total
                )
            else:
                out["replay/dropped_nodisk_total"] = float(
                    self.dropped_nodisk_total
                )
            return out

    def conservation_locked(self) -> bool:
        # metrics() already holds the (non-reentrant) lock; re-derive
        # without re-locking.
        disk_ok = True
        disk_received = 0
        if self.disk is not None:
            disk_ok = self.disk.conservation_holds()
            disk_received = self.disk.received_total - self._disk_received0
        return (
            self.shadow.conservation_holds()
            and self.host.conservation_holds()
            and self.shadow.received_total
            == self.pushed_total + self.refill_total
            and self.host.received_total == self.shadow.evicted_total
            and self.host.evicted_total
            == disk_received + self.dropped_nodisk_total
            and disk_ok
        )

    def snapshot(self) -> dict:
        """Structured state for ``replay`` telemetry events."""
        with self._lock:
            out = {
                "hbm": self.shadow.snapshot(),
                "host": self.host.snapshot(),
                "priority": self.priority,
                "pushed_total": self.pushed_total,
                "refill_total": self.refill_total,
                "dropped_nodisk_total": self.dropped_nodisk_total,
                "conservation_ok": self.conservation_locked(),
            }
            if self.disk is not None:
                out["disk"] = self.disk.snapshot()
            return out

    # ------------------------------------------------- checkpoint bridge

    def meta_state(self) -> dict:
        """JSON-safe counters for checkpoint metadata. Tier CONTENTS
        are not checkpointed: the disk tier is already durable (it
        reopens from its own manifest) and the host/shadow rows are
        declared ``dropped_restart`` at restore — the invariant, not
        the rows, survives."""
        with self._lock:
            return {
                "pushed_total": self.pushed_total,
                "refill_total": self.refill_total,
                "dropped_nodisk_total": self.dropped_nodisk_total,
                "shadow": self.shadow.snapshot(),
                "host": self.host.snapshot(),
            }

    def load_meta(self, meta: t.Mapping[str, t.Any]) -> None:
        with self._lock:
            self.pushed_total = int(meta.get("pushed_total", 0))
            self.refill_total = int(meta.get("refill_total", 0))
            self.dropped_nodisk_total = int(
                meta.get("dropped_nodisk_total", 0)
            )
            for ring, key in ((self.shadow, "shadow"), (self.host, "host")):
                ring.restore_counters(dict(meta.get(key) or {}))
            # Disk rows were durable across the restart: everything the
            # host tier ever evicted toward disk is still accounted by
            # the reopened DiskTier counters.
            self._disk_received0 = 0
            if self.disk is not None:
                self._disk_received0 = self.disk.received_total - (
                    self.host.evicted_total - self.dropped_nodisk_total
                )

    def close(self) -> None:
        if self.disk is not None:
            self.disk.close()
