"""Q-critics.

``Critic`` matches the reference single Q-network: MLP over
``concat([state, action], -1)`` with ReLU between layers, linear final
layer, squeezed scalar output (ref ``networks/linear.py:56-69``).

``DoubleCritic`` replaces the reference's two independent submodules
(ref ``networks/linear.py:72-79``) with a **vmapped parameter ensemble**:
one set of module definitions whose params carry a leading ensemble axis
of size ``num_qs``. On TPU this turns the twin forward passes into
batched matmuls on the MXU (one weight fetch, double the useful FLOPs)
instead of two sequential kernels, and generalizes to REDQ-style larger
ensembles by changing one integer.
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
from flax import linen as nn

from torch_actor_critic_tpu.models.mlp import MLP


class Critic(nn.Module):
    """Single Q-network: ``Q(s, a) -> scalar`` (batch-shaped)."""

    hidden_sizes: t.Sequence[int] = (256, 256)
    # Compute dtype for the matmuls (params stay float32); the Q value
    # is cast back to float32 so Bellman targets and losses are always
    # full precision.
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        dtype = self.dtype
        x = jnp.concatenate([obs, action], axis=-1)
        x = MLP(tuple(self.hidden_sizes) + (1,), activate_final=False,
                dtype=dtype)(x)
        return jnp.squeeze(x.astype(jnp.float32), axis=-1)


class DoubleCritic(nn.Module):
    """Ensemble of ``num_qs`` independent critics; returns ``(num_qs, ...)``.

    ``num_qs=2`` reproduces the reference ``DoubleCritic``'s
    ``(q1, q2)`` as ``q[0], q[1]``.
    """

    hidden_sizes: t.Sequence[int] = (256, 256)
    num_qs: int = 2
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        ensemble = nn.vmap(
            Critic,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=None,
            out_axes=0,
            axis_size=self.num_qs,
        )
        return ensemble(self.hidden_sizes, dtype=self.dtype, name="ensemble")(
            obs, action
        )
