"""Task-conditioned heads: learned task embedding over the one-hot.

With ``task_embed_dim == 0`` (the default) a multi-task scenario needs
no special models at all — the task one-hot is simply part of the flat
observation and the plain :class:`~torch_actor_critic_tpu.models.actor.
Actor`/``DoubleCritic`` condition on it like any other feature. These
modules are the opt-in upgrade (``config.task_embed_dim > 0``): the
trailing ``n_tasks`` one-hot dims are projected through a learned
linear embedding before joining the proprioceptive features, so tasks
share structure in embedding space instead of owning disjoint one-hot
columns — the standard multi-task conditioning lever once the task
count grows past a handful.

Both honor the exact actor/critic contracts, so every downstream
surface (fused loop, losses, serving engine, checkpoints) is
indifferent to which conditioning is active.
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
from flax import linen as nn

from torch_actor_critic_tpu.models.mlp import MLP, Dense
from torch_actor_critic_tpu.ops.distributions import squashed_gaussian_sample


def _embed_obs(obs: jax.Array, n_tasks: int, embed_dim: int, dtype) -> jax.Array:
    """Split the trailing task one-hot off, embed it, rejoin."""
    base, onehot = obs[..., :-n_tasks], obs[..., -n_tasks:]
    emb = Dense(embed_dim, dtype=dtype, name="task_embed")(onehot)
    return jnp.concatenate([base, emb], axis=-1)


class TaskConditionedActor(nn.Module):
    """Squashed-Gaussian actor over (features, task-embedding)."""

    n_tasks: int
    task_embed_dim: int
    act_dim: int
    hidden_sizes: t.Sequence[int] = (256, 256)
    act_limit: float = 1.0
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        obs: jax.Array,
        key: jax.Array | None = None,
        deterministic: bool = False,
        with_logprob: bool = True,
    ):
        x = _embed_obs(obs, self.n_tasks, self.task_embed_dim, self.dtype)
        trunk = MLP(self.hidden_sizes, activate_final=True, dtype=self.dtype)(x)
        mu = Dense(self.act_dim, dtype=self.dtype)(trunk).astype(jnp.float32)
        log_std = Dense(self.act_dim, dtype=self.dtype)(trunk).astype(
            jnp.float32
        )
        return squashed_gaussian_sample(
            key, mu, log_std, self.act_limit, deterministic, with_logprob
        )


class _TaskQ(nn.Module):
    n_tasks: int
    task_embed_dim: int
    hidden_sizes: t.Sequence[int]
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        x = _embed_obs(obs, self.n_tasks, self.task_embed_dim, self.dtype)
        x = jnp.concatenate([x, action], axis=-1)
        x = MLP(tuple(self.hidden_sizes) + (1,), activate_final=False,
                dtype=self.dtype)(x)
        return jnp.squeeze(x.astype(jnp.float32), axis=-1)


class TaskConditionedDoubleCritic(nn.Module):
    """Twin task-conditioned critics; returns ``(num_qs, batch)``."""

    n_tasks: int
    task_embed_dim: int
    hidden_sizes: t.Sequence[int] = (256, 256)
    num_qs: int = 2
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        ensemble = nn.vmap(
            _TaskQ,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=None,
            out_axes=0,
            axis_size=self.num_qs,
        )
        return ensemble(
            n_tasks=self.n_tasks,
            task_embed_dim=self.task_embed_dim,
            hidden_sizes=self.hidden_sizes,
            dtype=self.dtype,
            name="ensemble",
        )(obs, action)
