"""Sequence (transformer) policies and critics over observation histories.

A capability **extension** — the reference's models are feedforward over
fixed-width observation vectors with no sequence axis anywhere
(SURVEY.md §5 "Long-context: absent by construction"). These modules
give the framework a long-context policy class for partially-observable
tasks: a causal transformer encoder over the last ``T`` observations,
with the same squashed-Gaussian head as the MLP actor (ref
``networks/linear.py:39-51`` math, shared via
:mod:`torch_actor_critic_tpu.ops.distributions`), so a
``SequenceActor`` drops into the SAC losses wherever ``Actor`` does.

Designed for the distributed path from the start: the trunk takes a
``pos_offset`` (global position of this device's local chunk) and an
injectable ``attention_fn``, which is exactly the surface
:mod:`torch_actor_critic_tpu.parallel.context` needs to run the same
module under ``shard_map`` with ring attention over an ``sp`` mesh axis.
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
from flax import linen as nn

from torch_actor_critic_tpu.models.mlp import Dense
from torch_actor_critic_tpu.ops.attention import attention as sdpa
from torch_actor_critic_tpu.ops.distributions import squashed_gaussian_sample

# attention_fn(q, k, v, causal) -> out, all (batch, heads, seq, head_dim)
AttentionFn = t.Callable[..., jax.Array]


def _auto_batch(obs_seq: jax.Array, *rest: jax.Array):
    """Add a leading batch axis to an unbatched ``(T, D)`` history (and
    companion arrays), like the visual stack's auto-reshape (ref
    ``convolutional.py:91-96``). Returns ``(unbatched, obs_seq, *rest)``."""
    unbatched = obs_seq.ndim == 2
    if unbatched:
        obs_seq = obs_seq[None]
        rest = tuple(x[None] for x in rest)
    return (unbatched, obs_seq, *rest)


def default_attention(q, k, v, causal=True):
    return sdpa(q, k, v, causal=causal)


def _sp_pos_offset(obs_seq: jax.Array, sp_axis: str | None):
    """Global position of this device's chunk start: 0 single-device;
    ``axis_index(sp) * T_local`` when the sequence axis is sharded."""
    if sp_axis is None:
        return 0
    return jax.lax.axis_index(sp_axis) * obs_seq.shape[1]


def _sp_last_token(h: jax.Array, sp_axis: str | None, sp_size: int):
    """The representation of the *global* last timestep.

    Single-device: ``h[:, -1]``. Under sequence sharding the global last
    token lives on the final ``sp`` device; a masked ``psum`` broadcasts
    it to every device so downstream heads/losses are replicated over
    ``sp`` (same gather the acting path uses,
    ``parallel/context.py``)."""
    last = h[:, -1]
    if sp_axis is None:
        return last
    idx = jax.lax.axis_index(sp_axis)
    masked = jnp.where(idx == sp_size - 1, last, jnp.zeros_like(last))
    return jax.lax.psum(masked, sp_axis)


def xla_attention(q, k, v, causal=True):
    """Backend-portable attention (no Pallas): for modules that must
    compile on the host CPU while TPU is the default backend, e.g. the
    trainer's host actor mirror."""
    return sdpa(q, k, v, causal=causal, impl="xla")


class MultiHeadAttention(nn.Module):
    """Causal MHA with a pluggable attention kernel."""

    num_heads: int
    attention_fn: AttentionFn = default_attention
    dtype: t.Any = jnp.float32  # projection compute dtype; params stay f32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dtype = self.dtype
        b, s, d_model = x.shape
        assert d_model % self.num_heads == 0, (d_model, self.num_heads)
        head_dim = d_model // self.num_heads

        def split(y):  # (B, T, D) -> (B, H, T, d)
            return y.reshape(b, s, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        # Megatron attention pairing: q/k/v projections column-parallel
        # (equivalently: heads sharded over tp), output projection
        # row-parallel — one psum per attention block under tp.
        # The attention kernels accumulate in f32 regardless of input
        # dtype (see ops/attention.py), so bf16 q/k/v is safe.
        q = split(Dense(d_model, tp_role="col", dtype=dtype)(x))
        k = split(Dense(d_model, tp_role="col", dtype=dtype)(x))
        v = split(Dense(d_model, tp_role="col", dtype=dtype)(x))
        out = self.attention_fn(q, k, v, causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(b, s, d_model)
        return Dense(d_model, tp_role="row", dtype=dtype)(out)


class TransformerBlock(nn.Module):
    """Pre-LN block: LN → MHA → residual, LN → GELU MLP → residual."""

    num_heads: int
    mlp_ratio: int = 4
    attention_fn: AttentionFn = default_attention
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        dtype = self.dtype
        d_model = x.shape[-1]
        # LayerNorm statistics stay float32 (flax upcasts internally);
        # its output is cast to the compute dtype by the next Dense.
        x = x + MultiHeadAttention(
            self.num_heads, self.attention_fn, dtype=dtype
        )(nn.LayerNorm()(x))
        h = nn.LayerNorm()(x)
        h = Dense(self.mlp_ratio * d_model, tp_role="col", dtype=dtype)(h)
        h = nn.gelu(h)
        h = Dense(d_model, tp_role="row", dtype=dtype)(h)
        return x + h


class SequenceTrunk(nn.Module):
    """Embed + positional encode + N causal transformer blocks.

    ``pos_offset`` is the global index of this chunk's first timestep —
    0 on a single device; ``axis_index('sp') * T_local`` under context
    parallelism, so positional embeddings stay globally consistent when
    the sequence is sharded.
    """

    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 512
    attention_fn: AttentionFn = default_attention
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs_seq: jax.Array, pos_offset: jax.Array | int = 0):
        dtype = self.dtype
        b, s, _ = obs_seq.shape
        # jnp.take clamps out-of-bounds rows silently — aliased positions
        # would train without error, so reject oversized histories here.
        # (Under sp sharding `s` is the local chunk; the context wrapper
        # checks the global length against max_len.)
        assert s <= self.max_len, (
            f"history length {s} exceeds max_len={self.max_len}"
        )
        x = Dense(self.d_model, dtype=dtype)(obs_seq)
        pos_table = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
        )
        pos = pos_offset + jnp.arange(s)
        # The f32 pos table would promote a bf16 residual stream back to
        # f32; cast the sum to the compute dtype explicitly.
        x = (x + jnp.take(pos_table, pos, axis=0)[None]).astype(dtype)
        for _ in range(self.num_layers):
            x = TransformerBlock(
                self.num_heads, attention_fn=self.attention_fn, dtype=dtype
            )(x)
        return nn.LayerNorm()(x)


class SequenceActor(nn.Module):
    """Squashed-Gaussian policy conditioned on an observation history.

    ``__call__`` maps ``(B, T, obs_dim)`` histories to the action for
    the latest timestep; :meth:`trunk` / :meth:`head` are exposed
    separately so the context-parallel wrapper can insert the
    cross-device last-token gather between them.
    """

    act_dim: int
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 512
    act_limit: float = 1.0
    attention_fn: AttentionFn = default_attention
    # Sequence/context parallelism: when `sp_axis` names a *manual* mesh
    # axis (the module is being applied inside shard_map with the
    # sequence dimension sharded over it), positional offsets and the
    # last-token gather become sp-aware. Pair with a ring attention_fn
    # (`parallel.context.make_ring_attention_fn`). Attributes, not
    # params: the tree layout (and checkpoints) are unchanged.
    sp_axis: str | None = None
    sp_size: int = 1
    dtype: t.Any = jnp.float32  # see Actor.dtype; distribution math stays f32

    def setup(self):
        self._trunk = SequenceTrunk(
            self.d_model, self.num_heads, self.num_layers, self.max_len,
            self.attention_fn, dtype=self.dtype,
        )
        self._mu = Dense(self.act_dim, dtype=self.dtype)
        self._log_std = Dense(self.act_dim, dtype=self.dtype)

    def trunk(self, obs_seq: jax.Array, pos_offset: jax.Array | int = 0):
        return self._trunk(obs_seq, pos_offset)

    def head(
        self,
        h: jax.Array,
        key: jax.Array | None = None,
        deterministic: bool = False,
        with_logprob: bool = True,
    ):
        mu = self._mu(h).astype(jnp.float32)
        log_std = self._log_std(h).astype(jnp.float32)
        return squashed_gaussian_sample(
            key, mu, log_std, self.act_limit, deterministic, with_logprob
        )

    def __call__(
        self,
        obs_seq: jax.Array,
        key: jax.Array | None = None,
        deterministic: bool = False,
        with_logprob: bool = True,
    ):
        unbatched, obs_seq = _auto_batch(obs_seq)
        h_all = self.trunk(obs_seq, _sp_pos_offset(obs_seq, self.sp_axis))
        h = _sp_last_token(h_all, self.sp_axis, self.sp_size)
        action, logp = self.head(h, key, deterministic, with_logprob)
        if unbatched:
            action = jnp.squeeze(action, 0)
            logp = jnp.squeeze(logp, 0) if logp is not None else None
        return action, logp


class SequenceCritic(nn.Module):
    """Q(h_T, a): history-conditioned Q-network.

    The trunk encodes the history; the last token's representation is
    concatenated with the action and scored by a 2-layer MLP — the
    sequence analogue of ``Critic``'s concat([obs, act]) (ref
    ``networks/linear.py:62``).
    """

    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 512
    hidden: int = 256
    attention_fn: AttentionFn = default_attention
    sp_axis: str | None = None  # see SequenceActor.sp_axis
    sp_size: int = 1
    dtype: t.Any = jnp.float32  # see Critic.dtype; Q cast back to float32

    @nn.compact
    def __call__(self, obs_seq: jax.Array, action: jax.Array) -> jax.Array:
        dtype = self.dtype
        unbatched, obs_seq, action = _auto_batch(obs_seq, action)
        h_all = SequenceTrunk(
            self.d_model, self.num_heads, self.num_layers, self.max_len,
            self.attention_fn, dtype=dtype,
        )(obs_seq, _sp_pos_offset(obs_seq, self.sp_axis))
        h = _sp_last_token(h_all, self.sp_axis, self.sp_size)
        x = jnp.concatenate([h, action.astype(h.dtype)], axis=-1)
        x = nn.relu(Dense(self.hidden, dtype=dtype)(x))
        x = Dense(1, dtype=dtype)(x)
        q = jnp.squeeze(x.astype(jnp.float32), axis=-1)
        return jnp.squeeze(q, 0) if unbatched else q


class SequenceDoubleCritic(nn.Module):
    """Twin (or ``num_qs``-wide) ensemble of :class:`SequenceCritic`,
    vmapped over parameters like
    :class:`~torch_actor_critic_tpu.models.critic.DoubleCritic`."""

    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 512
    hidden: int = 256
    num_qs: int = 2
    attention_fn: AttentionFn = default_attention
    sp_axis: str | None = None  # see SequenceActor.sp_axis
    sp_size: int = 1
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs_seq: jax.Array, action: jax.Array) -> jax.Array:
        ensemble = nn.vmap(
            SequenceCritic,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            in_axes=None,
            out_axes=0,
            axis_size=self.num_qs,
        )
        return ensemble(
            self.d_model, self.num_heads, self.num_layers, self.max_len,
            self.hidden, self.attention_fn, self.sp_axis, self.sp_size,
            dtype=self.dtype,
            name="ensemble",
        )(obs_seq, action)
