"""CNN models for mixed proprioceptive + pixel observations.

Behavioral twins of the reference visual stack
(ref ``networks/convolutional.py``):

- :func:`conv_output_size` — flattened conv-stack output size
  (ref ``calculate_size``, ``convolutional.py:14-27``).
- :class:`SimpleCNN` — Atari-DQN trunk (filters [32,64,64], kernels
  [8,4,3], strides [4,2,1], VALID padding) -> Flatten -> Dense(512) ->
  Dense(out_features) (ref ``simple_cnn``, ``convolutional.py:30-51``,
  whose ``out_features`` is hardwired to **1**: the whole image becomes
  a single scalar).
- :class:`VisualActor` / :class:`VisualCritic` / :class:`VisualDoubleCritic`
  (ref ``convolutional.py:54-183``).

TPU-native differences:

- **NHWC layout** (uint8 HWC frames from the env, cast to float on
  device) instead of the reference's NCHW float frames — XLA:TPU's
  native conv layout; uint8 replay storage is 4x smaller in HBM.
- ``cnn_features`` is configurable. The default 1 reproduces the
  reference's scalar-vision bottleneck exactly (parity mode); widening
  it (e.g. 64) is the recommended deliberate deviation flagged in
  SURVEY.md §7 item 2.
- The twin visual critic is an explicitly unrolled ensemble (dense
  convs), NOT vmapped like
  :class:`~torch_actor_critic_tpu.models.critic.DoubleCritic`: vmapping
  per-member conv kernels lowers to grouped convolutions, which both
  XLA:CPU and the MXU handle far worse than independent dense convs
  (see :class:`VisualDoubleCritic`).
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from torch_actor_critic_tpu.core.types import MultiObservation
from torch_actor_critic_tpu.models.actor import clipped_noise_action
from torch_actor_critic_tpu.models.mlp import (
    MLP,
    Dense,
    torch_linear_bias_init,
    torch_linear_kernel_init,
)
from torch_actor_critic_tpu.ops.distributions import squashed_gaussian_sample


def conv_output_size(
    image_hw: t.Tuple[int, int],
    filters: t.Sequence[int],
    kernel_sizes: t.Sequence[int],
    strides: t.Sequence[int],
) -> int:
    """Flattened size after the VALID-padded conv stack.

    Same recurrence as the reference ``calculate_size``
    (ref ``convolutional.py:14-27``): ``d' = floor((d - k) / s + 1)``
    per spatial dim, channels replaced by the filter count.
    """
    h, w = image_hw
    c = filters[0]
    for f, k, s in zip(filters, kernel_sizes, strides):
        c = f
        h = int(np.floor((h - k) / s + 1))
        w = int(np.floor((w - k) / s + 1))
    return int(c * h * w)


class SimpleCNN(nn.Module):
    """Conv trunk -> Flatten -> Dense(dense_size) -> Dense(out_features).

    Expects NHWC input; uint8 frames are cast to float32 on entry (raw
    0-255 scale by default for parity — the reference never rescales
    pixels, ref ``wall_runner.py:54-59`` + ``visual_replay_buffer.py:52-58``).
    """

    filters: t.Sequence[int] = (32, 64, 64)
    kernel_sizes: t.Sequence[int] = (8, 4, 3)
    strides: t.Sequence[int] = (4, 2, 1)
    dense_size: int = 512
    out_features: int = 1  # the reference's scalar-vision bottleneck
    normalize_pixels: bool = False
    dtype: t.Any = jnp.float32  # conv/dense compute dtype; params stay f32

    @nn.compact
    def __call__(self, frame: jax.Array) -> jax.Array:
        dtype = self.dtype
        if jnp.issubdtype(frame.dtype, jnp.floating):
            # Fused pixel pipeline (ops/pixels.py): the frame batch
            # arrives already decoded, normalized and cast to the
            # compute dtype at sample time — decoding again here would
            # double-normalize. Float frames are, by contract,
            # pre-processed.
            x = frame
        else:
            # Legacy in-model decode — the bit-pinned reference path
            # (tac-lint frame-f32-materialize allowlists exactly this
            # site; new uint8->f32 frame decodes belong in ops/pixels).
            x = frame.astype(jnp.float32)
            if self.normalize_pixels:
                x = x / 255.0
        for i, (f, k, s) in enumerate(
            zip(self.filters, self.kernel_sizes, self.strides)
        ):
            fan_in = int(np.prod((k, k, x.shape[-1])))
            x = nn.Conv(
                f,
                kernel_size=(k, k),
                strides=(s, s),
                padding="VALID",
                kernel_init=torch_linear_kernel_init,
                bias_init=torch_linear_bias_init(fan_in),
                dtype=dtype,
                param_dtype=jnp.float32,
                name=f"conv_{i}",
            )(x)
            if 0 in x.shape[-3:]:
                raise ValueError(
                    f"SimpleCNN: conv_{i} (kernel {k}, stride {s}) reduced the "
                    f"feature map to {x.shape[-3:]}; the input image is too "
                    f"small for this conv geometry — shrink kernels/strides "
                    f"(SACConfig.filters/kernel_sizes/strides) or use larger "
                    f"frames."
                )
            x = nn.relu(x)
        x = x.reshape(x.shape[:-3] + (-1,))
        # Megatron pair over tp: the wide flatten->dense is
        # column-parallel, the projection to out_features row-parallel.
        x = Dense(self.dense_size, tp_role="col", dtype=dtype)(x)
        x = Dense(self.out_features, tp_role="row", dtype=dtype)(x)
        return x


def _visual_actor_trunk(mod, features: jax.Array, frame: jax.Array) -> jax.Array:
    """The MLP(features) ⊕ CNN(frame) embedding shared by both actor
    families (squashed-Gaussian and deterministic; identical attribute
    surface). Called inside ``nn.compact`` so submodule names — incl.
    the pinned ``visual_network`` — stay checkpoint-stable."""
    x = MLP(mod.hidden_sizes, activate_final=True, dtype=mod.dtype)(features)
    vision = SimpleCNN(
        mod.filters,
        mod.kernel_sizes,
        mod.strides,
        dense_size=mod.cnn_dense_size,
        out_features=mod.cnn_features,
        normalize_pixels=mod.normalize_pixels,
        dtype=mod.dtype,
        name="visual_network",
    )(frame)
    return jnp.concatenate([x, vision.astype(x.dtype)], axis=-1)


class VisualActor(nn.Module):
    """Squashed-Gaussian policy over a :class:`MultiObservation`.

    MLP trunk on ``features``, CNN embedding on ``frame``, concatenated
    before the ``mu``/``log_std`` heads (ref ``convolutional.py:78-104``:
    heads take ``hidden[-1] + cnn_features`` inputs). Unbatched inputs
    are auto-batched and outputs squeezed, mirroring the reference's
    reshape-and-squeeze behavior (ref ``convolutional.py:91-96,121``).
    """

    act_dim: int
    hidden_sizes: t.Sequence[int] = (256, 256)
    act_limit: float = 1.0
    filters: t.Sequence[int] = (32, 64, 64)
    kernel_sizes: t.Sequence[int] = (8, 4, 3)
    strides: t.Sequence[int] = (4, 2, 1)
    cnn_features: int = 1
    cnn_dense_size: int = 512  # conv trunk dense width (ref convolutional.py:36)
    normalize_pixels: bool = False
    dtype: t.Any = jnp.float32  # see Actor.dtype: matmuls only, heads cast f32

    @nn.compact
    def __call__(
        self,
        obs: MultiObservation,
        key: jax.Array | None = None,
        deterministic: bool = False,
        with_logprob: bool = True,
    ):
        dtype = self.dtype
        features, frame = obs.features, obs.frame
        unbatched = features.ndim == 1
        if unbatched:
            features = features[None]
        if frame.ndim == 3:
            frame = frame[None]

        x = _visual_actor_trunk(self, features, frame)
        mu = Dense(self.act_dim, dtype=dtype)(x).astype(jnp.float32)
        log_std = Dense(self.act_dim, dtype=dtype)(x).astype(jnp.float32)
        action, logprob = squashed_gaussian_sample(
            key, mu, log_std, self.act_limit, deterministic, with_logprob
        )
        if unbatched:
            action = jnp.squeeze(action, axis=0)
            if logprob is not None:
                logprob = jnp.squeeze(logprob, axis=0)
        return action, logprob


class DeterministicVisualActor(nn.Module):
    """Deterministic tanh policy over a :class:`MultiObservation` —
    the visual-stack actor for the TD3 extension (the reference has no
    TD3 and no visual deterministic policy; this mirrors
    :class:`VisualActor`'s trunk exactly — MLP(features) ⊕ CNN(frame)
    concat — with the single tanh head and clipped exploration noise of
    :class:`~torch_actor_critic_tpu.models.actor.DeterministicActor`).
    """

    act_dim: int
    hidden_sizes: t.Sequence[int] = (256, 256)
    act_limit: float = 1.0
    act_noise: float = 0.1
    filters: t.Sequence[int] = (32, 64, 64)
    kernel_sizes: t.Sequence[int] = (8, 4, 3)
    strides: t.Sequence[int] = (4, 2, 1)
    cnn_features: int = 1
    cnn_dense_size: int = 512  # conv trunk dense width (ref convolutional.py:36)
    normalize_pixels: bool = False
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        obs: MultiObservation,
        key: jax.Array | None = None,
        deterministic: bool = False,
        with_logprob: bool = True,  # noqa: ARG002 — contract-only
    ):
        features, frame = obs.features, obs.frame
        unbatched = features.ndim == 1
        if unbatched:
            features = features[None]
        if frame.ndim == 3:
            frame = frame[None]

        x = _visual_actor_trunk(self, features, frame)
        mu = Dense(self.act_dim, dtype=self.dtype)(x).astype(jnp.float32)
        action = clipped_noise_action(
            mu, self.act_limit, self.act_noise, key, deterministic,
            type(self).__name__,
        )
        if unbatched:
            action = jnp.squeeze(action, axis=0)
        return action, None


class VisualCritic(nn.Module):
    """Q-network over a :class:`MultiObservation` and an action.

    Parity quirk preserved: the feature/action MLP applies ReLU through
    **every** layer including the final width-1 output (ref
    ``convolutional.py:156-158`` loops activation over all layers), then
    concatenates the CNN embedding and applies a final
    ``Dense(1 + cnn_features -> 1)`` (ref ``convolutional.py:142,160-161``).
    """

    hidden_sizes: t.Sequence[int] = (256, 256)
    filters: t.Sequence[int] = (32, 64, 64)
    kernel_sizes: t.Sequence[int] = (8, 4, 3)
    strides: t.Sequence[int] = (4, 2, 1)
    cnn_features: int = 1
    cnn_dense_size: int = 512  # conv trunk dense width (ref convolutional.py:36)
    normalize_pixels: bool = False
    dtype: t.Any = jnp.float32  # see Critic.dtype: Q cast back to float32

    @nn.compact
    def __call__(self, obs: MultiObservation, action: jax.Array) -> jax.Array:
        dtype = self.dtype
        features, frame = obs.features, obs.frame
        unbatched = features.ndim == 1
        if unbatched:
            features = features[None]
            action = action[None]
        if frame.ndim == 3:
            frame = frame[None]

        x = jnp.concatenate([features, action], axis=-1)
        # ReLU after every layer, including the final width-1 layer
        # (reference behavior, convolutional.py:156-158).
        x = MLP(tuple(self.hidden_sizes) + (1,), activate_final=True,
                dtype=dtype)(x)
        vision = SimpleCNN(
            self.filters,
            self.kernel_sizes,
            self.strides,
            dense_size=self.cnn_dense_size,
            out_features=self.cnn_features,
            normalize_pixels=self.normalize_pixels,
            dtype=dtype,
            name="visual_network",
        )(frame)
        x = jnp.concatenate([x, vision.astype(x.dtype)], axis=-1)
        q = Dense(1, dtype=dtype, name="final")(x)
        q = jnp.squeeze(q.astype(jnp.float32), axis=-1)
        if unbatched:
            q = jnp.squeeze(q, axis=0)
        return q


class VisualDoubleCritic(nn.Module):
    """Unrolled ensemble of ``num_qs`` visual critics; returns ``(num_qs, ...)``.

    Capability twin of the reference ``VisualDoubleCritic``
    (ref ``convolutional.py:167-183``).

    Unlike the flat :class:`~torch_actor_critic_tpu.models.critic.DoubleCritic`
    (a vmapped parameter ensemble — matmuls batch perfectly over the
    ensemble axis), this ensemble is an explicit Python unroll over
    ``num_qs`` submodules (``ensemble_0``, ``ensemble_1``, ...). A
    vmapped *conv* with per-member kernels lowers to a
    ``feature_group_count=num_qs`` grouped convolution, which XLA:CPU
    implements naively (~7x slower than the equivalent dense convs,
    measured) and XLA:TPU tiles poorly onto the MXU; ``num_qs``
    independent dense convs fuse and schedule well on both. Per-layer
    group structure is inherent past the first conv (each member's
    layer N may only see its own layer N-1 outputs), so the unroll —
    not a wider fused conv — is the faithful dense formulation.
    """

    hidden_sizes: t.Sequence[int] = (256, 256)
    filters: t.Sequence[int] = (32, 64, 64)
    kernel_sizes: t.Sequence[int] = (8, 4, 3)
    strides: t.Sequence[int] = (4, 2, 1)
    cnn_features: int = 1
    cnn_dense_size: int = 512  # conv trunk dense width (ref convolutional.py:36)
    normalize_pixels: bool = False
    num_qs: int = 2
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs: MultiObservation, action: jax.Array) -> jax.Array:
        qs = [
            VisualCritic(
                self.hidden_sizes,
                self.filters,
                self.kernel_sizes,
                self.strides,
                self.cnn_features,
                self.cnn_dense_size,
                self.normalize_pixels,
                dtype=self.dtype,
                name=f"ensemble_{i}",
            )(obs, action)
            for i in range(self.num_qs)
        ]
        return jnp.stack(qs, axis=0)
