"""Squashed-Gaussian MLP actor.

Behavioral twin of the reference ``Actor`` (ref
``networks/linear.py:13-53``): ReLU MLP trunk, separate ``mu`` /
``log_std`` linear heads, log-std clipped to ``[-20, 2]``,
reparameterized sample, ``tanh * act_limit`` squash, softplus-form
log-prob correction — all via :mod:`torch_actor_critic_tpu.ops.distributions`.

TPU-native differences: a pure function of (params, obs, key) — the
PRNG key is explicit, so action selection jits and vmaps freely, and
``deterministic`` / ``with_logprob`` are static arguments that compile
to distinct (smaller) XLA programs rather than runtime branches.
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
from flax import linen as nn

from torch_actor_critic_tpu.models.mlp import MLP, Dense
from torch_actor_critic_tpu.ops.distributions import squashed_gaussian_sample


class Actor(nn.Module):
    """SquashedGaussian policy head over an MLP trunk.

    Attributes mirror the reference constructor
    (ref ``networks/linear.py:14-30``); ``act_limit`` defaults to 1.0
    (standard MuJoCo) rather than the reference's 10 — the train CLI
    passes the env's real limit exactly as the reference's
    ``init_session`` does (ref ``main.py:97``).
    """

    act_dim: int
    hidden_sizes: t.Sequence[int] = (256, 256)
    act_limit: float = 1.0
    # Compute dtype for trunk/head matmuls (params stay float32). The
    # distribution math (clip/exp/tanh/log-prob) always runs float32:
    # exp(log_std) and the softplus correction are precision-sensitive.
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        obs: jax.Array,
        key: jax.Array | None = None,
        deterministic: bool = False,
        with_logprob: bool = True,
    ):
        dtype = self.dtype
        trunk = MLP(self.hidden_sizes, activate_final=True, dtype=dtype)(obs)
        mu = Dense(self.act_dim, dtype=dtype)(trunk).astype(jnp.float32)
        log_std = Dense(self.act_dim, dtype=dtype)(trunk).astype(jnp.float32)
        return squashed_gaussian_sample(
            key, mu, log_std, self.act_limit, deterministic, with_logprob
        )


def clipped_noise_action(
    mu: jax.Array,
    act_limit: float,
    act_noise: float,
    key: jax.Array | None,
    deterministic: bool,
    module_name: str,
):
    """The TD3 deterministic head shared by the flat and visual actors:
    ``tanh(mu) * act_limit``, plus clipped zero-mean Gaussian
    exploration noise (std ``act_noise * act_limit``) when acting."""
    action = jnp.tanh(mu) * act_limit
    if deterministic:
        return action
    if key is None:
        raise ValueError(
            f"{module_name} needs a PRNG key for exploration noise; "
            "pass deterministic=True for the noiseless policy"
        )
    noise = act_noise * act_limit * jax.random.normal(key, action.shape)
    return jnp.clip(action + noise, -act_limit, act_limit)


class DeterministicActor(nn.Module):
    """Deterministic tanh policy for the TD3 extension.

    ``tanh(MLP(obs)) * act_limit``; when ``deterministic=False`` (env
    interaction), zero-mean Gaussian exploration noise with std
    ``act_noise * act_limit`` is added and the result clipped back to
    the action box — TD3's exploration scheme (Fujimoto et al. 2018;
    no reference counterpart, the reference is SAC-only). Returns
    ``(action, None)``: the log-prob slot exists only to satisfy the
    actor contract shared with the stochastic policies
    (``apply(params, obs, key, deterministic, with_logprob)``), since a
    deterministic policy has no density.
    """

    act_dim: int
    hidden_sizes: t.Sequence[int] = (256, 256)
    act_limit: float = 1.0
    act_noise: float = 0.1
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        obs: jax.Array,
        key: jax.Array | None = None,
        deterministic: bool = False,
        with_logprob: bool = True,  # noqa: ARG002 — contract-only
    ):
        trunk = MLP(self.hidden_sizes, activate_final=True, dtype=self.dtype)(obs)
        mu = Dense(self.act_dim, dtype=self.dtype)(trunk).astype(jnp.float32)
        action = clipped_noise_action(
            mu, self.act_limit, self.act_noise, key, deterministic,
            type(self).__name__,
        )
        return action, None
