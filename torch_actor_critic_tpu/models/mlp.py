"""MLP trunk builder.

The TPU-native counterpart of the reference's list-of-``nn.Linear``
factory (ref ``networks/core.py:6-10``; activations applied by callers,
ref ``networks/linear.py:33-35``). Here the trunk is a single Flax
module — a chain of ``Dense`` layers the XLA compiler fuses into MXU
matmuls with the ReLUs folded into the epilogues.

Initializers match torch ``nn.Linear`` defaults
(``U(-1/sqrt(fan_in), 1/sqrt(fan_in))`` for both kernel and bias) so
that our runs are distribution-identical to reference runs at init —
important for the ±5% return-parity gate in BASELINE.md.
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


def torch_linear_kernel_init(key: jax.Array, shape: t.Sequence[int], dtype=jnp.float32):
    """torch ``nn.Linear``/``nn.Conv2d`` weight init: kaiming-uniform(a=sqrt(5))
    == ``U(-1/sqrt(fan_in), 1/sqrt(fan_in))``. Works for both Flax Dense
    kernels ``(fan_in, fan_out)`` and Conv kernels ``(kh, kw, in, out)``:
    fan_in is the product of all but the last axis."""
    fan_in = int(np.prod(shape[:-1]))
    bound = 1.0 / np.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


def torch_linear_bias_init(fan_in: int):
    """torch ``nn.Linear``/``nn.Conv2d`` bias init:
    ``U(-1/sqrt(fan_in), 1/sqrt(fan_in))``."""

    def init(key: jax.Array, shape: t.Sequence[int], dtype=jnp.float32):
        bound = 1.0 / np.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -bound, bound)

    return init


class Dense(nn.Module):
    """``nn.Dense`` with torch-default initialization.

    ``tp_role`` is the layer's declared tensor-parallel layout —
    ``'col'`` (shard the output features), ``'row'`` (shard the input
    features), or ``'replicate'``. The role is encoded as the inner
    parameter-subtree name, so sharding-spec derivation
    (:func:`torch_actor_critic_tpu.parallel.sharding.tp_spec`) reads an
    explicit declaration made *by the module that knows its position*
    instead of guessing from auto-generated names.
    """

    features: int
    tp_role: str = "replicate"
    # Compute dtype for the matmul (params always stored float32 —
    # flax's param_dtype — so optimizer state, polyak targets and
    # checkpoints are precision-independent). bfloat16 is the MXU's
    # native input width; see SACConfig.compute_dtype.
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        fan_in = x.shape[-1]
        name = self.tp_role if self.tp_role in ("col", "row") else "Dense_0"
        return nn.Dense(
            self.features,
            kernel_init=torch_linear_kernel_init,
            bias_init=torch_linear_bias_init(fan_in),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name=name,
        )(x)


class MLP(nn.Module):
    """Plain ReLU MLP.

    ``hidden_sizes`` are the layer widths; ReLU after every layer when
    ``activate_final`` (the actor trunk, ref ``networks/linear.py:33-35``),
    or after all but the last (the critic, ref ``networks/linear.py:63-67``).

    Layers declare Megatron-paired tensor-parallel roles by their own
    index — even layers column-parallel, odd row-parallel — so a
    consecutive (col, row) pair costs a single ``psum`` under ``tp``.
    """

    hidden_sizes: t.Sequence[int]
    activate_final: bool = True
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        n = len(self.hidden_sizes)
        for i, width in enumerate(self.hidden_sizes):
            x = Dense(
                width, tp_role="col" if i % 2 == 0 else "row", dtype=self.dtype
            )(x)
            if self.activate_final or i < n - 1:
                x = nn.relu(x)
        return x
