"""Per-agent policy/critic heads for the multi-agent scenarios.

The agent axis uses the SAME machinery the PR-6 population and the
``DoubleCritic`` ensemble ride: ``nn.vmap`` with
``variable_axes={"params": 0}``, so N agents' independent MLP heads
batch onto the MXU as one set of stacked matmuls — one weight fetch, N
agents of useful FLOPs — instead of N sequential small kernels.

Factorization contract (shared with ``scenarios/multiagent.py``):

- the *joint* observation is the flat concatenation of ``n_agents``
  per-agent observations (``agent_obs_dim`` each); the joint action is
  the concatenation of per-agent actions;
- :class:`MultiAgentActor` samples each agent's action from its OWN
  squashed-Gaussian head over its OWN observation slice (decentralized
  execution); the joint log-prob is the per-agent sum, which is exactly
  what one diagonal Gaussian over the concatenated action computes —
  so SAC's entropy machinery applies unchanged;
- training is centralized (CTDE): the default critic is the plain
  :class:`~torch_actor_critic_tpu.models.critic.DoubleCritic` over the
  joint (obs, action) — no new critic code needed; the alternative
  :class:`MultiAgentDoubleCritic` is the VDN-style decomposition
  (per-agent twin critics over local slices, summed into the joint Q),
  selected by ``config.ma_critic="per_agent"``.
"""

from __future__ import annotations

import typing as t

import jax
import jax.numpy as jnp
from flax import linen as nn

from torch_actor_critic_tpu.models.mlp import MLP, Dense
from torch_actor_critic_tpu.ops.distributions import squashed_gaussian_sample


class _AgentGaussianHeads(nn.Module):
    """One agent's trunk + (mu, log_std) heads over its local obs."""

    act_dim: int
    hidden_sizes: t.Sequence[int]
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array):
        trunk = MLP(self.hidden_sizes, activate_final=True, dtype=self.dtype)(obs)
        mu = Dense(self.act_dim, dtype=self.dtype)(trunk)
        log_std = Dense(self.act_dim, dtype=self.dtype)(trunk)
        return mu, log_std


class _AgentQ(nn.Module):
    """One agent's Q over its local (obs, action) slice."""

    hidden_sizes: t.Sequence[int]
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)
        x = MLP(tuple(self.hidden_sizes) + (1,), activate_final=False,
                dtype=self.dtype)(x)
        return jnp.squeeze(x, axis=-1)


class MultiAgentActor(nn.Module):
    """N independent squashed-Gaussian heads over per-agent obs slices.

    Honors the shared actor contract
    ``apply(params, obs, key, deterministic, with_logprob) ->
    (action, logp)`` with the joint flat obs/action, so the fused loop,
    SAC losses and the serving engine use it like any other actor.
    """

    n_agents: int
    agent_obs_dim: int
    act_dim: int  # joint: n_agents * per-agent act dim
    hidden_sizes: t.Sequence[int] = (256, 256)
    act_limit: float = 1.0
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        obs: jax.Array,
        key: jax.Array | None = None,
        deterministic: bool = False,
        with_logprob: bool = True,
    ):
        if self.act_dim % self.n_agents:
            raise ValueError(
                f"joint act_dim {self.act_dim} must split evenly over "
                f"{self.n_agents} agents"
            )
        agent_act = self.act_dim // self.n_agents
        batch_shape = obs.shape[:-1]
        per = obs.reshape(
            batch_shape + (self.n_agents, self.agent_obs_dim)
        )
        heads = nn.vmap(
            _AgentGaussianHeads,
            in_axes=-2,
            out_axes=-2,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(
            act_dim=agent_act,
            hidden_sizes=self.hidden_sizes,
            dtype=self.dtype,
            name="agents",
        )
        mu, log_std = heads(per)  # (..., n_agents, agent_act)
        # Joint diagonal Gaussian over the concatenated action: the
        # sample factorizes per agent and the log-prob sums per agent —
        # the product policy, via the ONE shared sampling op.
        mu = mu.reshape(batch_shape + (self.act_dim,)).astype(jnp.float32)
        log_std = log_std.reshape(batch_shape + (self.act_dim,)).astype(
            jnp.float32
        )
        return squashed_gaussian_sample(
            key, mu, log_std, self.act_limit, deterministic, with_logprob
        )


class MultiAgentDoubleCritic(nn.Module):
    """VDN-style twin critics: per-agent Q over local slices, summed.

    Returns ``(num_qs, batch)`` like ``DoubleCritic`` — the joint Q is
    the sum of per-agent utilities, so the SAC losses are unchanged.
    The per-agent axis and the twin-Q ensemble are BOTH ``nn.vmap``
    parameter axes (agents inside, ensemble outside).
    """

    n_agents: int
    agent_obs_dim: int
    agent_act_dim: int
    hidden_sizes: t.Sequence[int] = (256, 256)
    num_qs: int = 2
    dtype: t.Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        batch_shape = obs.shape[:-1]
        per_obs = obs.reshape(
            batch_shape + (self.n_agents, self.agent_obs_dim)
        )
        per_act = action.reshape(
            batch_shape + (self.n_agents, self.agent_act_dim)
        )
        per_agent = nn.vmap(
            _AgentQ,
            in_axes=(-2, -2),
            out_axes=-1,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        ensemble = nn.vmap(
            per_agent,
            in_axes=(None, None),
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            axis_size=self.num_qs,
        )(hidden_sizes=self.hidden_sizes, dtype=self.dtype, name="ensemble")
        q_per_agent = ensemble(per_obs, per_act)  # (num_qs, ..., n_agents)
        return jnp.sum(q_per_agent.astype(jnp.float32), axis=-1)
