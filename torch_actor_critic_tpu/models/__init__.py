from torch_actor_critic_tpu.models.mlp import MLP, torch_linear_bias_init, torch_linear_kernel_init  # noqa: F401
from torch_actor_critic_tpu.models.actor import Actor, DeterministicActor  # noqa: F401
from torch_actor_critic_tpu.models.critic import Critic, DoubleCritic  # noqa: F401
from torch_actor_critic_tpu.models.visual import (  # noqa: F401
    DeterministicVisualActor,
    SimpleCNN,
    VisualActor,
    VisualCritic,
    VisualDoubleCritic,
    conv_output_size,
)
from torch_actor_critic_tpu.models.sequence import (  # noqa: F401
    SequenceActor,
    SequenceCritic,
    SequenceDoubleCritic,
    SequenceTrunk,
)
from torch_actor_critic_tpu.models.multiagent import (  # noqa: F401
    MultiAgentActor,
    MultiAgentDoubleCritic,
)
from torch_actor_critic_tpu.models.taskembed import (  # noqa: F401
    TaskConditionedActor,
    TaskConditionedDoubleCritic,
)
