"""Divergence sentinel: detect non-finite training state, budget rollbacks.

A single NaN reward (bad physics step, corrupted host memory, an env
bug) poisons the twin-Q targets and from there every parameter within
a handful of updates — and the reference trainer keeps stepping a dead
run for days (ref ``sac/algorithm.py:182-307`` has no finiteness check
anywhere). The sentinel makes divergence a *recoverable event*:

- :func:`tree_all_finite` — one fused all-finite reduction over
  arbitrary pytrees (params, optimizer state, losses, the replay
  ring). jit-compiled, so on TPU it is a single pass over HBM
  (~1 ms/GB) scheduled once per logging interval, off the hot loop.
- :class:`DivergenceSentinel` — the skip-and-resume policy: every
  divergence is answered by a rollback to the last sentinel-validated
  checkpoint (the trainer only checkpoints states the sentinel has
  passed, so "latest checkpoint" and "last-good" are the same thing),
  bounded by ``max_rollbacks`` *consecutive* failures before the run
  aborts with :class:`TrainingDiverged`. A finite epoch resets the
  budget: recovering from occasional faults is the normal path,
  oscillating forever is not.

The replay ring is part of the checked state on purpose: a NaN
transition sits in the buffer waiting to be sampled long after the
step that produced it, so checking losses alone would make recovery a
sampling lottery — rolling back params while keeping a poisoned
buffer re-diverges on the next unlucky batch.
"""

from __future__ import annotations

import logging
import typing as t

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

__all__ = ["TrainingDiverged", "DivergenceSentinel", "tree_all_finite"]


class TrainingDiverged(RuntimeError):
    """Raised when divergence persists past the rollback budget (or no
    checkpoint exists to roll back to)."""


@jax.jit
def _all_finite(leaves: t.List[jax.Array]) -> jax.Array:
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]))


def tree_all_finite(*trees: t.Any) -> bool:
    """True iff every inexact (float/complex) leaf of every tree is
    finite. Integer/bool/PRNG-key leaves are skipped host-side (they
    cannot hold NaN/inf); the reduction itself runs as one jitted
    program, retraced only per leaf-list structure."""
    leaves = [
        x
        for tree in trees
        for x in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
    ]
    if not leaves:
        return True
    return bool(_all_finite(leaves))


class DivergenceSentinel:
    """Rollback budget + bookkeeping around :func:`tree_all_finite`.

    Also the accounting point for **leading indicators**: the
    diagnostics early-warning monitor
    (:mod:`torch_actor_critic_tpu.diagnostics.monitor`) reports grad
    spikes / entropy collapses / Q-bias drift here via
    :meth:`note_warning` — epochs before any of them matures into the
    NaN this sentinel detects, so the telemetry stream shows the
    warning→divergence causality and operators can act on the warning
    (docs/RESILIENCE.md "Early warnings").
    """

    def __init__(self, max_rollbacks: int = 3):
        if max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {max_rollbacks}"
            )
        self.max_rollbacks = max_rollbacks
        self.consecutive = 0
        self.total_rollbacks = 0
        self.warnings_total = 0
        self.warnings_by_kind: t.Dict[str, int] = {}

    def check(self, *trees: t.Any) -> bool:
        """One sentinel pass; ``False`` means the caller must roll back
        (or abort via :meth:`note_divergence`)."""
        return tree_all_finite(*trees)

    def note_good(self) -> None:
        """A validated interval closes any divergence streak."""
        self.consecutive = 0

    def note_warning(self, kind: str) -> None:
        """Record a leading-indicator warning (no rollback, no budget
        consumed): the sentinel is the one place both early warnings
        and actual divergences are tallied, so their correlation is
        readable from a single object (and metrics.jsonl carries both
        ``early_warnings`` and ``rollbacks``)."""
        self.warnings_total += 1
        self.warnings_by_kind[kind] = self.warnings_by_kind.get(kind, 0) + 1

    def note_divergence(self, where: str = "training state") -> None:
        """Account one divergence; raises :class:`TrainingDiverged`
        once the consecutive budget is exhausted."""
        self.consecutive += 1
        self.total_rollbacks += 1
        if self.consecutive > self.max_rollbacks:
            raise TrainingDiverged(
                f"non-finite {where} persisted through "
                f"{self.max_rollbacks} consecutive rollbacks — the fault "
                "is systematic (bad hyperparameters, a deterministic env "
                "bug), not transient; aborting instead of looping"
            )
