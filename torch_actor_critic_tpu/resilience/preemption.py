"""Preemption-aware shutdown: SIGTERM/SIGINT -> save -> requeue exit.

Preemptible TPU slices hand the host a SIGTERM and a grace window;
the reference trainer dies mid-``allreduce`` and its half-written
MLflow artifacts brick the resume (ref ``main.py:28-51``). Podracer
(arXiv:2104.06272) treats preemption as a *normal event* — that is
the contract here:

- :class:`PreemptionGuard` installs idempotent SIGTERM/SIGINT
  handlers that only set a flag (async-signal-safe; no IO in the
  handler). The trainer polls the flag at safe boundaries:

  * **first signal** — graceful: finish the current epoch, take the
    regular end-of-epoch checkpoint synchronously, exit. Epochs are
    replayable units (epoch-boundary env reseeding,
    ``sac/trainer.py``), so resume is bitwise-lossless.
  * **second signal** — urgent: checkpoint at the next *update-window*
    boundary (staging just flushed, burst complete — the safe step
    boundary) and exit immediately. The learner state is still
    lossless; only the un-stepped tail of the epoch's env interaction
    is skipped on resume.

- :class:`Preempted` unwinds the training loop after the emergency
  save; ``train.py`` maps it to :data:`REQUEUE_EXIT_CODE` (75,
  BSD ``EX_TEMPFAIL`` — the classic "transient, try again" code) so
  ``make``/schedulers can distinguish *requeue me* from a crash and
  restart with ``--run <id>`` for a lossless resume.

Multi-host: schedulers deliver SIGTERM to every rank of a preempted
slice, and the end-of-epoch Orbax save is already collective, so each
process reaches the same save at the same boundary.
"""

from __future__ import annotations

import logging
import signal
import threading
import typing as t

logger = logging.getLogger(__name__)

__all__ = ["REQUEUE_EXIT_CODE", "Preempted", "PreemptionGuard"]

# BSD EX_TEMPFAIL: "temporary failure, retry later" — distinct from
# every Python/pytest/segfault exit code a crash would produce.
REQUEUE_EXIT_CODE = 75


class Preempted(RuntimeError):
    """Training was interrupted by a preemption signal *after* the
    emergency checkpoint landed; carries the requeue exit code."""

    def __init__(self, epoch: int, urgent: bool = False):
        self.epoch = epoch
        self.urgent = urgent
        self.exit_code = REQUEUE_EXIT_CODE
        super().__init__(
            f"preempted at epoch {epoch} "
            f"({'window' if urgent else 'epoch'} boundary); state saved, "
            f"exit with code {REQUEUE_EXIT_CODE} to requeue"
        )


class PreemptionGuard:
    """Signal-flag bridge between the OS and the training loop.

    ``install()`` replaces the handlers (saving the previous ones for
    ``uninstall()``); :meth:`request_preemption` is the programmatic
    path used by the fault-injection harness and by embedders that
    learn of preemption through an API instead of a signal (GCE
    metadata server, k8s preStop hook).
    """

    def __init__(
        self,
        signals: t.Sequence[int] = (signal.SIGTERM, signal.SIGINT),
    ):
        self.signals = tuple(signals)
        self._count = 0
        self._event = threading.Event()
        self._previous: dict = {}
        self.exit_code = REQUEUE_EXIT_CODE

    # ------------------------------------------------------------ handlers

    def _handle(self, signum, frame) -> None:  # noqa: ARG002
        # Flag-only: logging/IO is not async-signal-safe.
        self._count += 1
        self._event.set()

    def install(self) -> "PreemptionGuard":
        for sig in self.signals:
            try:
                self._previous[sig] = signal.signal(sig, self._handle)
            except ValueError:
                # Not the main thread (embedded trainer): signal-based
                # delivery is unavailable, request_preemption still works.
                logger.warning(
                    "cannot install handler for signal %s outside the "
                    "main thread; use request_preemption()", sig,
                )
        return self

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ------------------------------------------------------------- queries

    def request_preemption(self, urgent: bool = False) -> None:
        """Programmatic trigger: one call == one signal; ``urgent=True``
        counts as two (skip straight to the window-boundary save)."""
        self._count += 2 if urgent else 1
        self._event.set()

    @property
    def triggered(self) -> bool:
        """At least one signal: save and exit at the next epoch boundary."""
        return self._count >= 1

    @property
    def urgent(self) -> bool:
        """Repeated signals: save and exit at the next window boundary."""
        return self._count >= 2

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the first signal (monitoring threads)."""
        return self._event.wait(timeout)
