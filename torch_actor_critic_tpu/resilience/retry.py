"""Bounded retry-with-backoff for flaky checkpoint IO.

Long preemptible runs checkpoint to network filesystems (GCS fuse,
NFS) whose transient failures — timeouts, connection resets, 5xx
surfacing as ``OSError`` — are routine over a multi-day horizon. The
reference has no story at all: one failed MLflow write kills the run.
Here every Orbax save/restore goes through :func:`call_with_retries`
(``utils/checkpoint.py``), so a transient fault costs one backoff
sleep instead of the run.

Deterministic by design: the caller injects the ``sleep`` function, so
tests drive the retry ladder with zero real waiting (the
no-sleeps-flakiness rule in ``tests/test_resilience.py``).
"""

from __future__ import annotations

import logging
import time
import typing as t

logger = logging.getLogger(__name__)

__all__ = ["call_with_retries"]


def call_with_retries(
    fn: t.Callable[[], t.Any],
    *,
    attempts: int = 3,
    base_delay_s: float = 0.5,
    retry_on: t.Tuple[type, ...] = (OSError,),
    give_up_on: t.Tuple[type, ...] = (FileNotFoundError,),
    sleep: t.Callable[[float], None] = time.sleep,
    what: str = "checkpoint IO",
):
    """Run ``fn`` with up to ``attempts`` tries and exponential backoff.

    ``retry_on`` classifies transient faults; ``give_up_on`` carves out
    subclasses that are deterministic, not transient (a half-written
    checkpoint raises ``FileNotFoundError`` — an ``OSError`` subclass —
    on every read; retrying it only delays the fallback to the previous
    epoch). The final failure re-raises the original exception so
    callers keep their error taxonomy.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    for attempt in range(attempts):
        try:
            return fn()
        except give_up_on:
            raise
        except retry_on as e:
            if attempt == attempts - 1:
                raise
            delay = base_delay_s * (2**attempt)
            logger.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.2fs",
                what, attempt + 1, attempts, e, delay,
            )
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
