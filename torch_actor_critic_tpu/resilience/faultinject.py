"""Fault-injection harness: drive real failure modes through a real Trainer.

Recovery code that only runs during real outages is recovery code that
has never run. This module injects each production fault class into an
unmodified :class:`~torch_actor_critic_tpu.sac.trainer.Trainer` so
``tests/test_resilience.py`` can prove every recovery path end-to-end
on CPU:

- **NaN batches** — :class:`FaultyEnvPool` wraps any env pool and
  corrupts scheduled step outputs (rewards/observations), exercising
  the divergence sentinel + rollback path.
- **Simulated SIGTERM** — :meth:`FaultyEnvPool.call_at` runs an
  arbitrary callback at an exact pool step (e.g. ``os.kill(os.getpid(),
  SIGTERM)`` or ``guard.request_preemption()``), exercising the
  preemption save/requeue path deterministically: everything keys off
  step counts, never wall-clock sleeps.
- **Env-worker death** — :func:`kill_env_worker` SIGKILLs a
  :class:`ParallelEnvPool` worker and *joins* it, so the next pool op
  deterministically observes a dead (not "maybe-dead") worker.
- **Actor-process death** — :func:`kill_actor` SIGKILLs a fleet actor
  (by supervisor slot index or raw pid) and joins it, so the
  supervisor's next liveness poll deterministically sees the corpse —
  the restart/purge/dedup chain of ``decoupled/fleet.py`` runs against
  a provably-dead process.
- **Staging-transport flap** — :class:`FlakyTransport` wraps the
  actor's staging POST callable (the :class:`RemoteStagingClient`
  ``post`` seam) with scheduled connection drops and latency — the
  LossyLink pattern moved to the push path, driving the retry/backoff
  + sequence-number dedup machinery instead of the acting path.
- **Checkpoint IO faults** — :func:`make_flaky` wraps any callable to
  fail its first N calls (transient-IO retry path);
  :func:`corrupt_checkpoint` damages an on-disk Orbax step the way an
  interrupted async save does (missing items / truncated arrays), or
  NaN-poisons its parameters (``mode="nan-params"`` — the silent
  corruption the serving reload sentinel must reject), exercising the
  fallback-to-previous-epoch and last-good-generation paths.
- **Serving-engine faults** — :class:`FaultyEngine` wraps a
  :class:`~torch_actor_critic_tpu.serve.engine.PolicyEngine` and makes
  scheduled forwards raise (the forward-failure trip path of the
  circuit breaker); :func:`nan_params` NaN-poisons a params pytree so
  the engine's own in-graph all-finite reduction fires (the
  non-finite trip path); :func:`flood` fires a burst of requests past
  service rate at a micro-batcher (the admission-control/queue-bound
  path — ``scripts/chaos_smoke.py`` and ``tests/test_overload.py``).

Injection is deliberately *compositional*: tests build a normal
Trainer, then ``trainer.pool = FaultyEnvPool(trainer.pool, ...)`` —
the trainer code under test is exactly the code production runs; the
serving tests likewise wrap real engines and flood real batchers.
"""

from __future__ import annotations

import os
import shutil
import signal
import typing as t
from pathlib import Path

import numpy as np

__all__ = [
    "FaultyEnvPool",
    "FaultyEngine",
    "FlakyTransport",
    "LossyLink",
    "kill_actor",
    "kill_env_worker",
    "make_flaky",
    "corrupt_checkpoint",
    "nan_params",
    "flood",
]


class FaultyEnvPool:
    """Protocol-transparent env-pool wrapper with step-scheduled faults.

    Wraps any object implementing the pool protocol
    (``envs/vec_env.py``); every attribute not overridden here proxies
    to the wrapped pool, so the trainer cannot tell the difference.
    Step numbering counts ``step()`` calls on THIS wrapper, starting
    at 0 — i.e. lockstep trainer steps.
    """

    def __init__(self, pool: t.Any):
        self._pool = pool
        self._step_count = 0
        self._before: t.Dict[int, t.List[t.Callable[[], None]]] = {}
        self._corrupt: t.Dict[int, t.List[t.Callable]] = {}

    # ---------------------------------------------------------- scheduling

    def call_at(self, step: int, fn: t.Callable[[], None]) -> "FaultyEnvPool":
        """Run ``fn()`` immediately before pool step ``step`` executes."""
        self._before.setdefault(step, []).append(fn)
        return self

    def nan_rewards_at(
        self, step: int, envs: t.Sequence[int] | None = None
    ) -> "FaultyEnvPool":
        """Replace the scheduled step's rewards with NaN (all envs by
        default) — the classic silent-poison fault."""

        def corrupt(obs, rewards, terms, truncs):
            rewards = np.array(rewards, np.float32)
            rewards[list(envs) if envs is not None else slice(None)] = np.nan
            return obs, rewards, terms, truncs

        self._corrupt.setdefault(step, []).append(corrupt)
        return self

    def nan_obs_at(
        self, step: int, envs: t.Sequence[int] | None = None
    ) -> "FaultyEnvPool":
        """NaN the scheduled step's next-observations (flat leaves)."""

        def corrupt(obs, rewards, terms, truncs):
            import jax

            rows = list(envs) if envs is not None else None

            def poison(x):
                x = np.array(x)
                if np.issubdtype(x.dtype, np.floating):
                    x[rows if rows is not None else slice(None)] = np.nan
                return x

            return (
                jax.tree_util.tree_map(poison, obs), rewards, terms, truncs,
            )

        self._corrupt.setdefault(step, []).append(corrupt)
        return self

    # ------------------------------------------------------------ protocol

    def step(self, actions):
        n = self._step_count
        self._step_count += 1
        for fn in self._before.pop(n, []):
            fn()
        out = self._pool.step(actions)
        for corrupt in self._corrupt.pop(n, []):
            out = corrupt(*out)
        return out

    def __getattr__(self, name: str):
        return getattr(self._pool, name)


class FaultyEngine:
    """Protocol-transparent :class:`PolicyEngine` wrapper with
    scheduled forward failures — the engine-fault injector for the
    circuit-breaker path.

    Wraps a real engine (every attribute proxies through, so the
    batcher cannot tell the difference) and makes the next ``n``
    ``act`` calls raise. Register the wrapped slot, then::

        faulty = FaultyEngine(registry._slots["default"].engine)
        registry._slots["default"].engine = faulty      # tests only
        faulty.fail_next(5)                             # trips breaker

    Counting is on ``act`` calls on THIS wrapper, so tests can assert
    exactly how many forwards the engine actually ran (e.g. that a
    purged request never reached it).
    """

    def __init__(self, engine: t.Any):
        self._engine = engine
        self._fail_left = 0
        self._exc_factory: t.Callable[[], BaseException] = lambda: (
            RuntimeError("injected engine forward failure")
        )
        self.calls_total = 0
        self.failures_injected = 0

    def fail_next(
        self,
        n: int,
        exc_factory: t.Callable[[], BaseException] | None = None,
    ) -> "FaultyEngine":
        """Make the next ``n`` forwards raise (cumulative with any
        already scheduled)."""
        self._fail_left += int(n)
        if exc_factory is not None:
            self._exc_factory = exc_factory
        return self

    def act(self, *args, **kwargs):
        self.calls_total += 1
        if self._fail_left > 0:
            self._fail_left -= 1
            self.failures_injected += 1
            raise self._exc_factory()
        return self._engine.act(*args, **kwargs)

    def __getattr__(self, name: str):
        return getattr(self._engine, name)


class LossyLink:
    """Protocol-transparent lossy/slow network link between a policy
    client and its server — the actor↔serving fault injector for the
    decoupled plane (docs/RESILIENCE.md "Decoupled-plane failure
    modes").

    Wraps anything with an ``act(...)`` method (a
    :class:`~torch_actor_critic_tpu.serve.server.PolicyClient` in
    either transport mode, a :class:`~torch_actor_critic_tpu.serve.
    batcher.MicroBatcher`, a whole
    :class:`~torch_actor_critic_tpu.serve.fleet.EngineFleet`) and, per
    call, injects configurable **latency** (``latency_s``, via the
    injectable ``sleep``) and **drops** — a dropped call raises
    ``ConnectionError`` (an ``OSError``, exactly what a real dead link
    surfaces through urllib), so the caller's degradation path runs,
    not a special test path. Drops are either probabilistic
    (``drop_rate`` with a seedable ``rng``) or exactly scheduled
    (:meth:`drop_next` — the deterministic mode the step-synchronized
    tests use). Usable standalone::

        link = LossyLink(client, latency_s=0.05, drop_rate=0.3,
                         rng=random.Random(0))
        actor = ActorWorker(link, staging, fallback=...)

    Counting is on calls through THIS wrapper (``calls_total`` /
    ``drops_injected``) so tests can assert exactly which calls died.
    """

    def __init__(
        self,
        client: t.Any,
        drop_rate: float = 0.0,
        latency_s: float = 0.0,
        rng=None,
        sleep: t.Callable[[float], None] = None,
    ):
        if not 0.0 <= drop_rate <= 1.0:
            raise ValueError(f"drop_rate must be in [0, 1], got {drop_rate}")
        import random as _random
        import time as _time

        self._client = client
        self.drop_rate = float(drop_rate)
        self.latency_s = float(latency_s)
        self._rng = rng if rng is not None else _random.Random()
        self._sleep = sleep if sleep is not None else _time.sleep
        self._drop_left = 0
        self.calls_total = 0
        self.drops_injected = 0
        self.latency_injected_s = 0.0

    def drop_next(self, n: int) -> "LossyLink":
        """Deterministically drop the next ``n`` calls (cumulative with
        any already scheduled; takes precedence over ``drop_rate``)."""
        self._drop_left += int(n)
        return self

    def act(self, *args, **kwargs):
        self.calls_total += 1
        if self.latency_s > 0.0:
            self.latency_injected_s += self.latency_s
            self._sleep(self.latency_s)
        dropped = False
        if self._drop_left > 0:
            self._drop_left -= 1
            dropped = True
        elif self.drop_rate > 0.0 and self._rng.random() < self.drop_rate:
            dropped = True
        if dropped:
            self.drops_injected += 1
            raise ConnectionError(
                "injected lossy link: request dropped in flight "
                f"(call {self.calls_total})"
            )
        return self._client.act(*args, **kwargs)

    def __getattr__(self, name: str):
        return getattr(self._client, name)


class FlakyTransport:
    """Lossy/slow staging-push link: the LossyLink pattern moved from
    the acting path to the transport POST path (docs/RESILIENCE.md
    "Decoupled-plane failure modes", transport-flap row).

    Wraps the :class:`~torch_actor_critic_tpu.decoupled.transport.
    RemoteStagingClient` ``post`` callable (``post(path, payload,
    timeout_s) -> (status, body)``) and, per call, injects configurable
    **latency** (``latency_s``, via the injectable ``sleep``) and
    **drops** — a dropped call raises ``ConnectionError`` (an
    ``OSError``, what a real dead link surfaces through urllib), so the
    client's jittered retry/backoff + the server's sequence-number
    dedup run, not a special test path. Drops are probabilistic
    (``drop_rate`` with a seedable ``rng``) or exactly scheduled
    (:meth:`drop_next`). Inject either directly::

        client._post = FlakyTransport(client._post, drop_rate=0.3)

    or, for spawned fleet actors, via the ``TAC_FLAKY_PUSH`` env var
    (``"drop_rate=0.3,latency_s=0.01,seed=0"`` — decoupled/fleet.py),
    which is how the chaos smoke flaps the whole fleet's push path.
    """

    def __init__(
        self,
        post: t.Callable,
        drop_rate: float = 0.0,
        latency_s: float = 0.0,
        rng=None,
        sleep: t.Callable[[float], None] = None,
    ):
        if not 0.0 <= drop_rate <= 1.0:
            raise ValueError(f"drop_rate must be in [0, 1], got {drop_rate}")
        import random as _random
        import time as _time

        self._post = post
        self.drop_rate = float(drop_rate)
        self.latency_s = float(latency_s)
        self._rng = rng if rng is not None else _random.Random()
        self._sleep = sleep if sleep is not None else _time.sleep
        self._drop_left = 0
        self.calls_total = 0
        self.drops_injected = 0
        self.latency_injected_s = 0.0

    def drop_next(self, n: int) -> "FlakyTransport":
        """Deterministically drop the next ``n`` POSTs (cumulative;
        takes precedence over ``drop_rate``)."""
        self._drop_left += int(n)
        return self

    def __call__(self, path: str, payload: dict, timeout_s: float):
        self.calls_total += 1
        if self.latency_s > 0.0:
            self.latency_injected_s += self.latency_s
            self._sleep(self.latency_s)
        dropped = False
        if self._drop_left > 0:
            self._drop_left -= 1
            dropped = True
        elif self.drop_rate > 0.0 and self._rng.random() < self.drop_rate:
            dropped = True
        if dropped:
            self.drops_injected += 1
            raise ConnectionError(
                "injected flaky transport: POST dropped in flight "
                f"({path}, call {self.calls_total})"
            )
        return self._post(path, payload, timeout_s)


def kill_actor(
    target: t.Any, idx: int | None = None, join_timeout_s: float = 10.0
) -> int:
    """SIGKILL a fleet actor process and reap it.

    ``target`` is either a :class:`~torch_actor_critic_tpu.decoupled.
    fleet.FleetSupervisor` with ``idx`` naming the actor slot, or a raw
    pid (``idx`` omitted). Joining before returning makes the death
    *observable*: the supervisor's next liveness poll deterministically
    finds a dead process (not a maybe-dead one), so the
    kill→purge→restart→dedup chain is step-synchronized in tests.
    Returns the killed pid.
    """
    if idx is not None:
        with target._lock:
            proc = target._procs.get(idx)
        if proc is None:
            raise ValueError(f"supervisor has no live actor in slot {idx}")
        pid = proc.pid
        os.kill(pid, signal.SIGKILL)
        proc.join(timeout=join_timeout_s)
        if proc.is_alive():  # pragma: no cover — SIGKILL cannot be blocked
            raise RuntimeError(f"actor {idx} (pid {pid}) survived SIGKILL")
        return pid
    pid = int(target)
    os.kill(pid, signal.SIGKILL)
    # Raw-pid mode: not our child (e.g. the smoke killing across a
    # process boundary) — waitpid would raise; the kernel reaps it.
    return pid


def nan_params(params: t.Any, fraction_leaf: int = 0) -> t.Any:
    """NaN-poison a params pytree: every float leaf (or just leaf index
    ``fraction_leaf`` onward — one poisoned leaf is enough for the
    sentinel) becomes all-NaN. The non-finite-output injector: swap the
    result into a serving slot (``registry.swap(..., validate=False)``)
    and the engine's in-graph all-finite reduction reports every
    forward to the circuit breaker."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    out = []
    for i, x in enumerate(leaves):
        x = np.asarray(x)
        if i >= fraction_leaf and np.issubdtype(x.dtype, np.floating):
            x = np.full_like(x, np.nan)
        out.append(x)
    return jax.tree_util.tree_unflatten(treedef, out)


def flood(
    submit: t.Callable[..., t.Any],
    obs: t.Any,
    n_requests: int,
    **submit_kwargs,
) -> t.Tuple[list, list]:
    """Fire ``n_requests`` submits back-to-back (far past service
    rate) and return ``(futures, shed_errors)`` — accepted requests'
    futures versus the structured rejections admission control
    answered instead of queueing. ``submit`` is typically
    ``MicroBatcher.submit``; any exception that is not a rejection
    propagates (a flood must not hide real bugs)."""
    from torch_actor_critic_tpu.serve.admission import ShedError

    futures, sheds = [], []
    for _ in range(int(n_requests)):
        try:
            futures.append(submit(obs, **submit_kwargs))
        except ShedError as e:
            sheds.append(e)
    return futures, sheds


def kill_env_worker(pool, idx: int, join_timeout_s: float = 10.0) -> int:
    """SIGKILL worker ``idx`` of a :class:`ParallelEnvPool` and reap it.

    Joining before returning makes the death *observable* — the next
    pool operation deterministically times out and diagnoses a dead
    worker (with its exit code) instead of racing the kernel. Returns
    the worker's exit code (``-SIGKILL``).
    """
    proc = pool._procs[idx]
    os.kill(proc.pid, signal.SIGKILL)
    proc.join(timeout=join_timeout_s)
    if proc.is_alive():  # pragma: no cover — SIGKILL cannot be blocked
        raise RuntimeError(f"worker {idx} survived SIGKILL")
    return proc.exitcode


def make_flaky(
    fn: t.Callable,
    failures: int,
    exc_factory: t.Callable[[], BaseException] = lambda: OSError(
        "injected transient checkpoint IO failure"
    ),
) -> t.Callable:
    """Wrap ``fn`` so its first ``failures`` calls raise, then it
    delegates — the transient-IO model for the retry path."""
    state = {"left": failures}

    def wrapper(*args, **kwargs):
        if state["left"] > 0:
            state["left"] -= 1
            raise exc_factory()
        return fn(*args, **kwargs)

    return wrapper


def corrupt_checkpoint(
    directory: str | Path, epoch: int, mode: str = "drop-item"
) -> Path:
    """Damage the on-disk Orbax step for ``epoch`` like a mid-write crash.

    - ``"drop-item"``: remove the ``train_state`` item (an async save
      interrupted before the arrays landed);
    - ``"drop-meta"``: remove the ``meta`` JSON item (interrupted even
      earlier — the step is unreadable at probe time);
    - ``"truncate"``: zero-truncate every array file under
      ``train_state`` (partial flush: the structure exists, the bytes
      do not);
    - ``"nan-params"``: round-trip the step through Orbax with every
      float leaf NaN-poisoned — a *structurally valid* checkpoint whose
      parameters are garbage (corrupted host memory, a diverged run
      checkpointed by a writer without the sentinel). Restores succeed;
      only a finiteness check can catch it — exactly what the serving
      reload sentinel must reject while keeping the last-good
      generation (docs/SERVING.md "Overload & degradation").

    Returns the corrupted step directory.
    """
    step_dir = Path(directory) / str(epoch)
    if not step_dir.is_dir():
        raise FileNotFoundError(f"no checkpoint step dir {step_dir}")
    if mode == "drop-item":
        shutil.rmtree(step_dir / "train_state")
    elif mode == "drop-meta":
        shutil.rmtree(step_dir / "meta")
    elif mode == "truncate":
        for f in (step_dir / "train_state").rglob("*"):
            if f.is_file():
                f.write_bytes(b"")
    elif mode == "nan-params":
        import orbax.checkpoint as ocp

        mgr = ocp.CheckpointManager(Path(directory).absolute())
        try:
            saved_items = set(mgr.item_metadata(epoch).keys())
            args = {
                k: (ocp.args.JsonRestore() if k == "meta"
                    else ocp.args.StandardRestore())
                for k in saved_items
            }
            out = dict(mgr.restore(epoch, args=ocp.args.Composite(**args)))
            out["train_state"] = nan_params(out["train_state"])
            save_args = {
                k: (ocp.args.JsonSave(v) if k == "meta"
                    else ocp.args.StandardSave(v))
                for k, v in out.items()
            }
            mgr.delete(epoch)
            mgr.save(
                epoch, args=ocp.args.Composite(**save_args), force=True
            )
            mgr.wait_until_finished()
        finally:
            mgr.close()
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return step_dir
