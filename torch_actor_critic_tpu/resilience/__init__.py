"""Preemption-safe, self-healing training (docs/RESILIENCE.md).

The subsystem that makes multi-day runs on preemptible TPU slices
survivable without human intervention (the ROADMAP's "as fast as the
hardware allows" presumes the run is still alive to be fast):

- :mod:`~torch_actor_critic_tpu.resilience.sentinel` — divergence
  detection + bounded rollback-to-last-good-checkpoint policy;
- :mod:`~torch_actor_critic_tpu.resilience.preemption` — SIGTERM/
  SIGINT -> emergency save -> distinct requeue exit code;
- :mod:`~torch_actor_critic_tpu.resilience.retry` — bounded
  retry-with-backoff for flaky checkpoint IO;
- :mod:`~torch_actor_critic_tpu.resilience.faultinject` — the harness
  that injects each fault class into a real Trainer so every recovery
  path is *proven* in CI, not hoped for.
"""

from torch_actor_critic_tpu.resilience.preemption import (
    REQUEUE_EXIT_CODE,
    Preempted,
    PreemptionGuard,
)
from torch_actor_critic_tpu.resilience.retry import call_with_retries
from torch_actor_critic_tpu.resilience.sentinel import (
    DivergenceSentinel,
    TrainingDiverged,
    tree_all_finite,
)

__all__ = [
    "REQUEUE_EXIT_CODE",
    "Preempted",
    "PreemptionGuard",
    "DivergenceSentinel",
    "TrainingDiverged",
    "tree_all_finite",
    "call_with_retries",
]
