"""Networked staging transport: actor processes push into the learner.

The process-fleet link of the decoupled plane (docs/RESILIENCE.md
"Decoupled-plane failure modes"): :class:`StagingTransportServer` is a
stdlib HTTP frontend the learner owns, exposing its
:class:`~torch_actor_critic_tpu.decoupled.staging.StagingBuffer` to
actor subprocesses; :class:`RemoteStagingClient` is the actor-side
counterpart that duck-types ``StagingBuffer.put`` so an unmodified
:class:`~torch_actor_critic_tpu.decoupled.actor.ActorWorker` stages
over the wire exactly as it does in-process. Design contract:

- **Bitwise fidelity**: transition arrays travel as base64 raw bytes +
  dtype + shape per leaf — no float->decimal->float round trip — so a
  staged-then-checkpointed tail restores bit-identical whether it was
  produced by the inline actor or a remote process.
- **Strict admission**: a push whose payload is malformed — bad JSON,
  missing fields, wrong dtype/shape, truncated bytes — is rejected
  with **400 before any counter moves**: a poison push cannot corrupt
  the conservation invariant (regression-tested).
- **Idempotent ingestion**: every push carries ``(actor_id,
  incarnation, seq)``; the server keeps a per-actor watermark advanced
  only on *accepted* stagings, so a retried push (response lost in
  flight, learner restarted mid-request) is answered ``duplicate`` and
  never double-staged — the sequence-number audit is exact. A push
  from a superseded incarnation (a SIGKILL-reaped actor's zombie
  request) is answered **410** and never staged.
- **Backpressure over the wire**: the buffer's counted policies map to
  status codes — paused buffer -> **503** + ``Retry-After`` (actors
  idle-spin, PR-10 semantics), shed -> **429** + ``Retry-After``.
- **Bounded retry**: the client retries connection-level failures and
  5xx with jittered exponential backoff (the PR-9 semantics), never
  past its per-push budget — retrying longer than an epoch only feeds
  the staleness gate — and surfaces exhaustion as
  :class:`StagingUnavailable`, which the ActorWorker's idle-spin
  already handles by retrying the SAME transition (same ``seq``, so
  recovery cannot double-ingest).

The server also proxies ``POST /act`` to the learner's serving plane
(so actor subprocesses run a plain HTTP
:class:`~torch_actor_critic_tpu.serve.server.PolicyClient` against one
base URL), accepts ``POST /heartbeat`` for the fleet supervisor's
liveness table, and reports everything on ``GET /metrics``.
"""

from __future__ import annotations

import base64
import binascii
import collections
import json
import logging
import math
import random
import threading
import time
import typing as t
from concurrent.futures import TimeoutError as FutureTimeoutError
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from torch_actor_critic_tpu.core.types import MultiObservation
from torch_actor_critic_tpu.decoupled.staging import (
    StagingBuffer,
    StagingUnavailable,
)
from torch_actor_critic_tpu.serve.admission import (
    SUBMIT_SHED_REASONS,
    ShedError,
)

logger = logging.getLogger(__name__)

__all__ = [
    "RemoteStagingClient",
    "StagingTransportServer",
    "canonical_transition",
    "decode_transition",
    "encode_transition",
]

TRANSITION_FIELDS = ("obs", "actions", "rewards", "next_obs", "done")


# --------------------------------------------------------------- wire codec


def _encode_array(x: np.ndarray) -> dict:
    x = np.ascontiguousarray(x)
    return {
        "dtype": str(x.dtype),
        "shape": list(x.shape),
        "data": base64.b64encode(x.tobytes()).decode("ascii"),
    }


def _decode_array(enc: t.Any, dtype, shape: tuple) -> np.ndarray:
    """Decode one leaf, validating dtype/shape/length against the
    expectation BEFORE touching any buffer state — every mismatch is a
    ``ValueError`` the endpoint maps to a counter-neutral 400."""
    if not isinstance(enc, dict):
        raise ValueError(f"array encoding must be a dict, got {type(enc)}")
    want = np.dtype(dtype)
    if str(enc.get("dtype")) != str(want):
        raise ValueError(
            f"dtype mismatch: got {enc.get('dtype')!r}, expected {want}"
        )
    got_shape = tuple(int(d) for d in enc.get("shape", ()))
    if got_shape != tuple(shape):
        raise ValueError(
            f"shape mismatch: got {got_shape}, expected {tuple(shape)}"
        )
    try:
        raw = base64.b64decode(enc.get("data", ""), validate=True)
    except (binascii.Error, TypeError) as e:
        raise ValueError(f"bad base64 array data: {e}") from None
    expected = int(np.prod(shape, dtype=np.int64)) * want.itemsize
    if len(raw) != expected:
        raise ValueError(
            f"array data is {len(raw)} bytes, expected {expected}"
        )
    # .copy(): frombuffer views the b64 bytes read-only; staging owns a
    # writable array like every locally-produced transition.
    return np.frombuffer(raw, dtype=want).reshape(shape).copy()


def _encode_obs(obs: t.Any) -> t.Any:
    if hasattr(obs, "features"):  # MultiObservation pytree
        return {
            "features": _encode_array(np.asarray(obs.features)),
            "frame": _encode_array(np.asarray(obs.frame)),
        }
    return _encode_array(np.asarray(obs))


def _decode_obs(raw: t.Any, obs_spec, n_envs: int) -> t.Any:
    if isinstance(obs_spec, MultiObservation):
        if not isinstance(raw, dict) or set(raw) != {"features", "frame"}:
            raise ValueError(
                'visual obs must encode {"features": ..., "frame": ...}'
            )
        return MultiObservation(
            features=_decode_array(
                raw["features"], obs_spec.features.dtype,
                (n_envs,) + tuple(obs_spec.features.shape),
            ),
            frame=_decode_array(
                raw["frame"], obs_spec.frame.dtype,
                (n_envs,) + tuple(obs_spec.frame.shape),
            ),
        )
    return _decode_array(
        raw, obs_spec.dtype, (n_envs,) + tuple(obs_spec.shape)
    )


def canonical_transition(transition: tuple, obs_spec) -> tuple:
    """Pin a transition's dtypes to the env spec (obs leaves to the
    spec dtype, everything else float32) — the shared canonical form
    both planes stage, so checkpointed staging arrays restore against
    a shape/dtype-stable abstract tree regardless of producer."""
    import jax

    obs, actions, rewards, next_obs, done = transition

    def cast(x, s):
        return np.asarray(x, dtype=s.dtype)

    return (
        jax.tree_util.tree_map(cast, obs, obs_spec),
        np.asarray(actions, np.float32),
        np.asarray(rewards, np.float32),
        jax.tree_util.tree_map(cast, next_obs, obs_spec),
        np.asarray(done, np.float32),
    )


def encode_transition(transition: tuple) -> dict:
    """Canonical transition tuple -> JSON-ready wire dict (base64 raw
    bytes per leaf; bitwise-exact round trip)."""
    obs, actions, rewards, next_obs, done = transition
    return {
        "obs": _encode_obs(obs),
        "actions": _encode_array(np.asarray(actions)),
        "rewards": _encode_array(np.asarray(rewards)),
        "next_obs": _encode_obs(next_obs),
        "done": _encode_array(np.asarray(done)),
    }


def decode_transition(
    raw: t.Any, obs_spec, n_envs: int, act_dim: int
) -> tuple:
    """Wire dict -> transition tuple, validated leaf-by-leaf against
    the learner's env spec; raises ``ValueError`` on ANY malformation
    (the 400 path — nothing is staged, no counter moves)."""
    if not isinstance(raw, dict):
        raise ValueError(f"transition must be a dict, got {type(raw)}")
    missing = [f for f in TRANSITION_FIELDS if f not in raw]
    if missing:
        raise ValueError(f"transition missing fields {missing}")
    n = int(n_envs)
    return (
        _decode_obs(raw["obs"], obs_spec, n),
        _decode_array(raw["actions"], np.float32, (n, int(act_dim))),
        _decode_array(raw["rewards"], np.float32, (n,)),
        _decode_obs(raw["next_obs"], obs_spec, n),
        _decode_array(raw["done"], np.float32, (n,)),
    )


def _require_int(body: dict, key: str, minimum: int | None = None) -> int:
    v = body.get(key)
    if not isinstance(v, int) or isinstance(v, bool):
        raise ValueError(f'"{key}" must be an integer, got {v!r}')
    if minimum is not None and v < minimum:
        raise ValueError(f'"{key}" must be >= {minimum}, got {v}')
    return v


# ------------------------------------------------------------- server side


class _ActorEntry:
    """Liveness + idempotency state for one fleet actor. Every field is
    guarded by the owning server's ``_lock``; ``lock`` additionally
    serializes this actor's dedup-check -> stage -> watermark-advance
    sequences end-to-end WITHOUT holding the global lock across a
    (possibly blocking) ``staging.put`` — one actor waiting out
    backpressure must not stall every other actor's pushes and
    heartbeats. Ordering: ``lock`` before ``_lock``, never the
    reverse."""

    __slots__ = (
        "lock", "incarnation", "seq", "accepted_total",
        "duplicates_total", "pid", "steps", "last_heartbeat",
        "heartbeats_total",
    )

    def __init__(self, incarnation: int, now: float):
        self.lock = threading.Lock()
        self.incarnation = incarnation
        self.seq = -1  # highest ACCEPTED seq for this incarnation
        self.accepted_total = 0
        self.duplicates_total = 0
        self.pid = 0
        self.steps = 0
        self.last_heartbeat = now
        self.heartbeats_total = 0


class StagingTransportServer:
    """Learner-side HTTP endpoint for the actor-process fleet.

    Routes (all JSON):

    - ``POST /stage`` — push one canonical transition (module
      docstring wire contract). 200 ``{"accepted": true, "duplicate":
      bool}`` / 400 malformed / 410 superseded incarnation / 429 shed
      / 503 paused.
    - ``POST /heartbeat`` — liveness ping ``{actor_id, incarnation,
      pid, steps}`` feeding the supervisor's deadline check.
    - ``POST /act`` — proxy into the learner's serving plane via the
      injected ``act`` callable, same surface as ``PolicyServer /act``
      (actors run a plain HTTP PolicyClient against this one URL).
    - ``GET /healthz``, ``GET /metrics``.

    Dedup check -> staging insert -> watermark advance run under a
    **per-actor lock**, so concurrent retries of the same
    ``(incarnation, seq)`` — a client timing out while its first
    request is still in flight — can never double-stage, while a
    ``block``-backpressure wait stalls only that actor's lane, never
    other actors' pushes or anyone's heartbeats (those take only the
    global ``_lock``). A push whose incarnation was superseded *during*
    its staging wait is swept back out of the buffer (counted
    ``dropped_dead_actor``) and answered 410 — the retire-time purge
    plus this post-put fence together guarantee nothing from a reaped
    actor survives.
    """

    def __init__(
        self,
        staging: StagingBuffer,
        obs_spec,
        n_envs: int,
        act_dim: int,
        act: t.Callable[..., t.Any] | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        request_timeout_s: float = 30.0,
        clock: t.Callable[[], float] = time.monotonic,
    ):
        self.staging = staging
        self.obs_spec = obs_spec
        self.n_envs = int(n_envs)
        self.act_dim = int(act_dim)
        self._act = act
        self._clock = clock
        self.request_timeout_s = float(request_timeout_s)
        self._lock = threading.Lock()
        self._actors: t.Dict[int, _ActorEntry] = {}  # guarded-by: _lock
        # Transport-level outcomes (conservation lives in the staging
        # counters; these account for what never reached the buffer).
        self.pushes_total = 0  # guarded-by: _lock
        self.accepted_total = 0  # guarded-by: _lock
        self.duplicate_pushes_total = 0  # guarded-by: _lock
        self.rejected_malformed_total = 0  # guarded-by: _lock
        self.rejected_zombie_total = 0  # guarded-by: _lock
        self.unavailable_503_total = 0  # guarded-by: _lock
        self.shed_429_total = 0  # guarded-by: _lock
        self.heartbeats_total = 0  # guarded-by: _lock
        self.acts_total = 0  # guarded-by: _lock
        # Trace stitching (PR 19): when a RequestSpanLog is attached
        # (fleet runs with tracing on), every ACCEPTED push records an
        # ingest span carrying its ``a<actor>.<incarnation>.<seq>``
        # span id, and the id queues for the learner to tag onto the
        # drain window that consumes it. Default None — the staging
        # hot path pays one pointer check, the ``telemetry=None``
        # contract.
        self.span_log = None  # RequestSpanLog | None
        self._recent_span_ids: t.Deque[str] = (  # guarded-by: _lock
            collections.deque(maxlen=4096)
        )
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Per-connection socket timeout (same slow-loris rationale
            # as PolicyServer): a stalled actor releases its handler
            # thread instead of pinning it.
            timeout = server.request_timeout_s

            def log_message(self, fmt, *args):  # noqa: A003
                logger.debug("transport http: " + fmt, *args)

            def _send(
                self,
                code: int,
                payload: dict,
                headers: dict | None = None,
            ):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path == "/healthz":
                    paused = server.staging.paused
                    # Health, not just liveness (PR 19): the probe
                    # carries the conservation invariant and depth so
                    # one GET distinguishes "up" from "healthy" — the
                    # ObsCollector scrapes this and the SLO engine
                    # alarms on conservation_ok going false.
                    self._send(200, {
                        "status": "paused" if paused else "ok",
                        "staging_depth": server.staging.depth(),
                        "conservation_ok": (
                            server.staging.conservation_holds()
                        ),
                        "actors": len(server.liveness()),
                    })
                elif self.path == "/metrics":
                    self._send(200, {
                        "transport": server.snapshot(),
                        "staging": server.staging.snapshot(),
                    })
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802 — stdlib API
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(length) if length else b"{}"
                    body = json.loads(raw or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, json.JSONDecodeError) as e:
                    if self.path == "/stage":
                        server._note_malformed()
                    self._send(400, {"error": f"bad JSON body: {e}"})
                    return
                if self.path == "/stage":
                    code, payload, headers = server.handle_stage(body)
                    self._send(code, payload, headers=headers)
                elif self.path == "/heartbeat":
                    code, payload = server.handle_heartbeat(body)
                    self._send(code, payload)
                elif self.path == "/act":
                    self._proxy_act(body)
                else:
                    self._send(404, {"error": f"no route {self.path}"})

            def _proxy_act(self, body: dict):
                if server._act is None:
                    self._send(404, {
                        "error": "this transport has no serving proxy",
                    })
                    return
                if "obs" not in body:
                    self._send(400, {"error": 'missing "obs"'})
                    return
                from torch_actor_critic_tpu.serve.server import _parse_obs

                try:
                    obs = _parse_obs(body["obs"], server.obs_spec)
                    res = server._act(
                        obs, bool(body.get("deterministic", False))
                    )
                except ShedError as e:
                    code = (
                        429 if e.reason in SUBMIT_SHED_REASONS else 503
                    )
                    self._send(
                        code, e.to_payload(),
                        headers={"Retry-After": str(
                            max(1, math.ceil(e.retry_after_s))
                        )},
                    )
                    return
                except FutureTimeoutError:
                    self._send(
                        503,
                        {"error": "policy backend timed out; retry"},
                        headers={"Retry-After": "1"},
                    )
                    return
                except (ValueError, TypeError) as e:
                    self._send(400, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 — engine fault
                    logger.exception("transport /act proxy failed")
                    self._send(500, {"error": repr(e)[:500]})
                    return
                server._note_act()
                self._send(200, {
                    "action": np.asarray(res.action).tolist(),
                    "generation": int(res.generation),
                    "epoch": res.epoch,
                })

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None  # guarded-by: _lock

    # --------------------------------------------------------- endpoints

    def _note_malformed(self) -> None:
        with self._lock:
            self.pushes_total += 1
            self.rejected_malformed_total += 1

    def _note_act(self) -> None:
        with self._lock:
            self.acts_total += 1

    def handle_stage(
        self, body: dict
    ) -> t.Tuple[int, dict, dict | None]:
        """Validate -> dedup -> stage -> advance watermark; returns
        ``(status, payload, headers)``. Exposed for direct unit tests —
        the HTTP handler is a thin shim over this."""
        span_log = self.span_log
        t_ingest = time.perf_counter() if span_log is not None else 0.0
        try:
            actor_id = _require_int(body, "actor_id", minimum=0)
            incarnation = _require_int(body, "incarnation", minimum=0)
            seq = _require_int(body, "seq", minimum=0)
            generation = _require_int(body, "generation")
            epoch = body.get("epoch")
            if epoch is not None and (
                not isinstance(epoch, int) or isinstance(epoch, bool)
            ):
                raise ValueError(f'"epoch" must be an int or null, got '
                                 f'{epoch!r}')
            transition = decode_transition(
                body.get("transition"), self.obs_spec,
                self.n_envs, self.act_dim,
            )
        except ValueError as e:
            # The poison-push contract: reject BEFORE any buffer or
            # watermark state moves — conservation counters untouched.
            self._note_malformed()
            return 400, {"error": str(e)}, None
        with self._lock:
            entry = self._actors.get(actor_id)
            if entry is None:
                entry = self._actors[actor_id] = _ActorEntry(
                    incarnation, self._clock()
                )
        with entry.lock:
            with self._lock:
                self.pushes_total += 1
                if incarnation < entry.incarnation:
                    # A SIGKILL-reaped actor's zombie request: its
                    # staged tail was purged; nothing from it may land
                    # again.
                    self.rejected_zombie_total += 1
                    return 410, {
                        "error": "incarnation superseded",
                        "incarnation": entry.incarnation,
                    }, None
                if incarnation > entry.incarnation:
                    entry.incarnation = incarnation
                    entry.seq = -1
                if seq <= entry.seq:
                    # Retried push whose original was ACCEPTED
                    # (response lost in flight): answer success, stage
                    # nothing.
                    self.duplicate_pushes_total += 1
                    entry.duplicates_total += 1
                    return 200, {
                        "accepted": True, "duplicate": True,
                    }, None
            try:
                # Outside _lock: a block-policy wait stalls only this
                # actor's lane (entry.lock), never heartbeats or other
                # actors. Same-actor retries still serialize here.
                accepted = self.staging.put(
                    transition, generation=generation, epoch=epoch,
                    actor_id=actor_id,
                )
            except StagingUnavailable:
                with self._lock:
                    self.unavailable_503_total += 1
                return 503, {
                    "error": "staging paused (learner checkpointing "
                             "or draining); retry",
                    "reason": "staging_paused",
                }, {"Retry-After": "1"}
            if not accepted:
                with self._lock:
                    self.shed_429_total += 1
                return 429, {
                    "error": "staging backpressure shed",
                    "reason": "staging_shed",
                }, {"Retry-After": "1"}
            landed = False
            with self._lock:
                if entry.incarnation != incarnation:
                    # Superseded mid-put: retire_actor's purge ran
                    # before this landed. Sweep it back out (counted
                    # dropped_dead_actor — conservation intact) and
                    # fence the zombie.
                    self.rejected_zombie_total += 1
                    superseded = entry.incarnation
                else:
                    entry.seq = seq
                    entry.accepted_total += 1
                    self.accepted_total += 1
                    landed = True
                    if span_log is not None:
                        self._recent_span_ids.append(
                            f"a{actor_id}.{incarnation}.{seq}"
                        )
            if landed:
                if span_log is not None:
                    span_log.record({
                        "name": "stage_ingest",
                        "t0": t_ingest,
                        "t1": time.perf_counter(),
                        "span_id": f"a{actor_id}.{incarnation}.{seq}",
                        "actor_id": actor_id,
                        "incarnation": incarnation,
                        "seq": seq,
                        "outcome": "accepted",
                    })
                return 200, {
                    "accepted": True, "duplicate": False,
                }, None
            # Still under entry.lock: the successor incarnation's
            # pushes are queued behind this lane, so the sweep can only
            # catch the zombie's own transition, never theirs.
            self.staging.purge_actor(actor_id)
            return 410, {
                "error": "incarnation superseded",
                "incarnation": superseded,
            }, None

    def handle_heartbeat(self, body: dict) -> t.Tuple[int, dict]:
        try:
            actor_id = _require_int(body, "actor_id", minimum=0)
            incarnation = _require_int(body, "incarnation", minimum=0)
        except ValueError as e:
            return 400, {"error": str(e)}
        with self._lock:
            entry = self._actors.get(actor_id)
            if entry is None:
                entry = self._actors[actor_id] = _ActorEntry(
                    incarnation, self._clock()
                )
            if incarnation < entry.incarnation:
                self.rejected_zombie_total += 1
                return 410, {
                    "error": "incarnation superseded",
                    "incarnation": entry.incarnation,
                }
            if incarnation > entry.incarnation:
                entry.incarnation = incarnation
                entry.seq = -1
            entry.last_heartbeat = self._clock()
            entry.pid = int(body.get("pid", 0))
            entry.steps = int(body.get("steps", 0))
            entry.heartbeats_total += 1
            self.heartbeats_total += 1
            return 200, {"ok": True}

    # -------------------------------------------------- supervisor bridge

    def liveness(self) -> t.Dict[int, dict]:
        """Per-actor liveness view for the fleet supervisor's deadline
        check: heartbeat age (via the injected clock), incarnation,
        pid, reported steps."""
        now = self._clock()
        with self._lock:
            return {
                aid: {
                    "age_s": now - e.last_heartbeat,
                    "incarnation": e.incarnation,
                    "pid": e.pid,
                    "steps": e.steps,
                }
                for aid, e in self._actors.items()
            }

    def retire_actor(self, actor_id: int, incarnation: int) -> int:
        """Supersede a dead actor's incarnation, then purge its staged
        tail; returns the purge count. The watermark bump happens
        FIRST (under ``_lock``, serialized with every in-flight stage)
        so a zombie request racing the purge is 410-rejected instead
        of re-staging after the purge swept."""
        with self._lock:
            entry = self._actors.get(actor_id)
            if entry is None:
                # The actor died before ever making contact (e.g. the
                # spawn-grace deadline): fence it anyway so a late
                # first push from the reaped process cannot land.
                entry = _ActorEntry(incarnation, self._clock())
                self._actors[actor_id] = entry
            if entry.incarnation <= incarnation:
                entry.incarnation = incarnation + 1
                entry.seq = -1
        return self.staging.purge_actor(actor_id)

    # ------------------------------------------------- checkpoint bridge

    def watermarks(self) -> dict:
        """JSON-ready per-actor idempotency state for the checkpoint:
        a resumed learner restores these so a push retried across its
        restart is still deduplicated (keys stringified for JSON)."""
        with self._lock:
            return {
                str(aid): {
                    "incarnation": e.incarnation,
                    "seq": e.seq,
                    "accepted_total": e.accepted_total,
                    "duplicates_total": e.duplicates_total,
                }
                for aid, e in self._actors.items()
            }

    def load_watermarks(self, marks: t.Mapping[str, t.Any]) -> None:
        now = self._clock()
        with self._lock:
            for aid, m in (marks or {}).items():
                entry = _ActorEntry(int(m.get("incarnation", 0)), now)
                entry.seq = int(m.get("seq", -1))
                entry.accepted_total = int(m.get("accepted_total", 0))
                entry.duplicates_total = int(m.get("duplicates_total", 0))
                self._actors[int(aid)] = entry

    # ----------------------------------------------------- introspection

    def take_recent_span_ids(self) -> t.List[str]:
        """Drain the span ids of pushes accepted since the last call —
        the learner tags them onto the drain-window span that consumed
        them (trace stitching). Empty unless a span_log is attached."""
        with self._lock:
            ids = list(self._recent_span_ids)
            self._recent_span_ids.clear()
        return ids

    def snapshot(self) -> dict:
        now = self._clock()
        with self._lock:
            return {
                "pushes_total": self.pushes_total,
                "accepted_total": self.accepted_total,
                "duplicate_pushes_total": self.duplicate_pushes_total,
                "rejected_malformed_total": self.rejected_malformed_total,
                "rejected_zombie_total": self.rejected_zombie_total,
                "unavailable_503_total": self.unavailable_503_total,
                "shed_429_total": self.shed_429_total,
                "heartbeats_total": self.heartbeats_total,
                "acts_total": self.acts_total,
                "actors": {
                    str(aid): {
                        "incarnation": e.incarnation,
                        "seq": e.seq,
                        "accepted_total": e.accepted_total,
                        "duplicates_total": e.duplicates_total,
                        "pid": e.pid,
                        "steps": e.steps,
                        "heartbeat_age_s": now - e.last_heartbeat,
                        "heartbeats_total": e.heartbeats_total,
                    }
                    for aid, e in self._actors.items()
                },
            }

    # ---------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "StagingTransportServer":
        thread = threading.Thread(
            target=self._httpd.serve_forever, name="staging-transport",
            daemon=True,
        )
        with self._lock:
            self._thread = thread
        thread.start()
        return self

    def close(self, thread_join_timeout_s: float = 10.0) -> None:
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        if thread is not None:
            thread.join(timeout=thread_join_timeout_s)
            if thread.is_alive():  # pragma: no cover — wedged handler
                logger.warning(
                    "transport thread still alive after %.1fs join; "
                    "leaking it", thread_join_timeout_s,
                )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -------------------------------------------------------------- actor side


class RemoteStagingClient:
    """Actor-process staging handle: ``put`` pushes one transition to
    the learner's :class:`StagingTransportServer`, with the module
    docstring's retry/idempotency contract. Duck-types
    ``StagingBuffer.put`` so :class:`ActorWorker.stage` drives it
    unmodified; a paused/unreachable learner surfaces as
    :class:`StagingUnavailable` and the worker's existing idle-spin
    retries the SAME transition (same ``seq`` — dedup makes the retry
    safe even when the first attempt was accepted and only the
    response was lost).

    ``post`` is the transport seam: a callable ``(path, payload,
    timeout_s) -> (status, payload_dict)`` raising ``OSError`` on
    connection-level failure. The default is a stdlib urllib POST;
    :class:`~torch_actor_critic_tpu.resilience.faultinject.
    FlakyTransport` wraps it to inject drops/latency underneath the
    retry loop.
    """

    def __init__(
        self,
        url: str,
        actor_id: int,
        incarnation: int = 0,
        retry_budget_s: float = 2.0,
        request_timeout_s: float = 5.0,
        backoff_s: float = 0.05,
        sleep: t.Callable[[float], None] = time.sleep,
        rng: random.Random | None = None,
        post: t.Callable[..., t.Tuple[int, dict]] | None = None,
        start_seq: int = 0,
    ):
        if retry_budget_s <= 0:
            raise ValueError(
                f"retry_budget_s must be > 0, got {retry_budget_s}"
            )
        self.url = url.rstrip("/")
        self.actor_id = int(actor_id)
        self.incarnation = int(incarnation)
        self.retry_budget_s = float(retry_budget_s)
        self.request_timeout_s = float(request_timeout_s)
        self.backoff_s = float(backoff_s)
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self._post = post if post is not None else self._http_post
        self._next_seq = int(start_seq)
        # Trace stitching (PR 19): when set, a callable fed one record
        # per ACCEPTED push — the actor loop points it at a JsonlSink
        # under the run dir so the learner's trace export can stitch
        # this process's ``stage_push`` spans (same
        # ``a<actor>.<incarnation>.<seq>`` id the transport stamps on
        # its ingest span) into the one run timeline. Default None:
        # the push hot path pays one pointer check.
        self.span_sink: t.Callable[[dict], None] | None = None
        # Counted outcomes (client side of the sequence audit).
        self.pushes_total = 0
        self.accepted_total = 0
        self.duplicates_total = 0
        self.shed_total = 0
        self.retries_total = 0
        self.unavailable_total = 0
        self.heartbeat_failures_total = 0

    def _http_post(
        self, path: str, payload: dict, timeout_s: float
    ) -> t.Tuple[int, dict]:
        import urllib.error as urlerr
        import urllib.request as urlreq

        req = urlreq.Request(
            self.url + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urlreq.urlopen(req, timeout=timeout_s) as resp:
                return resp.status, json.loads(resp.read())
        except urlerr.HTTPError as e:
            try:
                body = json.loads(e.read())
            except (ValueError, OSError):
                body = {}
            return e.code, body

    # -------------------------------------------------------------- push

    def put(
        self,
        transition: tuple,
        generation: int = 0,
        epoch: int | None = None,
        timeout_s: float | None = None,
        actor_id: int = -1,
    ) -> bool:
        """Push one tagged transition; True = accepted (or already
        accepted — a deduplicated retry), False = shed by the server's
        backpressure policy. Raises :class:`StagingUnavailable` when
        the learner is paused/unreachable past the retry budget — the
        caller keeps the transition and calls again (same ``seq``).
        ``actor_id`` is accepted for ``StagingBuffer.put`` duck-parity
        and ignored: this client IS one actor."""
        del actor_id  # the constructor's actor identity is authoritative
        seq = self._next_seq
        payload = {
            "actor_id": self.actor_id,
            "incarnation": self.incarnation,
            "seq": seq,
            "generation": int(generation),
            "epoch": int(epoch) if epoch is not None else None,
            "transition": encode_transition(transition),
        }
        budget = float(
            timeout_s if timeout_s is not None else self.retry_budget_s
        )
        deadline = time.monotonic() + budget
        attempt = 0
        self.pushes_total += 1
        t_push = (
            time.perf_counter() if self.span_sink is not None else 0.0
        )
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.unavailable_total += 1
                raise StagingUnavailable(
                    f"push retry budget of {budget:.2f}s exhausted "
                    f"(seq {seq}); retry the same transition"
                )
            try:
                status, out = self._post(
                    "/stage", payload,
                    min(self.request_timeout_s, remaining),
                )
            except (OSError, FutureTimeoutError, TimeoutError) as e:
                # Connection-level failure: the push may or may not
                # have landed — retry the SAME seq (dedup absorbs the
                # ambiguity) within the budget.
                retry_after = 0.0
                err: t.Any = e
            else:
                if status == 200:
                    self._next_seq = seq + 1
                    if out.get("duplicate"):
                        self.duplicates_total += 1
                    else:
                        self.accepted_total += 1
                    if self.span_sink is not None:
                        self._record_push_span(t_push, seq, out)
                    return True
                if status == 429:
                    # Counted server-side shed; the transition is gone
                    # by policy, not by accident — move on.
                    self._next_seq = seq + 1
                    self.shed_total += 1
                    return False
                if status == 503:
                    # Paused buffer / learner draining: idle-spin land.
                    self.unavailable_total += 1
                    raise StagingUnavailable(
                        out.get("error", "staging paused; retry")
                    )
                if status == 410:
                    raise RuntimeError(
                        "this actor incarnation was superseded by the "
                        "supervisor; exiting is the only correct move"
                    )
                if status < 500:
                    # 4xx: a malformed push is a BUG — surface it.
                    raise ValueError(
                        f"stage push rejected with HTTP {status}: "
                        f"{out.get('error', '')}"
                    )
                retry_after = 1.0
                err = f"HTTP {status}: {out.get('error', '')}"
            delay = max(retry_after, self.backoff_s * (2 ** attempt))
            delay *= 1.0 + 0.25 * self._rng.random()  # jitter
            if time.monotonic() + delay >= deadline:
                self.unavailable_total += 1
                raise StagingUnavailable(
                    f"staging push failing ({err}) and the "
                    f"{budget:.2f}s retry budget is exhausted; retry "
                    "the same transition"
                )
            self.retries_total += 1
            attempt += 1
            self._sleep(delay)

    def _record_push_span(self, t_push: float, seq: int, out: dict):
        """One accepted push -> one span record, with ABSOLUTE
        microsecond timestamps (this process anchors its own wall
        clock) so the learner-side trace merge needs no alien perf
        anchor. Sink failures must not break staging."""
        import os

        from torch_actor_critic_tpu.telemetry.traceview import perf_to_us

        try:
            self.span_sink({
                "name": "stage_push",
                "ts_us": perf_to_us(t_push),
                "dur_us": (time.perf_counter() - t_push) * 1e6,
                "span_id": (
                    f"a{self.actor_id}.{self.incarnation}.{seq}"
                ),
                "actor_id": self.actor_id,
                "incarnation": self.incarnation,
                "seq": seq,
                "outcome": (
                    "duplicate" if out.get("duplicate") else "accepted"
                ),
                "os_pid": os.getpid(),
            })
        except Exception:  # noqa: BLE001 - tracing must never fail a push
            logger.debug("push span record failed", exc_info=True)

    # --------------------------------------------------------- heartbeat

    def heartbeat(self, pid: int, steps: int) -> bool:
        """One liveness ping; False on delivery failure (counted, never
        raised — heartbeat LOSS is precisely the signal the supervisor
        acts on, so the actor must not die of it). A 410 means this
        incarnation was superseded and is re-raised as RuntimeError."""
        try:
            status, _ = self._post(
                "/heartbeat",
                {
                    "actor_id": self.actor_id,
                    "incarnation": self.incarnation,
                    "pid": int(pid),
                    "steps": int(steps),
                },
                self.request_timeout_s,
            )
        except (OSError, FutureTimeoutError, TimeoutError):
            self.heartbeat_failures_total += 1
            return False
        if status == 410:
            raise RuntimeError(
                "heartbeat rejected: this actor incarnation was "
                "superseded by the supervisor"
            )
        if status != 200:
            self.heartbeat_failures_total += 1
            return False
        return True

    def stats(self) -> dict:
        return {
            "actor_id": self.actor_id,
            "incarnation": self.incarnation,
            "next_seq": self._next_seq,
            "pushes_total": self.pushes_total,
            "accepted_total": self.accepted_total,
            "duplicates_total": self.duplicates_total,
            "shed_total": self.shed_total,
            "retries_total": self.retries_total,
            "unavailable_total": self.unavailable_total,
            "heartbeat_failures_total": self.heartbeat_failures_total,
        }
