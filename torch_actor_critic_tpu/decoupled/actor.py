"""Actor-side of the decoupled plane: act through serving, degrade, re-home.

:class:`ActorWorker` owns the actor↔serving link (docs/RESILIENCE.md
"Decoupled-plane failure modes"): action selection goes through a
:class:`~torch_actor_critic_tpu.serve.server.PolicyClient` (in-process
against a co-located registry, or HTTP against a worker / the fleet
router — the client's retry/backoff is transport-agnostic), and every
response's ``(generation, epoch)`` stamps the transitions it produces.

On serving unavailability — breaker open, drain, timeout, connection
loss, or a lossy link — the worker **degrades instead of stalling
envs**: the client's own bounded, deadline-aware retry runs first;
when that fails, acting falls back to a **last-known local param
snapshot** (the callable the learner hands it), whose transitions are
staleness-stamped with the snapshot's publish epoch so the staging
gate — not luck — bounds how much degraded data enters training. While
degraded, the serving plane is re-probed every ``probe_every`` acting
steps (cheap: one bounded call) and the worker **re-homes** on the
first success. Every state change is counted
(``degradations_total``/``fallback_actions_total``/``rehomes_total``).

:meth:`run` is the standalone loop for remote/threaded actors: step a
pool, stage tagged transitions, and — when the staging buffer is
paused because the learner is checkpointing or restarting —
**idle-spin with bounded backoff and reconnect**, retrying the SAME
transition so a learner restart loses nothing actor-side.
"""

from __future__ import annotations

import logging
import time
import typing as t
from concurrent.futures import TimeoutError as FutureTimeoutError

import numpy as np

from torch_actor_critic_tpu.decoupled.staging import (
    StagingBuffer,
    StagingUnavailable,
)
from torch_actor_critic_tpu.serve.admission import ShedError

logger = logging.getLogger(__name__)

__all__ = ["ActorWorker"]

# Serving-unavailability classes the degradation path absorbs: sheds
# (breaker/drain/queue/deadline taxonomy), connection-level failures
# (OSError covers urllib's URLError and injected lossy links), backend
# timeouts, and engine faults surfaced as RuntimeError (the HTTP 5xx
# analogue). Request-shape errors (ValueError/TypeError) propagate —
# falling back would hide a real bug.
_DEGRADABLE = (
    ShedError, OSError, FutureTimeoutError, TimeoutError, RuntimeError,
)


class ActorWorker:
    """One host actor: envs in, tagged transitions out, via serving.

    ``fallback(obs, deterministic) -> (actions, generation, epoch)`` is
    the local-snapshot acting path (the learner supplies one built on
    its own param mirror, stamped with the last published generation/
    epoch); ``fallback=None`` makes serving failures fatal (a pure
    remote actor with no weights of its own).
    """

    def __init__(
        self,
        client,
        staging: StagingBuffer,
        fallback: t.Callable[..., tuple] | None = None,
        slot: str = "default",
        act_timeout_s: float = 5.0,
        probe_every: int = 8,
        idle_backoff_s: float = 0.05,
        max_idle_backoff_s: float = 1.0,
        sleep: t.Callable[[float], None] = time.sleep,
    ):
        if probe_every < 1:
            raise ValueError(f"probe_every must be >= 1, got {probe_every}")
        self.client = client
        self.staging = staging
        self.fallback = fallback
        self.slot = slot
        self.act_timeout_s = float(act_timeout_s)
        self.probe_every = int(probe_every)
        self.idle_backoff_s = float(idle_backoff_s)
        self.max_idle_backoff_s = float(max_idle_backoff_s)
        self._sleep = sleep
        self.degraded = False
        self.last_error: str | None = None
        self._since_probe = 0
        # Counted link-state outcomes.
        self.serving_actions_total = 0
        self.fallback_actions_total = 0
        self.degradations_total = 0
        self.rehomes_total = 0
        self.probes_total = 0
        self.idle_spins_total = 0

    # ------------------------------------------------------------- acting

    def act(
        self, obs: t.Any, deterministic: bool = False
    ) -> t.Tuple[np.ndarray, int, int | None, str]:
        """Select actions for a batched observation; returns
        ``(actions, generation, epoch, source)`` where ``source`` is
        ``"serving"`` or ``"fallback"``. Never stalls the env loop on a
        dead serving plane: while degraded only every ``probe_every``-th
        call pays a (bounded) serving attempt."""
        if self.degraded:
            self._since_probe += 1
            if self._since_probe < self.probe_every:
                return self._act_fallback(obs, deterministic)
            self._since_probe = 0
            self.probes_total += 1
        try:
            res = self.client.act(
                obs, deterministic=deterministic, slot=self.slot,
                timeout=self.act_timeout_s,
            )
        except _DEGRADABLE as e:
            self.last_error = f"{type(e).__name__}: {e}"
            if self.fallback is None:
                raise
            if not self.degraded:
                self.degraded = True
                self.degradations_total += 1
                self._since_probe = 0
                logger.warning(
                    "serving plane unavailable (%s); degrading to the "
                    "local param snapshot (probing every %d steps)",
                    self.last_error, self.probe_every,
                )
            return self._act_fallback(obs, deterministic)
        if self.degraded:
            self.degraded = False
            self.rehomes_total += 1
            logger.info(
                "serving plane recovered after %d fallback actions; "
                "re-homed", self.fallback_actions_total,
            )
        self.serving_actions_total += 1
        return (
            np.asarray(res.action), int(res.generation), res.epoch,
            "serving",
        )

    def _act_fallback(self, obs, deterministic):
        self.fallback_actions_total += 1
        actions, generation, epoch = self.fallback(obs, deterministic)
        return np.asarray(actions), int(generation), epoch, "fallback"

    # ------------------------------------------------------------ staging

    def stage(
        self, transition: tuple, generation: int, epoch: int | None,
        stop: t.Optional[t.Any] = None,
    ) -> bool:
        """Put one tagged transition, idle-spinning with bounded
        backoff while the staging buffer is paused (learner away).
        Returns False only when ``stop`` was set before the buffer
        reopened — the transition is then abandoned by shutdown, not
        lost to a restart."""
        backoff = self.idle_backoff_s
        while stop is None or not stop.is_set():
            try:
                self.staging.put(
                    transition, generation=generation, epoch=epoch
                )
                return True
            except StagingUnavailable:
                self.idle_spins_total += 1
                self._sleep(backoff)
                backoff = min(backoff * 2, self.max_idle_backoff_s)
        return False

    # ----------------------------------------------------- standalone loop

    def run(
        self,
        pool,
        stop,
        seeds: t.Sequence[int],
        max_steps: int | None = None,
        sample_until: int = 0,
    ) -> int:
        """Standalone collection loop (remote/threaded actors): step
        the pool, stage tagged transitions, reset finished episodes.
        ``stop`` is a ``threading.Event``; ``seeds`` seed the pool's
        envs; the first ``sample_until`` steps act randomly (warmup).
        Returns the number of lockstep steps taken. The trainer-driven
        path does NOT use this — the :class:`~torch_actor_critic_tpu.
        decoupled.learner.DecoupledTrainer` drives acting inline
        through :meth:`act`/:meth:`stage` so its loop keeps the
        hardened epoch machinery."""
        import jax

        obs = pool.reset_all(list(seeds))
        steps = 0
        while not stop.is_set() and (
            max_steps is None or steps < max_steps
        ):
            if steps < sample_until:
                actions, gen, epoch = pool.sample_actions(), 0, None
            else:
                actions, gen, epoch, _ = self.act(obs)
            next_obs, rewards, terms, truncs = pool.step(actions)
            terms = np.asarray(terms, bool)
            truncs = np.asarray(truncs, bool)
            transition = (
                obs,
                np.asarray(actions),
                np.asarray(rewards, np.float32),
                jax.tree_util.tree_map(np.array, next_obs),
                terms.astype(np.float32),
            )
            if not self.stage(transition, gen, epoch, stop=stop):
                break
            ended = terms | truncs
            for i in map(int, np.flatnonzero(ended)):
                jax.tree_util.tree_map(
                    lambda dst, src: dst.__setitem__(i, src),
                    next_obs, pool.reset_at(i),
                )
            obs = next_obs
            steps += 1
        return steps

    # ------------------------------------------------------ introspection

    def stats(self) -> dict:
        return {
            "degraded": self.degraded,
            "last_error": self.last_error,
            "serving_actions_total": self.serving_actions_total,
            "fallback_actions_total": self.fallback_actions_total,
            "degradations_total": self.degradations_total,
            "rehomes_total": self.rehomes_total,
            "probes_total": self.probes_total,
            "idle_spins_total": self.idle_spins_total,
        }

    def load_stats(self, stats: t.Mapping[str, t.Any]) -> None:
        """Restore the counted link-state totals from a checkpoint (the
        degraded flag itself is live state — a resumed learner's actor
        re-probes from scratch)."""
        for key in (
            "serving_actions_total", "fallback_actions_total",
            "degradations_total", "rehomes_total", "probes_total",
            "idle_spins_total",
        ):
            if key in stats:
                setattr(self, key, int(stats[key]))
