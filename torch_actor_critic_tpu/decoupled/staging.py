"""Bounded, thread-safe transition staging between actors and learner.

The decoupled plane's middle link (docs/RESILIENCE.md "Decoupled-plane
failure modes"): actors :meth:`StagingBuffer.put` batched transitions
tagged with the policy **generation** and published **epoch** that
produced them; the learner :meth:`StagingBuffer.pop_window`-drains
fixed-size windows into the existing replay/update path. Every way a
transition can leave the buffer is an explicit, counted policy — never
an accident:

- **Backpressure** (``policy``) when the buffer is full at ``put``:

  * ``"block"`` — the actor waits (bounded by ``block_timeout_s``) for
    the learner to drain; a timed-out wait sheds the transition.
    Counted ``blocked_total`` / ``shed_total``.
  * ``"drop_oldest"`` — evict the oldest staged transition to admit
    the new one (freshest-data-wins). Counted
    ``dropped_backpressure_total``.
  * ``"shed"`` — refuse the new transition (``put`` returns False).
    Counted ``shed_total``.

- **Bounded-staleness admission gate** (``max_lag``): at drain time,
  any staged transition whose published epoch is more than ``max_lag``
  epochs behind the learner's current epoch is dropped and counted
  (``dropped_stale_total``) — off-policy drift is a knob
  (``--max-actor-lag``), not an accident. Transitions with no epoch
  tag (random warmup actions, pre-first-publish) carry zero lag.

- **Pause/resume**: the learner (or its preemption path) ``pause()``-s
  the buffer; ``put`` then raises :class:`StagingUnavailable` and a
  remote/threaded actor idle-spins until ``resume()`` reopens it —
  actors survive a learner restart without losing their own envs.

- **Dead-actor purge** (``purge_actor``): when the fleet supervisor
  declares an actor process dead (missed heartbeat deadline,
  ``decoupled/fleet.py``), its not-yet-drained transitions are removed
  and counted (``dropped_dead_actor_total``) — a dead actor's tail is
  an explicit accounting entry, never silent residue trained on after
  its producer was SIGKILL-reaped. Transitions carry the producing
  ``actor_id`` (``-1`` = the learner's own inline actor).

Per-transition **generation-lag accounting** rides the shared
:class:`~torch_actor_critic_tpu.telemetry.histogram.
FixedBucketHistogram` schema (``actor_lag`` on metrics.jsonl, epoch
telemetry events and ``/metrics``), so staleness is observable with
the same estimator as every other histogram in the system.

Conservation invariant (the "zero transitions lost" proof the chaos
smoke asserts — now spanning process boundaries)::

    staged_total == drained_total + dropped_stale_total
                    + dropped_backpressure_total
                    + dropped_dead_actor_total + depth()

Everything here is deterministic and injectable (no hidden clocks): the
only wait is the ``block`` policy's bounded condition wait.
"""

from __future__ import annotations

import collections
import threading
import typing as t

import numpy as np

from torch_actor_critic_tpu.telemetry.histogram import FixedBucketHistogram

__all__ = ["StagedTransition", "StagingBuffer", "StagingUnavailable"]

# Lag histogram bucket spec: lags are small integers; lo=1 puts lag 0
# in the (exact-min) underflow bucket and growth=2 gives exact bounds
# at 1, 2, 4, ... — merges across checkpoints require this spec.
_LAG_HIST_SPEC = dict(lo=1.0, hi=4096.0, growth=2.0)

BACKPRESSURE_POLICIES = ("block", "drop_oldest", "shed")


class StagingUnavailable(RuntimeError):
    """The buffer is paused/closed (learner restarting or shutting
    down): actors should idle-spin with backoff and retry the SAME
    transition — nothing is lost to a learner restart."""


class StagedTransition(t.NamedTuple):
    """One staged lockstep step: the batched transition tuple
    ``(obs, actions, rewards, next_obs, done)`` (leading axis = envs)
    plus the policy provenance tags and the producing actor
    (``actor_id=-1`` = the learner's inline actor)."""

    transition: tuple
    generation: int
    epoch: int | None
    actor_id: int = -1


class StagingBuffer:
    def __init__(
        self,
        capacity: int,
        policy: str = "block",
        max_lag: int | None = None,
        block_timeout_s: float = 1.0,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if policy not in BACKPRESSURE_POLICIES:
            raise ValueError(
                f"policy must be one of {BACKPRESSURE_POLICIES}, got "
                f"{policy!r}"
            )
        if max_lag is not None and max_lag < 0:
            raise ValueError(f"max_lag must be >= 0, got {max_lag}")
        self.capacity = int(capacity)
        self.policy = policy
        self.max_lag = max_lag
        self.block_timeout_s = float(block_timeout_s)
        self._q: collections.deque[StagedTransition] = (  # guarded-by: _cond
            collections.deque()
        )
        self._cond = threading.Condition()
        self._closed = False  # guarded-by: _cond
        # Counted outcomes (the conservation invariant; module docstring).
        self.staged_total = 0  # guarded-by: _cond
        self.drained_total = 0  # guarded-by: _cond
        self.dropped_stale_total = 0  # guarded-by: _cond
        self.dropped_backpressure_total = 0  # guarded-by: _cond
        self.dropped_dead_actor_total = 0  # guarded-by: _cond
        self.shed_total = 0  # guarded-by: _cond
        self.blocked_total = 0  # guarded-by: _cond
        self.lag_hist = FixedBucketHistogram(  # guarded-by: _cond
            **_LAG_HIST_SPEC
        )

    # ------------------------------------------------------------ actors

    def put(
        self,
        transition: tuple,
        generation: int = 0,
        epoch: int | None = None,
        timeout_s: float | None = None,
        actor_id: int = -1,
    ) -> bool:
        """Stage one tagged transition; returns True when accepted.

        A full buffer applies the configured backpressure policy (see
        module docstring). A paused buffer raises
        :class:`StagingUnavailable` — the actor keeps the transition
        and retries after the learner reopens."""
        with self._cond:
            if self._closed:
                raise StagingUnavailable(
                    "staging buffer is paused (learner away); retry "
                    "after resume()"
                )
            if len(self._q) >= self.capacity:
                if self.policy == "shed":
                    self.shed_total += 1
                    return False
                if self.policy == "drop_oldest":
                    self._q.popleft()
                    self.dropped_backpressure_total += 1
                else:  # block (bounded)
                    self.blocked_total += 1
                    budget = float(
                        timeout_s if timeout_s is not None
                        else self.block_timeout_s
                    )
                    import time as _time

                    t_end = _time.monotonic() + budget
                    while (
                        len(self._q) >= self.capacity and not self._closed
                    ):
                        remaining = t_end - _time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                    if self._closed:
                        raise StagingUnavailable(
                            "staging buffer paused while blocked on "
                            "backpressure; retry after resume()"
                        )
                    if len(self._q) >= self.capacity:
                        # Bounded block: a wait that never drained is a
                        # shed, loudly counted — never a deadlock.
                        self.shed_total += 1
                        return False
            self._q.append(
                StagedTransition(
                    transition, int(generation),
                    int(epoch) if epoch is not None else None,
                    int(actor_id),
                )
            )
            self.staged_total += 1
            self._cond.notify_all()
            return True

    # ----------------------------------------------------------- learner

    @staticmethod
    def _lag(entry: StagedTransition, current_epoch: int | None) -> int:
        if entry.epoch is None or current_epoch is None:
            return 0
        return max(0, int(current_epoch) - int(entry.epoch))

    def pop_window(
        self, k: int, current_epoch: int | None = None
    ) -> t.List[StagedTransition] | None:
        """Drain exactly ``k`` admitted transitions (oldest first), or
        ``None`` when fewer are available — windows are fixed-size so
        the learner's chunk shapes (and jit cache) never vary.

        The bounded-staleness gate runs first: staged transitions whose
        lag against ``current_epoch`` exceeds ``max_lag`` are dropped
        and counted. Each drained transition's lag is recorded in the
        ``actor_lag`` histogram — by construction every recorded lag is
        ``<= max_lag``."""
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        with self._cond:
            if self.max_lag is not None and current_epoch is not None:
                kept = [
                    e for e in self._q
                    if self._lag(e, current_epoch) <= self.max_lag
                ]
                n_dropped = len(self._q) - len(kept)
                if n_dropped:
                    self.dropped_stale_total += n_dropped
                    self._q = collections.deque(kept)
            if len(self._q) < k:
                return None
            out = [self._q.popleft() for _ in range(k)]
            for e in out:
                self.lag_hist.record(float(self._lag(e, current_epoch)))
            self.drained_total += len(out)
            self._cond.notify_all()
            return out

    def purge_actor(self, actor_id: int) -> int:
        """Drop every staged transition produced by ``actor_id``
        (counted ``dropped_dead_actor_total``); returns how many were
        purged. The fleet supervisor calls this when it declares an
        actor process dead — the orphaned tail leaves the buffer as an
        explicit conservation entry, not as training data from a
        producer that no longer exists."""
        with self._cond:
            kept = [e for e in self._q if e.actor_id != int(actor_id)]
            n_purged = len(self._q) - len(kept)
            if n_purged:
                self.dropped_dead_actor_total += n_purged
                self._q = collections.deque(kept)
                self._cond.notify_all()
            return n_purged

    # ------------------------------------------------------ pause/resume

    def pause(self) -> None:
        """Stop admitting (learner checkpointing/restarting): actors
        get :class:`StagingUnavailable` and idle-spin; staged
        transitions stay put for the checkpoint."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def resume(self) -> None:
        with self._cond:
            self._closed = False
            self._cond.notify_all()

    @property
    def paused(self) -> bool:
        with self._cond:
            return self._closed

    # ----------------------------------------------------- introspection

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def snapshot(self) -> dict:
        """Counters + the lag histogram in ``/metrics`` form — merged
        into serving ``/metrics`` via ``extra_snapshot`` and streamed
        as per-epoch ``decoupled`` telemetry events."""
        with self._cond:
            return {
                "depth": len(self._q),
                "capacity": self.capacity,
                "policy": self.policy,
                "max_lag": self.max_lag,
                "staged_total": self.staged_total,
                "drained_total": self.drained_total,
                "dropped_stale_total": self.dropped_stale_total,
                "dropped_backpressure_total":
                    self.dropped_backpressure_total,
                "dropped_dead_actor_total": self.dropped_dead_actor_total,
                "shed_total": self.shed_total,
                "blocked_total": self.blocked_total,
                "actor_lag": self.lag_hist.snapshot(
                    prefix="actor_lag_", unit=""
                ),
            }

    def conservation_holds(self) -> bool:
        """The zero-loss invariant (module docstring) — every accepted
        transition is accounted for."""
        with self._cond:
            return self.staged_total == (
                self.drained_total
                + self.dropped_stale_total
                + self.dropped_backpressure_total
                + self.dropped_dead_actor_total
                + len(self._q)
            )

    # ------------------------------------------------- checkpoint bridge

    def meta_state(self) -> dict:
        """JSON-ready counters + lag histogram + queue length, saved in
        checkpoint metadata (the queue CONTENTS ride the checkpoint's
        ``arrays`` item via :meth:`export_arrays`)."""
        with self._cond:
            return {
                "count": len(self._q),
                "staged_total": self.staged_total,
                "drained_total": self.drained_total,
                "dropped_stale_total": self.dropped_stale_total,
                "dropped_backpressure_total":
                    self.dropped_backpressure_total,
                "dropped_dead_actor_total": self.dropped_dead_actor_total,
                "shed_total": self.shed_total,
                "blocked_total": self.blocked_total,
                "lag_hist": self.lag_hist.raw_counts(),
            }

    def load_meta(self, meta: t.Mapping[str, t.Any]) -> None:
        with self._cond:
            self.staged_total = int(meta.get("staged_total", 0))
            self.drained_total = int(meta.get("drained_total", 0))
            self.dropped_stale_total = int(
                meta.get("dropped_stale_total", 0)
            )
            self.dropped_backpressure_total = int(
                meta.get("dropped_backpressure_total", 0)
            )
            self.dropped_dead_actor_total = int(
                meta.get("dropped_dead_actor_total", 0)
            )
            self.shed_total = int(meta.get("shed_total", 0))
            self.blocked_total = int(meta.get("blocked_total", 0))
            self.lag_hist = FixedBucketHistogram(**_LAG_HIST_SPEC)
            if meta.get("lag_hist"):
                self.lag_hist.merge_raw(meta["lag_hist"])

    _ARRAY_FIELDS = ("obs", "actions", "rewards", "next_obs", "done")

    def export_arrays(self) -> dict | None:
        """The queued transitions as one stacked array pytree (leading
        axis = queue position) for the checkpoint ``arrays`` item, or
        ``None`` when empty. Epoch ``None`` serializes as ``-1``."""
        with self._cond:
            if not self._q:
                return None
            entries = list(self._q)
        import jax

        out: dict = {}
        for i, field in enumerate(self._ARRAY_FIELDS):
            out[field] = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs, axis=0),
                *[e.transition[i] for e in entries],
            )
        out["generation"] = np.asarray(
            [e.generation for e in entries], np.int64
        )
        out["epoch"] = np.asarray(
            [-1 if e.epoch is None else e.epoch for e in entries], np.int64
        )
        out["actor_id"] = np.asarray(
            [e.actor_id for e in entries], np.int64
        )
        return out

    def import_arrays(self, arrays: t.Mapping[str, t.Any]) -> int:
        """Rebuild the queue (in order) from :meth:`export_arrays`
        output; returns the number of transitions restored. Replaces
        any current contents — the restore path owns the queue."""
        import jax

        generations = np.asarray(arrays["generation"])
        epochs = np.asarray(arrays["epoch"])
        count = int(generations.shape[0])
        # Pre-fleet checkpoints carry no actor_id item: everything
        # staged then was the learner's inline actor (-1).
        actor_ids = (
            np.asarray(arrays["actor_id"]) if "actor_id" in arrays
            else np.full((count,), -1, np.int64)
        )
        entries = []
        for i in range(count):
            txn = tuple(
                jax.tree_util.tree_map(
                    lambda x, i=i: np.asarray(x)[i], arrays[field]
                )
                for field in self._ARRAY_FIELDS
            )
            ep = int(epochs[i])
            entries.append(
                StagedTransition(txn, int(generations[i]),
                                 None if ep < 0 else ep,
                                 int(actor_ids[i]))
            )
        with self._cond:
            self._q = collections.deque(entries)
            self._cond.notify_all()
        return count
