"""Decoupled actor/learner plane (ROADMAP item 5, docs/RESILIENCE.md
"Decoupled-plane failure modes"): actors act through the serving
plane, transitions flow through a bounded staging buffer with a
staleness admission gate, and the learner publishes epochs via the
validated hot-reload — every link fault-injected and recovery-proven
(``make decouple-smoke``). ``--actors N`` scales the actor side to a
supervised process fleet over a networked staging transport
(``fleet.py`` / ``transport.py``): heartbeat liveness, SIGKILL-reap +
jittered-backoff restarts, and idempotent per-actor sequence-numbered
ingestion, with the conservation invariant extended across process
boundaries."""

from torch_actor_critic_tpu.decoupled.actor import ActorWorker
from torch_actor_critic_tpu.decoupled.fleet import (
    FleetSupervisor,
    FleetTrainer,
    actor_main,
)
from torch_actor_critic_tpu.decoupled.learner import DecoupledTrainer
from torch_actor_critic_tpu.decoupled.staging import (
    StagedTransition,
    StagingBuffer,
    StagingUnavailable,
)
from torch_actor_critic_tpu.decoupled.transport import (
    RemoteStagingClient,
    StagingTransportServer,
)

__all__ = [
    "ActorWorker",
    "DecoupledTrainer",
    "FleetSupervisor",
    "FleetTrainer",
    "RemoteStagingClient",
    "StagedTransition",
    "StagingBuffer",
    "StagingTransportServer",
    "StagingUnavailable",
    "actor_main",
]
