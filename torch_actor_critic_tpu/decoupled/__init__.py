"""Decoupled actor/learner plane (ROADMAP item 5, docs/RESILIENCE.md
"Decoupled-plane failure modes"): actors act through the serving
plane, transitions flow through a bounded staging buffer with a
staleness admission gate, and the learner publishes epochs via the
validated hot-reload — every link fault-injected and recovery-proven
(``make decouple-smoke``)."""

from torch_actor_critic_tpu.decoupled.actor import ActorWorker
from torch_actor_critic_tpu.decoupled.learner import DecoupledTrainer
from torch_actor_critic_tpu.decoupled.staging import (
    StagedTransition,
    StagingBuffer,
    StagingUnavailable,
)

__all__ = [
    "ActorWorker",
    "DecoupledTrainer",
    "StagedTransition",
    "StagingBuffer",
    "StagingUnavailable",
]
