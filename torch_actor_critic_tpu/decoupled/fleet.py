"""Supervised actor-process fleet: ``train.py --actors N``.

Scales the PR-10 decoupled actor/learner from threads to *processes*
the way ``serve.py --fleet N`` scaled serving (ROADMAP item 1,
Sebulba arXiv:2104.06272 / TorchBeast arXiv:1910.03552). Three pieces:

- :func:`actor_main` — the subprocess entry point: its own env pool,
  acting over HTTP through the learner's serving proxy
  (:class:`~torch_actor_critic_tpu.serve.server.PolicyClient` against
  the transport's ``/act``), staging over the wire through a
  :class:`~torch_actor_critic_tpu.decoupled.transport.
  RemoteStagingClient`, a heartbeat thread feeding the supervisor's
  liveness table, SIGTERM -> graceful stop. When the learner is away
  the actor **degrades to local acting** (uniform-random actions — a
  fleet actor owns no weights — stamped untagged like warmup, so the
  staleness gate treats them as PR-10 degraded data) and re-homes on
  the first successful probe.
- :class:`FleetSupervisor` — liveness-gated supervision: an actor
  that misses its heartbeat deadline (or whose process died) is
  **declared dead**, SIGKILL-reaped, its staged tail purged
  (``dropped_dead_actor_total`` — the conservation invariant's new
  term), and **restarted with jittered exponential backoff** up to
  ``--actor-max-restarts``, counted as ``decoupled/actor_restarts``.
  Every restart is a new *incarnation*: the transport's watermark bump
  happens before the purge, so a zombie push from the reaped process
  can never land after its tail was swept.
- :class:`FleetTrainer` — a :class:`DecoupledTrainer` that owns the
  transport server and the supervisor. The learner's own inline actor
  keeps collecting (``actor_id=-1``); fleet transitions are additional
  feed into the SAME bounded staging buffer, under the same counted
  backpressure, staleness gate, and the extended invariant checked
  every epoch::

      staged == drained + dropped_stale + dropped_backpressure
                + dropped_dead_actor + depth

  Checkpoints additionally carry the transport's per-actor dedup
  watermarks, so a push retried across a learner SIGTERM->resume
  (requeue 75) is still deduplicated — zero accepted transitions lost
  AND zero double-ingested, sequence-number audit exact
  (``make decouple-smoke`` phase 3, tests/test_actor_fleet.py).
"""

from __future__ import annotations

import logging
import os
import random
import signal
import threading
import time
import typing as t

from torch_actor_critic_tpu.decoupled.learner import DecoupledTrainer
from torch_actor_critic_tpu.decoupled.transport import (
    RemoteStagingClient,
    StagingTransportServer,
    canonical_transition,
)

logger = logging.getLogger(__name__)

__all__ = ["FleetSupervisor", "FleetTrainer", "actor_main"]

# Fault-injection hook (resilience/faultinject.py FlakyTransport): a
# spawned actor whose environment carries TAC_FLAKY_PUSH wraps its
# staging POST with scheduled drops/latency — the chaos smoke's
# transport flap, injected under the retry loop like a real bad NIC.
FLAKY_PUSH_ENV = "TAC_FLAKY_PUSH"


def _maybe_flaky_post(client: RemoteStagingClient, actor_id: int):
    spec = os.environ.get(FLAKY_PUSH_ENV, "")
    if not spec:
        return
    from torch_actor_critic_tpu.resilience.faultinject import FlakyTransport

    opts = dict(
        kv.split("=", 1) for kv in spec.split(",") if "=" in kv
    )
    client._post = FlakyTransport(
        client._post,
        drop_rate=float(opts.get("drop_rate", 0.0)),
        latency_s=float(opts.get("latency_s", 0.0)),
        rng=random.Random(int(opts.get("seed", 0)) + actor_id),
    )
    logger.info(
        "actor %d: flaky push transport injected (%s)", actor_id, spec
    )


def _actor_loop(
    actor_id: int,
    incarnation: int,
    url: str,
    env_name: str,
    n_envs: int,
    base_seed: int,
    stop: threading.Event,
    options: t.Mapping[str, t.Any] | None = None,
) -> dict:
    """The actor's collection loop, factored out of the process shim so
    tests can drive it on a thread against a real transport server.
    Returns the worker/client stats for the caller's audit."""
    from torch_actor_critic_tpu.decoupled.actor import ActorWorker
    from torch_actor_critic_tpu.envs.vec_env import make_env_pool
    from torch_actor_critic_tpu.serve.server import PolicyClient

    opts = dict(options or {})
    staging = RemoteStagingClient(
        url,
        actor_id=actor_id,
        incarnation=incarnation,
        retry_budget_s=float(opts.get("push_retry_s", 2.0)),
        rng=random.Random(base_seed),
    )
    trace_dir = opts.get("trace_dir")
    span_sink = None
    if trace_dir:
        # Trace stitching: one spans file per (actor, incarnation),
        # absolute-µs records the learner's trace export merges onto
        # this actor's own timeline lane (obs/tracecollect.py).
        from torch_actor_critic_tpu.telemetry.sinks import JsonlSink

        span_sink = JsonlSink(os.path.join(
            str(trace_dir),
            f"actor{actor_id}-{incarnation}.spans.jsonl",
        ))
        staging.span_sink = span_sink.write
    _maybe_flaky_post(staging, actor_id)
    client = PolicyClient(url=url, retries=1, backoff_s=0.05)
    pool = make_env_pool(env_name, n_envs, base_seed=base_seed)
    worker = ActorWorker(
        client,
        staging,
        # A fleet actor owns no weights: degraded acting is uniform
        # env-space sampling, untagged (generation 0, epoch None) like
        # warmup — lag 0 through the admission gate, honestly counted
        # in fallback_actions_total.
        fallback=lambda obs, deterministic: (
            pool.sample_actions(), 0, None
        ),
        act_timeout_s=float(opts.get("act_timeout_s", 5.0)),
        probe_every=int(opts.get("probe_every", 8)),
    )
    hb_interval = float(opts.get("heartbeat_interval_s", 0.5))

    def hb_loop():
        while not stop.is_set():
            try:
                staging.heartbeat(
                    os.getpid(),
                    worker.serving_actions_total
                    + worker.fallback_actions_total,
                )
            except RuntimeError:
                # Superseded incarnation: the supervisor already
                # replaced this actor — stop producing.
                logger.warning(
                    "actor %d inc %d superseded; stopping",
                    actor_id, incarnation,
                )
                stop.set()
                break
            stop.wait(hb_interval)

    hb = threading.Thread(
        target=hb_loop, name=f"actor{actor_id}-heartbeat", daemon=True
    )
    hb.start()
    try:
        steps = worker.run(
            pool, stop,
            seeds=[base_seed + i for i in range(n_envs)],
            max_steps=opts.get("max_steps"),
            sample_until=int(opts.get("sample_until", 0)),
        )
    finally:
        stop.set()
        hb.join(timeout=5.0)
        if span_sink is not None:
            span_sink.close()
        close = getattr(pool, "close", None)
        if close is not None:
            close()
    return {
        "steps": steps,
        "worker": worker.stats(),
        "staging": staging.stats(),
    }


def actor_main(
    actor_id: int,
    incarnation: int,
    url: str,
    env_name: str,
    n_envs: int,
    base_seed: int,
    options: dict | None = None,
) -> None:
    """Subprocess entry point (multiprocessing ``spawn`` target):
    installs SIGTERM/SIGINT -> graceful stop, runs :func:`_actor_loop`,
    exits 0 on a clean roll-down. Crashes propagate as a nonzero exit
    the supervisor observes and restarts."""
    logging.basicConfig(
        level=logging.INFO,
        format=f"[actor {actor_id}.{incarnation}] %(message)s",
    )
    # Cold-start machinery (aot/cache.py): a learner running with
    # --compile-cache publishes the dir via TAC_COMPILE_CACHE, which
    # this spawn-child inherited — so a RESPAWNED actor (incarnation
    # > 0) finds its acting programs already compiled on disk instead
    # of re-paying the compile inside its restart window.
    from torch_actor_critic_tpu.aot.cache import enable_cache_from_env

    enable_cache_from_env()
    stop = threading.Event()

    def _stop_handler(signum, frame):  # pragma: no cover — signal path
        # is exercised end-to-end by the chaos smoke
        del frame
        logger.info("actor %d: signal %d, rolling down", actor_id, signum)
        stop.set()

    signal.signal(signal.SIGTERM, _stop_handler)
    signal.signal(signal.SIGINT, _stop_handler)
    stats = _actor_loop(
        actor_id, incarnation, url, env_name, n_envs, base_seed,
        stop, options,
    )
    logger.info(
        "actor %d inc %d done: %d steps, %d accepted, %d duplicates, "
        "%d shed",
        actor_id, incarnation, stats["steps"],
        stats["staging"]["accepted_total"],
        stats["staging"]["duplicates_total"],
        stats["staging"]["shed_total"],
    )


class FleetSupervisor:
    """Liveness-gated actor supervision with bounded, jittered restarts.

    ``spawn(actor_id, incarnation) -> proc`` returns a started process
    handle (``pid`` / ``is_alive()`` / ``join(timeout)``); ``liveness()
    -> {actor_id: {"age_s", "incarnation", ...}}`` is the transport's
    heartbeat table; ``on_death(actor_id, incarnation) -> purged`` runs
    after the kill+join (the transport retire: watermark bump + staged-
    tail purge). ``clock``/``sleeper``/``kill`` are injectable so the
    deadline/backoff machinery is provable with fake processes and a
    fake clock (tests/test_actor_fleet.py).

    Death verdicts per poll: a process that is no longer alive, or a
    live one whose newest heartbeat **for the current incarnation** is
    older than ``heartbeat_timeout_s``, is declared dead, SIGKILLed
    (idempotent for already-dead), joined, retired, and — up to
    ``max_restarts`` per slot — respawned as incarnation+1 after a
    jittered exponential backoff. A slot past its budget is abandoned
    loudly (``gave_up``). Fresh spawns get ``grace_s`` to first
    heartbeat (process start + imports are not a liveness failure).
    """

    def __init__(
        self,
        spawn: t.Callable[[int, int], t.Any],
        n_actors: int,
        liveness: t.Callable[[], t.Dict[int, dict]],
        on_death: t.Callable[[int, int], int],
        heartbeat_timeout_s: float = 3.0,
        max_restarts: int = 3,
        backoff_s: float = 0.5,
        max_backoff_s: float = 8.0,
        poll_interval_s: float = 0.25,
        grace_s: float = 60.0,
        clock: t.Callable[[], float] = time.monotonic,
        kill: t.Callable[[int, int], None] = os.kill,
        rng: random.Random | None = None,
    ):
        if n_actors < 1:
            raise ValueError(f"n_actors must be >= 1, got {n_actors}")
        self._spawn = spawn
        self.n_actors = int(n_actors)
        self._liveness = liveness
        self._on_death = on_death
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.poll_interval_s = float(poll_interval_s)
        self.grace_s = float(grace_s)
        self._clock = clock
        self._kill = kill
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()
        self._procs: t.Dict[int, t.Any] = {}  # guarded-by: _lock
        self._incarnation: t.Dict[int, int] = {}  # guarded-by: _lock
        self._spawned_at: t.Dict[int, float] = {}  # guarded-by: _lock
        self._restarts: t.Dict[int, int] = {}  # guarded-by: _lock
        self._respawn_at: t.Dict[int, float] = {}  # guarded-by: _lock
        self._gave_up: t.Set[int] = set()  # guarded-by: _lock
        self.restarts_total = 0  # guarded-by: _lock
        self.deaths_total = 0  # guarded-by: _lock
        self.purged_on_death_total = 0  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None  # guarded-by: _lock

    # ---------------------------------------------------------- lifecycle

    def start(
        self, start_incarnations: t.Mapping[int, int] | None = None
    ) -> "FleetSupervisor":
        """Spawn the full fleet and begin supervising on a daemon
        thread. ``start_incarnations`` seeds per-slot incarnation
        numbers ABOVE any checkpoint-restored transport watermark, so
        respawned-after-resume actors are not mistaken for zombies."""
        base = dict(start_incarnations or {})
        now = self._clock()
        with self._lock:
            for aid in range(self.n_actors):
                inc = int(base.get(aid, 0))
                self._incarnation[aid] = inc
                self._restarts[aid] = 0
                proc = self._spawn(aid, inc)
                self._procs[aid] = proc
                self._spawned_at[aid] = now
                logger.info(
                    "spawned actor %d (incarnation %d, pid %s)",
                    aid, inc, getattr(proc, "pid", "?"),
                )
        self._stop.clear()
        thread = threading.Thread(
            target=self._monitor_loop, name="fleet-supervisor",
            daemon=True,
        )
        with self._lock:
            self._thread = thread
        thread.start()
        return self

    def _monitor_loop(self):
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_interval_s)

    def poll_once(self) -> None:
        """One supervision pass (the monitor thread's body; tests call
        it directly with an injected clock)."""
        live = self._liveness()
        now = self._clock()
        with self._lock:
            for aid in range(self.n_actors):
                if aid in self._gave_up:
                    continue
                if aid in self._respawn_at:
                    self._respawn_due_locked(aid, now)
                    continue
                proc = self._procs.get(aid)
                if proc is None:
                    continue
                inc = self._incarnation[aid]
                if not proc.is_alive():
                    self._declare_dead_locked(
                        aid, now, reason="process exited "
                        f"(exitcode {getattr(proc, 'exitcode', '?')})",
                    )
                    continue
                info = live.get(aid)
                if info is not None and info["incarnation"] == inc:
                    if info["age_s"] > self.heartbeat_timeout_s:
                        self._declare_dead_locked(
                            aid, now,
                            reason=f"heartbeat {info['age_s']:.2f}s "
                            "past deadline",
                        )
                elif now - self._spawned_at[aid] > max(
                    self.grace_s, self.heartbeat_timeout_s
                ):
                    self._declare_dead_locked(
                        aid, now, reason="no heartbeat since spawn",
                    )

    def _declare_dead_locked(
        self, aid: int, now: float, reason: str
    ) -> None:
        """Kill/reap/retire one actor and schedule (or refuse) its
        restart. Callers hold ``self._lock``."""
        proc = self._procs.pop(aid)
        inc = self._incarnation[aid]
        self.deaths_total += 1
        logger.warning(
            "actor %d (incarnation %d, pid %s) declared DEAD: %s",
            aid, inc, getattr(proc, "pid", "?"), reason,
        )
        try:
            self._kill(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass  # already reaped — SIGKILL is idempotent here
        proc.join(timeout=10.0)
        # Retire AFTER the join: the process is provably gone, so the
        # purge sweeps everything it will ever have staged (any zombie
        # request still in a handler is 410-fenced by the watermark
        # bump inside on_death).
        self.purged_on_death_total += self._on_death(aid, inc)
        if self._restarts[aid] >= self.max_restarts:
            self._gave_up.add(aid)
            logger.error(
                "actor %d exhausted its %d-restart budget; abandoning "
                "the slot (the fleet keeps training on the survivors)",
                aid, self.max_restarts,
            )
            return
        delay = min(
            self.backoff_s * (2 ** self._restarts[aid]),
            self.max_backoff_s,
        ) * (1.0 + 0.5 * self._rng.random())  # jitter
        self._respawn_at[aid] = now + delay
        logger.info(
            "actor %d restart %d/%d scheduled in %.2fs",
            aid, self._restarts[aid] + 1, self.max_restarts, delay,
        )

    def _respawn_due_locked(self, aid: int, now: float) -> None:
        """Respawn a scheduled slot once its backoff expired. Callers
        hold ``self._lock``."""
        if now < self._respawn_at[aid]:
            return
        del self._respawn_at[aid]
        inc = self._incarnation[aid] + 1
        self._incarnation[aid] = inc
        self._restarts[aid] += 1
        self.restarts_total += 1
        proc = self._spawn(aid, inc)
        self._procs[aid] = proc
        self._spawned_at[aid] = now
        logger.info(
            "respawned actor %d as incarnation %d (pid %s, restart %d)",
            aid, inc, getattr(proc, "pid", "?"), self._restarts[aid],
        )

    def readmit(self, aid: int) -> bool:
        """Elastic re-admission of an abandoned slot (docs/RESILIENCE.md
        "Elasticity"): clear the exhausted restart budget and respawn
        the slot at the next incarnation — the watermark fence still
        holds because the incarnation strictly increases past every
        retired one. Called at epoch boundaries by the
        TrainingElasticManager; returns False for a slot that never
        gave up (nothing to re-admit)."""
        with self._lock:
            if aid not in self._gave_up:
                return False
            self._gave_up.discard(aid)
            self._restarts[aid] = 0
            self._respawn_at.pop(aid, None)
            inc = self._incarnation.get(aid, 0) + 1
            self._incarnation[aid] = inc
            proc = self._spawn(aid, inc)
            self._procs[aid] = proc
            self._spawned_at[aid] = self._clock()
        logger.info(
            "re-admitted actor %d as incarnation %d (pid %s); restart "
            "budget reset", aid, inc, getattr(proc, "pid", "?"),
        )
        return True

    def shutdown(self, term_timeout_s: float = 10.0) -> None:
        """Roll the fleet down: stop supervising, SIGTERM every live
        actor (graceful stop -> flush), join, SIGKILL stragglers."""
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=term_timeout_s)
        with self._lock:
            procs = list(self._procs.items())
        for aid, proc in procs:
            if not proc.is_alive():
                continue
            try:
                self._kill(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, OSError):
                continue
        for aid, proc in procs:
            proc.join(timeout=term_timeout_s)
            if proc.is_alive():
                logger.warning(
                    "actor %d ignored SIGTERM for %.1fs; SIGKILL",
                    aid, term_timeout_s,
                )
                try:
                    self._kill(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, OSError):
                    pass
                proc.join(timeout=5.0)

    # ----------------------------------------------------- introspection

    def stats(self) -> dict:
        with self._lock:
            return {
                "restarts_total": self.restarts_total,
                "deaths_total": self.deaths_total,
                "purged_on_death_total": self.purged_on_death_total,
                "gave_up": sorted(self._gave_up),
                "alive": sum(
                    1 for p in self._procs.values() if p.is_alive()
                ),
                "actors": {
                    aid: {
                        "incarnation": self._incarnation.get(aid, 0),
                        "restarts": self._restarts.get(aid, 0),
                        "pid": getattr(
                            self._procs.get(aid), "pid", None
                        ),
                        "alive": (
                            aid in self._procs
                            and self._procs[aid].is_alive()
                        ),
                    }
                    for aid in range(self.n_actors)
                },
            }

    def load_stats(self, stats: t.Mapping[str, t.Any]) -> None:
        """Restore the monotone counters from a checkpoint so
        ``decoupled/actor_restarts`` keeps counting across a learner
        resume instead of resetting to zero."""
        with self._lock:
            self.restarts_total = int(stats.get("restarts_total", 0))
            self.deaths_total = int(stats.get("deaths_total", 0))
            self.purged_on_death_total = int(
                stats.get("purged_on_death_total", 0)
            )


class FleetTrainer(DecoupledTrainer):
    """DecoupledTrainer + a supervised actor-process fleet.

    The learner keeps its hardened inline collection loop
    (``actor_id=-1``); ``config.actors`` subprocesses feed the same
    staging buffer over the networked transport. Checkpoints grow the
    transport watermarks and supervisor counters; saves pause the
    buffer so the exported tail + watermark state is one consistent
    cut (in-flight pushes get 503 and retry the same seq — accepted
    exactly once, before or after the cut, never both).
    """

    def __init__(self, *args, spawn=None, **kwargs):
        super().__init__(*args, **kwargs)
        cfg = self.config
        self.transport = StagingTransportServer(
            staging=self.staging,
            obs_spec=self.pool.obs_spec,
            n_envs=self.n_envs,
            act_dim=self.pool.act_dim,
            act=self._serve_act,
            port=cfg.fleet_port,
        ).start()
        self._spawn_override = spawn
        self.supervisor = FleetSupervisor(
            spawn=self._spawn_actor,
            n_actors=cfg.actors,
            liveness=self.transport.liveness,
            on_death=self.transport.retire_actor,
            heartbeat_timeout_s=cfg.heartbeat_timeout_s,
            max_restarts=cfg.actor_max_restarts,
        )
        self._restored_incarnations: t.Dict[int, int] = {}
        self._fleet_started = False
        # Elastic degrade/re-admit (docs/RESILIENCE.md "Elasticity").
        # Off (the default) constructs nothing: no decision log, no
        # elastic/ metric keys — the key-pin contract matches the
        # obs-off one.
        self.elastic = None
        if cfg.elastic == "on":
            from torch_actor_critic_tpu.elastic import (
                DecisionLog,
                TrainingElasticManager,
            )

            self.elastic = TrainingElasticManager(
                supervisor=self.supervisor,
                n_actors=cfg.actors,
                log=DecisionLog(telemetry=self.telemetry),
                readmit_epochs=cfg.elastic_readmit_epochs,
            )
        # Run-wide obs plane: the collector (built in Trainer.__init__,
        # started at train() entry) scrapes the transport's /metrics +
        # /healthz — staging conservation and per-actor liveness land
        # in the aggregated series as the ``fleet`` source.
        if self.obs is not None:
            from torch_actor_critic_tpu.obs import http_source

            self.obs.add_source(
                "fleet",
                http_source(
                    self.transport.address, ("/metrics", "/healthz")
                ),
            )
        # Trace stitching: with telemetry on, the transport records
        # ingest spans + queues accepted span ids, the learner tags
        # drain windows with the ids they consumed, and actor
        # subprocesses append their push spans under the run dir —
        # merged into one timeline by extra_trace_events().
        self._stage_spans = None
        self._trace_dir = None
        if self.telemetry is not None:
            from torch_actor_critic_tpu.telemetry.traceview import (
                RequestSpanLog,
            )

            self.transport.span_log = RequestSpanLog(4096)
            self._stage_spans = RequestSpanLog(2048)
            tracker = self.tracker
            if tracker is not None and getattr(tracker, "run_dir", None):
                self._trace_dir = os.path.join(
                    str(tracker.run_dir), "stage_spans"
                )
        logger.info(
            "actor fleet: %d actors, transport at %s, heartbeat "
            "%.2fs/%.2fs, max restarts %d",
            cfg.actors, self.transport.address,
            cfg.heartbeat_interval_s, cfg.heartbeat_timeout_s,
            cfg.actor_max_restarts,
        )

    # ------------------------------------------------------------- fleet

    def _serve_act(self, obs, deterministic):
        """The transport's /act proxy: actor subprocesses act through
        the learner's own serving plane (registry + micro-batcher —
        the exact stack the inline actor uses)."""
        return self.client.act(
            obs, deterministic=deterministic,
            timeout=self.config.actor_timeout_s,
        )

    def _spawn_actor(self, actor_id: int, incarnation: int):
        if self._spawn_override is not None:
            return self._spawn_override(actor_id, incarnation)
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        proc = ctx.Process(
            target=actor_main,
            args=(
                actor_id,
                incarnation,
                self.transport.address,
                self.env_name,
                self.n_envs,
                # Disjoint from the learner's env seeds (seed + 10000k)
                # and stable per (actor, incarnation) so restarts are
                # reproducible.
                self.seed + 20000 + 1000 * actor_id + incarnation,
            ),
            kwargs={"options": {
                "heartbeat_interval_s": self.config.heartbeat_interval_s,
                "act_timeout_s": self.config.actor_timeout_s,
                "push_retry_s": self.config.actor_push_retry_s,
                "trace_dir": self._trace_dir,
            }},
            daemon=True,
        )
        proc.start()
        return proc

    def train(self, render: bool = False) -> dict:
        if not self._fleet_started:
            self._fleet_started = True
            self.supervisor.start(
                start_incarnations=self._restored_incarnations
            )
        return super().train(render)

    # ----------------------------------------------------- trace stitching

    def _drain_window(self, staging):
        """Tag each drain window with the span ids of the fleet pushes
        accepted since the last one — the learner-side end of the
        actor-push -> transport-ingest -> drain stitch. No span log
        attached = exactly the parent's behavior."""
        if self._stage_spans is None:
            return super()._drain_window(staging)
        t0 = time.perf_counter()
        chunk = super()._drain_window(staging)
        if chunk is not None:
            span_ids = self.transport.take_recent_span_ids()
            self._stage_spans.record({
                "name": "drain_window",
                "t0": t0,
                "t1": time.perf_counter(),
                "span_ids": span_ids,
                "entries": self.config.update_every,
            })
        return chunk

    def extra_trace_events(self) -> t.List[dict]:
        """Staging-plane spans for the merged run timeline: transport
        ingest spans, learner drain windows, and every actor process's
        push-span file."""
        from torch_actor_critic_tpu.obs.tracecollect import actor_span_events
        from torch_actor_critic_tpu.telemetry.traceview import (
            TRAIN_PID,
            TRANSPORT_PID,
            staging_span_events,
        )

        events = list(super().extra_trace_events())
        if self.transport.span_log is not None:
            events.extend(staging_span_events(
                self.transport.span_log.records(), pid=TRANSPORT_PID
            ))
        if self._stage_spans is not None:
            events.extend(staging_span_events(
                self._stage_spans.records(), pid=TRAIN_PID
            ))
        if self._trace_dir is not None:
            events.extend(actor_span_events(self._trace_dir))
        if self.elastic is not None:
            from torch_actor_critic_tpu.telemetry.traceview import (
                elastic_decision_events,
            )

            events.extend(elastic_decision_events(
                self.elastic.log.records()
            ))
        return events

    # --------------------------------------------------------- checkpoint

    def _save_checkpoint(self, epoch: int, step: int, wait: bool = False):
        # One consistent cut across counters, queue contents and dedup
        # watermarks: pause admissions (in-flight pushes 503-retry the
        # same seq) for the synchronous slice of the save.
        was_paused = self.staging.paused
        if not was_paused:
            self.staging.pause()
        try:
            return super()._save_checkpoint(epoch, step, wait=wait)
        finally:
            if not was_paused:
                self.staging.resume()

    def _checkpoint_extra(self, step: int) -> dict:
        extra = super()._checkpoint_extra(step)
        extra["decoupled"]["transport_watermarks"] = (
            self.transport.watermarks()
        )
        extra["decoupled"]["fleet"] = self.supervisor.stats()
        if self.elastic is not None:
            # Degraded topology rides the checkpoint: a learner that
            # saved with slots degraded resumes degraded and re-admits
            # on its own epoch schedule.
            extra["decoupled"]["elastic"] = self.elastic.snapshot()
        return extra

    def _restore_extras(self, meta: dict, arrays) -> None:
        super()._restore_extras(meta, arrays)
        dec = meta.get("decoupled") or {}
        marks = dec.get("transport_watermarks") or {}
        self.transport.load_watermarks(marks)
        # Respawned actors must start ABOVE every restored watermark
        # incarnation — otherwise the zombie fence rejects them.
        self._restored_incarnations = {
            int(aid): int(m.get("incarnation", 0)) + 1
            for aid, m in marks.items()
        }
        self.supervisor.load_stats(dec.get("fleet") or {})
        if self.elastic is not None:
            self.elastic.restore(dec.get("elastic"))
        if marks:
            logger.info(
                "restored transport watermarks for %d actors; "
                "respawns start at incarnations %s",
                len(marks), self._restored_incarnations,
            )

    # ------------------------------------------------------ epoch metrics

    def _epoch_boundary_hook(
        self, epoch, sentinel_ok, saved, last_metrics, rec
    ) -> None:
        super()._epoch_boundary_hook(
            epoch, sentinel_ok, saved, last_metrics, rec
        )
        tsnap = self.transport.snapshot()
        sup = self.supervisor.stats()
        last_metrics.update({
            "decoupled/actor_restarts": sup["restarts_total"],
            "decoupled/fleet_alive": sup["alive"],
            "decoupled/fleet_deaths_total": sup["deaths_total"],
            "decoupled/transport_accepted_total":
                tsnap["accepted_total"],
            "decoupled/transport_duplicate_pushes_total":
                tsnap["duplicate_pushes_total"],
            "decoupled/transport_rejected_malformed_total":
                tsnap["rejected_malformed_total"],
            "decoupled/transport_rejected_zombie_total":
                tsnap["rejected_zombie_total"],
        })
        # Per-actor lag labels (docs/OBSERVABILITY.md): sequence
        # watermark + heartbeat age per live fleet actor, keyed by
        # actor id — the per-actor view of "who is falling behind".
        for aid, a in tsnap["actors"].items():
            last_metrics[f"decoupled/actor{aid}_seq"] = float(a["seq"])
            last_metrics[f"decoupled/actor{aid}_heartbeat_age_s"] = (
                round(float(a["heartbeat_age_s"]), 3)
            )
        if self.elastic is not None:
            # Degrade newly abandoned slots, re-admit served ones —
            # the training-plane actuation point (epoch boundaries
            # only, so a re-admitted slice joins at a clean cut).
            self.elastic.poll_epoch(int(epoch))
            last_metrics.update(self.elastic.metrics())
        if rec is not None:
            rec.event(
                "fleet", epoch=int(epoch), transport=tsnap,
                supervisor=sup,
            )

    # ------------------------------------------------------ introspection

    def metrics_snapshot(self) -> dict:
        snap = super().metrics_snapshot()
        snap["decoupled"]["transport"] = self.transport.snapshot()
        snap["decoupled"]["fleet"] = self.supervisor.stats()
        if self.elastic is not None:
            snap["decoupled"]["elastic"] = self.elastic.snapshot()
        return snap

    def close(self):
        if self._fleet_started:
            self._fleet_started = False
            self.supervisor.shutdown()
        self.transport.close()
        super().close()


# Re-exported for callers staging canonically on the actor side.
_ = canonical_transition
