"""Decoupled learner: the hardened Trainer loop over the serving plane.

:class:`DecoupledTrainer` keeps every hardened piece of the host
:class:`~torch_actor_critic_tpu.sac.trainer.Trainer` — divergence
sentinel, preemption guard, telemetry phases, diagnostics, cost
attribution, bitwise resume — and replaces only the DATA PATH through
the subclass seams (ROADMAP item 5, Podracer arXiv:2104.06272 /
TorchBeast arXiv:1910.03552):

- **Acting** goes through a :class:`~torch_actor_critic_tpu.serve.
  server.PolicyClient` (in-process registry+batcher built here, or
  HTTP at ``config.serve_url``) via an :class:`~torch_actor_critic_tpu
  .decoupled.actor.ActorWorker` — bounded retry, graceful degradation
  to the learner's own param mirror (staleness-stamped), re-homing.
- **Staging** is the bounded :class:`~torch_actor_critic_tpu.decoupled
  .staging.StagingBuffer`: every transition tagged with the serving
  response's ``(generation, epoch)``, drained in fixed windows through
  the bounded-staleness admission gate into the unchanged replay/
  update path.
- **Publishing**: each sentinel-validated epoch swaps the new actor
  params into the registry through the PR-5 validated hot-reload (a
  non-finite publish is *rejected* and actors keep acting on
  last-good); in ``serve_url`` mode the epoch checkpoint IS the
  publish — the remote worker's poller picks it up.
- **Fault tolerance**: checkpoints additionally carry the staged-but-
  undrained transitions (the ``arrays`` item), the staging counters +
  lag histogram, and the batcher's sampled-action PRNG key — so a
  SIGTERM on the learner (PreemptionGuard, requeue code 75) loses no
  accepted transition and the replay stream is **bitwise** across the
  resume, while remote actors idle-spin against the paused staging
  buffer and reconnect (proven in tests/test_decoupled.py and
  ``make decouple-smoke``).

Deployment story (docs/SERVING.md "Training feeds serving"): the same
registry/batcher/client stack serves production traffic and training
actors; a training cluster's learner publishes into the serving fleet
its actors read from.
"""

from __future__ import annotations

import logging
import typing as t

import jax
import numpy as np

from torch_actor_critic_tpu.decoupled.actor import ActorWorker
from torch_actor_critic_tpu.decoupled.staging import StagingBuffer
from torch_actor_critic_tpu.sac.trainer import Trainer

logger = logging.getLogger(__name__)

__all__ = ["DecoupledTrainer"]


class DecoupledTrainer(Trainer):
    """Trainer whose actors act through the serving plane.

    Accepts every :class:`Trainer` argument; ``client`` injects a
    pre-built :class:`PolicyClient` (tests wrap it in the lossy-link
    fault injector), otherwise ``config.serve_url`` selects HTTP mode
    and the default builds a co-located in-process serving plane
    (registry + micro-batcher) that doubles as this process's policy
    service — ``metrics_snapshot`` plugs into a ``PolicyServer``'s
    ``extra_snapshot`` to put staging/staleness on ``/metrics``.
    """

    def __init__(self, *args, client=None, **kwargs):
        super().__init__(*args, **kwargs)
        cfg = self.config
        self.staging = StagingBuffer(
            capacity=cfg.resolved_staging_capacity,
            policy=cfg.staging_policy,
            max_lag=cfg.max_actor_lag,
        )
        self._published_generation = 0
        self._published_epoch: int | None = None
        self._publish_rejected_total = 0
        self._collecting = False
        self._last_tag: t.Tuple[int, int | None] = (0, None)
        self.registry = None
        self.batcher = None
        self._owns_plane = False
        if client is not None:
            self.client = client
        elif cfg.serve_url:
            from torch_actor_critic_tpu.serve.server import PolicyClient

            self.client = PolicyClient(
                url=cfg.serve_url, retries=1, backoff_s=0.1
            )
        else:
            self._build_inprocess_plane()
        self.actor = ActorWorker(
            self.client,
            self.staging,
            fallback=self._local_fallback,
            act_timeout_s=cfg.actor_timeout_s,
            probe_every=4,
        )

    def _build_inprocess_plane(self):
        """Co-located serving plane: one registry slot holding this
        learner's actor, behind a real micro-batcher — the exact stack
        ``serve.py`` runs, so "training feeds serving" is one code
        path whether the fleet is in-process or remote."""
        from torch_actor_critic_tpu.serve.batcher import MicroBatcher
        from torch_actor_critic_tpu.serve.registry import ModelRegistry
        from torch_actor_critic_tpu.serve.server import PolicyClient

        serve_batch = max(self.n_envs, 1)
        self.registry = ModelRegistry()
        self.registry.register(
            "default",
            self.sac.actor_def,
            self.pool.obs_spec,
            params=self._fetch_params_single_transfer(),
            max_batch=serve_batch,
            warmup=True,
        )
        self.batcher = MicroBatcher(
            self.registry, max_batch=serve_batch, seed=self.seed + 7919
        )
        self.client = PolicyClient(
            self.registry, self.batcher, retries=1, backoff_s=0.05
        )
        self._owns_plane = True

    # ------------------------------------------------------------- acting

    def _local_fallback(self, obs, deterministic):
        """Degraded-mode acting: the learner-local param path the base
        trainer uses (host mirror, one transfer per window), stamped
        with the last PUBLISHED generation/epoch — what degraded
        transitions honestly are to the staging gate."""
        actions = Trainer._policy_actions(self, obs, deterministic)
        return actions, self._published_generation, self._published_epoch

    def _policy_actions(self, obs_batch, deterministic=False) -> np.ndarray:
        if deterministic or not self._collecting:
            # Evaluation (and any deterministic rollout) reads the
            # current learner params directly, exactly as lockstep.
            return super()._policy_actions(obs_batch, deterministic)
        actions, generation, epoch, _ = self.actor.act(
            obs_batch, deterministic=False
        )
        self._last_tag = (generation, epoch)
        return np.asarray(actions)

    def train(self, render: bool = False) -> dict:
        self._collecting = True
        try:
            return super().train(render)
        finally:
            self._collecting = False

    # ------------------------------------------------------------ staging

    def _canonical_transition(self, transition: tuple) -> tuple:
        """Pin the staged dtypes to the env spec so checkpointed
        staging arrays restore against a shape/dtype-stable abstract
        tree regardless of what a normalizer upcast."""
        obs, actions, rewards, next_obs, done = transition
        spec = self.pool.obs_spec

        def cast(x, s):
            return np.asarray(x, dtype=s.dtype)

        return (
            jax.tree_util.tree_map(cast, obs, spec),
            np.asarray(actions, np.float32),
            np.asarray(rewards, np.float32),
            jax.tree_util.tree_map(cast, next_obs, spec),
            np.asarray(done, np.float32),
        )

    def _stage(self, staging, transition) -> None:
        # `staging` (the base loop's host list) is unused: transitions
        # live in the bounded buffer, under its backpressure policy.
        generation, epoch = self._last_tag
        self.staging.put(
            self._canonical_transition(transition),
            generation=generation,
            epoch=epoch,
        )

    def _drain_window(self, staging):
        entries = self.staging.pop_window(
            self.config.update_every, current_epoch=self._epoch
        )
        if entries is None:
            return None
        return self._build_chunk([e.transition for e in entries])

    # --------------------------------------------------------- publishing

    def _publish_epoch(self, epoch: int, saved: bool) -> None:
        if self.registry is not None:
            try:
                generation = self.registry.swap(
                    "default",
                    self._fetch_params_single_transfer(),
                    epoch=int(epoch),
                )
            except ValueError as e:
                # PR-5 validated hot-reload: a non-finite publish is
                # rejected; the slot keeps serving last-good and actors
                # never see the poison (docs/SERVING.md).
                self._publish_rejected_total += 1
                logger.warning(
                    "epoch %d publish REJECTED (%s); actors keep "
                    "acting on generation %d (epoch %s)",
                    epoch, e, self._published_generation,
                    self._published_epoch,
                )
                return
            self._published_generation += 1
            self._published_epoch = int(epoch)
            logger.debug(
                "published epoch %d as generation %d",
                epoch, generation,
            )
        elif saved:
            # Remote serving: the epoch checkpoint IS the publish — the
            # worker's hot-reload poller validates and swaps it.
            self._published_generation += 1
            self._published_epoch = int(epoch)

    def _epoch_boundary_hook(
        self, epoch, sentinel_ok, saved, last_metrics, rec
    ) -> None:
        if sentinel_ok:
            self._publish_epoch(epoch, saved)
        snap = self.staging.snapshot()
        actor = self.actor.stats()
        lag = snap["actor_lag"]
        last_metrics.update({
            "decoupled/staged_total": snap["staged_total"],
            "decoupled/drained_total": snap["drained_total"],
            "decoupled/dropped_stale_total": snap["dropped_stale_total"],
            "decoupled/dropped_backpressure_total":
                snap["dropped_backpressure_total"],
            "decoupled/dropped_dead_actor_total":
                snap["dropped_dead_actor_total"],
            "decoupled/shed_total": snap["shed_total"],
            "decoupled/blocked_total": snap["blocked_total"],
            "decoupled/staging_depth": snap["depth"],
            # The cross-process conservation invariant, checked every
            # epoch: staged == drained + dropped_stale +
            # dropped_backpressure + dropped_dead_actor + depth.
            "decoupled/conservation_ok":
                float(self.staging.conservation_holds()),
            "decoupled/actor_lag_mean": lag.get("actor_lag_mean", 0.0),
            "decoupled/actor_lag_p95": lag.get("actor_lag_p95", 0.0),
            "decoupled/actor_lag_max": lag.get("actor_lag_max", 0.0),
            "decoupled/serving_actions_total":
                actor["serving_actions_total"],
            "decoupled/fallback_actions_total":
                actor["fallback_actions_total"],
            "decoupled/degradations_total": actor["degradations_total"],
            "decoupled/rehomes_total": actor["rehomes_total"],
            "decoupled/degraded": float(actor["degraded"]),
            "decoupled/published_generation": self._published_generation,
            "decoupled/publish_rejected_total":
                self._publish_rejected_total,
            "decoupled/client_retries_total": self.client.retries_total,
        })
        # Lag drift is a leading indicator of a sick actor↔serving
        # link (a degraded fleet keeps feeding ever-staler data until
        # the gate bites): route it through the early-warning monitor
        # into the sentinel, like the in-graph diagnostics.
        if self.monitor is not None:
            for w in self.monitor.update({
                "decoupled/actor_lag_mean":
                    lag.get("actor_lag_mean", 0.0),
            }):
                logger.warning(
                    "early warning %s: %s=%.4g vs baseline %.4g "
                    "(deviation envelope %.4g) — actor staleness "
                    "drifting, see docs/RESILIENCE.md",
                    w["kind"], w["key"], w["value"], w["baseline"],
                    w["spread"],
                )
                if self.sentinel is not None:
                    self.sentinel.note_warning(w["kind"])
                if rec is not None:
                    rec.event("early_warning", epoch=int(epoch), **w)
        if rec is not None:
            rec.event(
                "decoupled", epoch=int(epoch), staging=snap,
                actor=actor,
                published_generation=self._published_generation,
                publish_rejected_total=self._publish_rejected_total,
            )

    # --------------------------------------------------------- checkpoint

    def _checkpoint_extra(self, step: int) -> dict:
        extra = super()._checkpoint_extra(step)
        dec = {
            "staging": self.staging.meta_state(),
            "published_generation": self._published_generation,
            "published_epoch": self._published_epoch,
            "publish_rejected_total": self._publish_rejected_total,
            "actor": self.actor.stats(),
        }
        if self.batcher is not None:
            # The serving plane's sampled-action PRNG stream is part of
            # the run: resume continues it bitwise.
            dec["batcher_key"] = self.batcher.export_key()
        extra["decoupled"] = dec
        return extra

    def _checkpoint_arrays(self):
        return self.staging.export_arrays()

    def _staging_abstract(self, count: int) -> dict:
        n = self.n_envs
        spec = self.pool.obs_spec

        def zeros(s):
            return np.zeros((count, n) + tuple(s.shape), s.dtype)

        return {
            "obs": jax.tree_util.tree_map(zeros, spec),
            "actions": np.zeros((count, n, self.pool.act_dim), np.float32),
            "rewards": np.zeros((count, n), np.float32),
            "next_obs": jax.tree_util.tree_map(zeros, spec),
            "done": np.zeros((count, n), np.float32),
            "generation": np.zeros((count,), np.int64),
            "epoch": np.zeros((count,), np.int64),
            "actor_id": np.zeros((count,), np.int64),
        }

    def _checkpoint_abstract_arrays(self, meta_probe: dict):
        dec = (meta_probe or {}).get("decoupled") or {}
        count = int((dec.get("staging") or {}).get("count", 0))
        if count == 0:
            return None
        return self._staging_abstract(count)

    def _restore_extras(self, meta: dict, arrays) -> None:
        dec = meta.get("decoupled") or {}
        if dec.get("staging"):
            self.staging.load_meta(dec["staging"])
        if arrays is not None:
            restored = self.staging.import_arrays(arrays)
            logger.info(
                "restored %d staged transitions from the checkpoint "
                "(zero accepted transitions lost across the restart)",
                restored,
            )
        self._published_generation = int(
            dec.get("published_generation", 0)
        )
        self._published_epoch = dec.get("published_epoch")
        self._publish_rejected_total = int(
            dec.get("publish_rejected_total", 0)
        )
        self.actor.load_stats(dec.get("actor") or {})
        if self.batcher is not None and dec.get("batcher_key"):
            self.batcher.import_key(dec["batcher_key"])
        if self.registry is not None:
            # Refresh the co-located slot to the restored weights so
            # serving resumes from the checkpointed policy, not the
            # fresh-init params it was registered with.
            try:
                self.registry.swap(
                    "default",
                    self._fetch_params_single_transfer(),
                    epoch=meta.get("epoch"),
                )
            except ValueError as e:  # pragma: no cover — a restored
                # checkpoint is sentinel-validated; belt and braces
                logger.warning(
                    "restored params rejected by the serving "
                    "sentinel (%s); slot keeps its current params", e,
                )

    # ------------------------------------------------------- introspection

    def metrics_snapshot(self) -> dict:
        """``/metrics``-mergeable view of the decoupled plane — pass as
        ``PolicyServer(extra_snapshot=...)`` so a co-located server
        reports staging depth, backpressure counts and the actor-lag
        histogram next to its serving metrics."""
        return {
            "decoupled": {
                "staging": self.staging.snapshot(),
                "actor": self.actor.stats(),
                "published_generation": self._published_generation,
                "published_epoch": self._published_epoch,
                "publish_rejected_total": self._publish_rejected_total,
            }
        }

    def close(self):
        if self._owns_plane:
            try:
                self.batcher.close()
            finally:
                self.registry.close()
        super().close()
