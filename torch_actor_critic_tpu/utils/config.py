"""Typed configuration.

Replaces the reference's hardcoded hyperparameter dict
(ref ``main.py:147-160``) and scattered constants (lr ``main.py:93``,
buffer size ``main.py:140``, hidden sizes ``main.py:61``) with one
dataclass that round-trips through JSON for checkpoint/resume — the
reference round-trips params through MLflow *strings* and re-parses
them with ``int(float(v))`` heuristics (ref ``main.py:46-50``).

Defaults reproduce the reference run configuration exactly
(BASELINE.md "Reference run config").
"""

from __future__ import annotations

import dataclasses
import json
import typing as t


@dataclasses.dataclass
class SACConfig:
    # --- SAC hyperparameters (ref main.py:147-160) ---
    alpha: float = 0.2  # fixed entropy temperature (ref main.py:148)
    gamma: float = 0.99
    polyak: float = 0.995
    reward_scale: float = 1.0
    epochs: int = 1000
    batch_size: int = 64
    steps_per_epoch: int = 5000
    start_steps: int = 1000
    update_after: int = 1000
    update_every: int = 50
    max_ep_len: int = 5000
    save_every: int = 10

    # --- model / optimizer (ref main.py:61,93,140) ---
    lr: float = 3e-4
    hidden_sizes: t.Tuple[int, ...] = (256, 256)
    buffer_size: int = 1_000_000
    num_qs: int = 2  # ensemble size; 2 == reference DoubleCritic

    # --- extensions beyond the reference capability envelope ---
    # Algorithm family: "sac" (the reference's algorithm, parity) or
    # "td3" (extension — Twin Delayed DDPG over the same TrainState/
    # replay/burst/mesh machinery, torch_actor_critic_tpu/td3/).
    algorithm: str = "sac"
    # TD3 hyperparameters (Fujimoto et al. 2018 defaults); ignored
    # under algorithm="sac".
    policy_delay: int = 2      # critic steps per policy/target update
    act_noise: float = 0.1     # exploration noise std, x act_limit
    target_noise: float = 0.2  # target-policy smoothing std, x act_limit
    noise_clip: float = 0.5    # smoothing noise clip, x act_limit

    # Learned entropy temperature (SAC v2). The reference fixes
    # alpha=0.2; learn_alpha=False is parity mode.
    learn_alpha: bool = False
    target_entropy: t.Optional[float] = None  # default: -act_dim

    # Reference-quirk switch (SURVEY.md §7 item 4): the reference
    # samples pi from `next_state` but evaluates Q at `state` in the
    # policy loss (ref sac/algorithm.py:37-38). False (default) uses
    # `state` for both, matching spinningup; True reproduces the
    # reference exactly for return-parity runs.
    parity_pi_obs: bool = False

    # Visual stack (ref main.py:63-90: filters/kernels/strides passed to
    # the conv nets; defaults are the Atari-DQN trunk the reference
    # hardcodes at main.py:65-67)
    filters: t.Tuple[int, ...] = (32, 64, 64)
    kernel_sizes: t.Tuple[int, ...] = (8, 4, 3)
    strides: t.Tuple[int, ...] = (4, 2, 1)
    cnn_features: int = 1  # 1 == reference scalar-vision bottleneck
    cnn_dense_size: int = 512  # conv-trunk dense width (ref convolutional.py:36)
    # DrQ random-shift frame augmentation in the update path (pixel-RL
    # stabilizer, ops/augment.py). "none" = parity (the reference has
    # no augmentation); "shift" = DrQ K=M=1.
    frame_augment: str = "none"
    augment_pad: int = 4
    normalize_pixels: bool = False
    # Pixel hot path (ops/pixels.py, docs/SCALING.md "Mixed precision
    # & the pixel pipeline"). "reference" (parity default): sample
    # gathers uint8 frames and the CNN trunk decodes them to float32
    # in-graph — the historical path, bit-pinned. "fused": replay
    # gather + uint8 decode + DrQ shift + cast-to-compute-dtype run as
    # ONE fused gather (a Pallas kernel on TPU, the bitwise-equal jnp
    # reference elsewhere), so the sampled frame batch reaches the conv
    # towers in the compute dtype without ever materializing as f32 in
    # HBM. At compute_dtype=float32 with frame_augment="none" the two
    # pipelines are bitwise-identical per update (pinned by
    # tests/test_pixels.py); with augmentation the fused path draws its
    # shift offsets at sample time, so the PRNG streams differ by
    # construction. Visual observations only (build_models enforces).
    pixel_pipeline: str = "reference"

    # Sequence-policy extension: history_len > 1 wraps the env in a
    # sliding observation window (envs/wrappers.py HistoryEnv) and
    # dispatches to the causal-transformer SequenceActor/Critic stack
    # (models/sequence.py) — long-context capability the reference
    # lacks by construction (SURVEY.md §5). seq_* set the transformer
    # geometry.
    history_len: int = 1
    seq_d_model: int = 64
    seq_num_heads: int = 4
    seq_num_layers: int = 2

    # Fully-fused on-device training (sac/ondevice.py): env + replay +
    # learner compiled into one program per epoch. Only for envs with a
    # pure-JAX twin (envs/ondevice.py registry). on_device_envs is the
    # vectorized env batch per dp slice.
    on_device: bool = False
    on_device_envs: int = 16

    # Update-to-data ratio (REDQ-style, extension): gradient steps per
    # env step. The reference is pinned at 1 (update_every updates per
    # update_every steps, ref sac/algorithm.py:273-283); utd > 1 runs
    # round(update_every * utd) updates per window — the second lever
    # (after population) that converts idle MXU into learning. utd < 1
    # thins updates for env-bound setups. Must yield >= 1 update per
    # window.
    utd: float = 1.0

    # Population training (parallel/population.py): N completely
    # independent learners — own init, replay ring, optimizer and PRNG
    # streams per member — advanced by ONE vmapped compiled burst, so
    # the member matmuls batch onto the MXU together. The TPU-native
    # answer to multi-seed runs (the reference needs N full processes,
    # ref sac/mpi.py:10-34). Each member gets its own host env and its
    # own `buffer_size`-slot ring; metrics carry per-member curves.
    # Composes with on_device=True: the fused loop vmaps the ENTIRE
    # epoch program — envs, replay rings, PRNG streams and update
    # bursts — over the member axis (sac/ondevice.py
    # PopulationOnDeviceLoop), so N complete learning curves advance
    # per device dispatch.
    population: int = 1

    # On-device PBT (population-based training) exploit/explore over
    # the fused population loop: every pbt_every epochs members are
    # ranked by an in-loop episode-return EMA; each bottom-quantile
    # member copies params + optimizer state from a random top-quantile
    # member and multiplicatively perturbs its own hyperparameters
    # (lrs, alpha/target-entropy, TD3 target noise) by pbt_perturb^±1 —
    # all in-graph, no host round-trip (Jaderberg et al. 2017).
    # pbt_every=0 disables (the population stays N fixed-hyperparam
    # seeds). Requires population > 1 with on_device.
    pbt_every: int = 0
    pbt_quantile: float = 0.25  # exploit fraction at each end of the ranking
    pbt_perturb: float = 1.25   # multiplicative explore factor (>1)
    pbt_ema: float = 0.5        # EMA weight of each new epoch's mean return

    # --- scenarios/ (multi-agent / procedural / multi-task on-device
    # workloads, docs/SCENARIOS.md) ---
    # Multi-agent critic mode: "centralized" (CTDE — one twin critic
    # over the joint observation/action; the default) or "per_agent"
    # (VDN-style per-agent twin critics summed into the joint Q).
    # Ignored for envs without a multi-agent structure.
    ma_critic: str = "centralized"
    # Multi-task conditioning: 0 (default) feeds the task one-hot to
    # the policy/critics as ordinary observation features; > 0 projects
    # it through a learned linear embedding of this width first
    # (models/taskembed.py). Ignored for single-task envs.
    task_embed_dim: int = 0

    # Observation normalization (the reference ships a Welford
    # normalizer as dead code, ref sac/utils.py:27-65; here it's a
    # usable option).
    normalize_observations: bool = False

    # Network compute dtype — the mixed-precision training policy
    # (docs/SCALING.md "Mixed precision & the pixel pipeline"):
    # "float32" (parity default) or "bfloat16" (the MXU's native input
    # width — CNN-trunk convs and MLP matmuls run bf16 while params
    # (master weights), optimizer state, Bellman targets and all
    # loss/distribution math stay float32, so checkpoints are
    # precision-independent and no loss scaling is needed: bf16 shares
    # f32's 8-bit exponent, so there is no fp16-style underflow cliff
    # to scale away). The short aliases "f32"/"bf16" (the
    # `--precision` CLI spelling) normalize to the long names. The
    # torch reference has no mixed-precision path at all.
    compute_dtype: str = "float32"

    # Actor/learner split: run env-loop action selection on the host
    # CPU backend against a param mirror refreshed per update window,
    # instead of a per-step accelerator round trip.
    host_actor: bool = True

    # Overlap env stepping with the gradient burst (host_actor only):
    # the host mirror is refreshed from the PRE-burst params right
    # before each burst dispatches, so the env loop never waits for the
    # burst to finish — at the cost of acting with params one update
    # window stale (the reference acts on post-update params; off =
    # parity). Evaluation always refreshes to the current params.
    actor_param_lag: bool = False

    # lax.scan unroll factor for the fused gradient burst
    # (sac/algorithm.py update_burst). At the reference's tiny model
    # the per-step kernels are launch-bound on TPU; unrolling trades
    # compile time and code size for less loop overhead. 1 = plain
    # scan; 0 = auto (5 on the TPU backend — the chip-measured best at
    # the reference config, +12% over plain scan: burst_unroll section
    # of runs/tpu/bench_20260731T034827Z.json — 1 elsewhere, where the
    # gain is small and the unrolled scan body compiles ~3x slower). The knob
    # is semantics-preserving (exact-equality pinned in
    # tests/test_sac_update.py), so auto-tuning it is safe.
    burst_unroll: int = 0

    # Step the host env batch in parallel worker processes over the
    # native shared-memory runtime (envs/vec_env.py + native/). False =
    # in-process sequential stepping. The reference gets env parallelism
    # only as a side effect of whole-trainer MPI replication (ref
    # sac/mpi.py:10-34); here the host physics scales independently of
    # the learner mesh.
    parallel_envs: bool = False
    # Native-pool wait timeout: a worker that exceeds it is diagnosed
    # (hung vs dead) and surfaced as an error instead of deadlocking the
    # run (cf. the reference's per-step recv deadlock, SURVEY.md §5).
    env_timeout_s: float = 120.0
    # Worker bootstrap: "spawn" (default; workers never inherit live
    # TPU-client/jax state) or "fork" (fast startup; safe when envs are
    # pure numpy).
    env_start_method: str = "spawn"

    # --- resilience (resilience/, docs/RESILIENCE.md) ---
    # Divergence sentinel: one fused all-finite check over the learner
    # state + replay ring + epoch losses at every epoch boundary; a
    # non-finite epoch rolls back to the last sentinel-validated
    # checkpoint instead of poisoning the run (the reference trains on
    # NaNs forever). max_rollbacks bounds CONSECUTIVE rollbacks before
    # aborting with TrainingDiverged — a streak means the fault is
    # systematic, not transient.
    sentinel: bool = True
    max_rollbacks: int = 3
    # Reseed every env at each epoch boundary with a seed derived from
    # (run seed, epoch, slice). Epochs become replayable units — the
    # property that makes preemption resume bitwise-identical to an
    # uninterrupted run (envs carry no state across the checkpoint
    # boundary). False restores pre-resilience behavior: epoch-boundary
    # resets continue each env's internal RNG stream, so a resumed run
    # sees different env realizations than the run it resumes.
    epoch_reseed: bool = True

    # --- decoupled actor/learner (decoupled/, docs/RESILIENCE.md
    # "Decoupled-plane failure modes", docs/SERVING.md "Training feeds
    # serving") ---
    # Sebulba/TorchBeast-style split: actors fetch actions through the
    # serving plane (in-process registry+batcher by default, or the
    # HTTP worker/router at serve_url), stream tagged transitions into
    # a bounded staging buffer, and the learner publishes each epoch
    # to the registry via the validated hot-reload. Incompatible with
    # on_device (acting is fused into the device program there) and
    # population > 1.
    decoupled: bool = False
    # "" = build an in-process serving plane; otherwise the HTTP base
    # URL of a serve.py worker or fleet router whose slot this run's
    # checkpoints feed (the worker hot-reload-polls the run's ckpt dir).
    serve_url: str = ""
    # Bounded-staleness admission gate: staged transitions published
    # more than this many epochs before the learner's current epoch
    # are dropped (counted dropped_stale_total) at drain time. With
    # one publish per epoch this is exactly the registry-generation
    # lag. NOTE: in serve_url mode publishes happen on checkpoint
    # saves, so choose max_actor_lag > save_every there.
    max_actor_lag: int = 4
    # Staging queue bound; 0 = auto (4 x update_every, which keeps the
    # inline actor from ever blocking on its own learner).
    staging_capacity: int = 0
    # Backpressure when staging is full: "block" (bounded wait, then
    # shed), "drop_oldest" (freshest-data-wins), "shed" (refuse new).
    # All three are counted (decoupled/staging.py).
    staging_policy: str = "block"
    # Per-acting-call serving budget: the PolicyClient retries within
    # it (jittered backoff, deadline-aware) and past it the actor
    # degrades to its local param snapshot instead of stalling envs.
    actor_timeout_s: float = 5.0

    # --- actor-process fleet (decoupled/fleet.py, docs/RESILIENCE.md
    # "Decoupled-plane failure modes") ---
    # N > 0 spawns N supervised ActorWorker subprocesses on their own
    # env pools, acting through the learner's serving plane and pushing
    # transitions over the networked staging transport (HTTP, per-actor
    # monotonic sequence numbers for idempotent ingestion). Implies
    # decoupled=True. 0 = no fleet (inline actor only).
    actors: int = 0
    # Restart budget per actor slot: a dead actor (process exit or
    # missed heartbeat deadline) is SIGKILL-reaped, its staged tail
    # purged (dropped_dead_actor_total), and respawned with jittered
    # exponential backoff up to this many times; past it the slot is
    # abandoned and the fleet trains on the survivors.
    actor_max_restarts: int = 3
    # Actors POST /heartbeat every interval; the supervisor declares an
    # actor dead when its newest heartbeat is older than the timeout.
    # The timeout must exceed the interval with slack for scheduling
    # jitter (CPU CI boxes stall; 6x is a sane floor).
    heartbeat_interval_s: float = 0.5
    heartbeat_timeout_s: float = 3.0
    # Actor-side staging-push retry budget: transient failures (refused
    # connections, 5xx, learner checkpoint pauses) are retried with
    # jittered exponential backoff within this budget, then the actor
    # degrades to local acting and re-homes on recovery (PR-10
    # semantics across the wire).
    actor_push_retry_s: float = 2.0
    # Transport bind port for the staging/heartbeat/act endpoint;
    # 0 = ephemeral (the chaos smoke pins a port so a resumed learner
    # rebinds the same address and live actors reconnect).
    fleet_port: int = 0

    # --- tiered replay + offline training (replay/, docs/REPLAY.md) ---
    # Tier stack under the HBM ring: "off" (parity default — no host
    # mirroring, no extra metric keys, jit cache and replay stream
    # bitwise identical to pre-tier builds), "host" (HBM + host-RAM
    # ring; evictions past the host ring are counted and dropped), or
    # "disk" (host evictions spill to append-only chunk files under
    # replay_dir). Host-loop single-member training only.
    replay_tiers: str = "off"
    # Host-ring capacity in transitions; 0 = auto (= buffer_size, i.e.
    # the host tier holds as much again as the device ring).
    replay_host_capacity: int = 0
    # Disk-tier directory; "" = <run_dir>/replay under the tracker.
    replay_dir: str = ""
    # Disk-tier byte budget; 0 = unbounded. Over budget the eviction
    # policy applies per chunk file: "fifo" deletes oldest chunks,
    # "stop" refuses new appends (counted, never raises).
    replay_disk_bytes: int = 0
    replay_disk_policy: str = "fifo"
    # Host-tier sampling prior for refill draws: "uniform" over the
    # resident window or "recent" (newest half).
    replay_priority: str = "uniform"
    # Refill rows per env per update window pushed back HBM-ward from
    # the host tier (0 = archival only: tiers record spill but never
    # feed samples back, leaving the device stream bit-identical).
    replay_refill: int = 0
    # Stage refill chunks on a background thread (double-buffered) so
    # the host→device copy hides behind the update burst; False
    # samples synchronously at the window boundary (the measured
    # stall, bench.py --stage=replay).
    replay_prefetch: bool = True

    # Offline training (train.py --offline): no env in the loop — the
    # dataset is a replay disk tier (trainer spill or serve-side
    # flywheel), loaded to host RAM and sampled by a host RNG.
    offline: bool = False
    offline_dataset: str = ""
    # Off-support Q-overestimation counterweight: "none" (plain SAC
    # steps), "bc" (behavior-cloning MSE anchor on the actor), "cql"
    # (conservative logsumexp gap penalty on the critic).
    offline_reg: str = "none"
    offline_reg_weight: float = 1.0
    offline_steps: int = 10000

    # --- observability (telemetry/, docs/OBSERVABILITY.md) ---
    # Per-step phase spans (act/env_step/stage/place_chunk/
    # burst_dispatch/drain/sentinel/checkpoint), per-epoch device HBM
    # watermarks and a JSONL event stream under the tracker run dir.
    # Off by default: the disabled hot path carries zero telemetry work
    # (bench.py `telemetry_overhead` pins the enabled cost at <5%).
    telemetry: bool = False
    # Learning-health diagnostics tier (diagnostics/,
    # docs/OBSERVABILITY.md "Learning-health diagnostics"): in-graph
    # gradient/Q/entropy reductions fused into the update burst.
    #   "off"   — parity default: compiled graph, metric keys and jit
    #             cache bitwise identical to an uninstrumented build;
    #   "light" — scalar diagnostics (grad global-norms, update-to-
    #             param ratios, Q stats, action saturation, per-burst
    #             loss maxima) + dp replica-skew + the recompilation
    #             watchdog; bench.py `diagnostics_overhead` holds this
    #             within the 5% bar on the CPU smoke config;
    #   "full"  — light + the on-device fixed-bucket TD-error
    #             histogram (merged host-side into the telemetry
    #             histogram schema).
    # The tier is read at trace time, so it is part of the compiled
    # program's identity — flipping it can never alias a cache entry.
    diagnostics: str = "off"
    # Runtime transfer sanitizer (docs/ANALYSIS.md "Runtime
    # sanitizers"): "on" wraps the Trainer's device phases (the
    # update-burst/push dispatch and the epoch drain) in
    # jax.transfer_guard("disallow"), so an IMPLICIT host<->device
    # transfer on the hot path — numpy leaking into the jit, a stray
    # Python scalar — is a hard failure in smokes instead of an
    # invisible per-step transfer tax (the 0.02-MFU class). "off"
    # (default) is no-op parity: the dispatch sites are untouched and
    # the metric stream is bitwise identical (pinned by
    # tests/test_sanitize.py).
    sanitize: str = "off"
    # Cold-start machinery (aot/, docs/SERVING.md "Cold start &
    # warm-start bundles"): `compile_cache` points the persistent XLA
    # compilation cache at a directory shared by fleet workers,
    # spawned actors, and learner RESTARTS — a preempted learner
    # resumes compile-free because its epoch programs are already on
    # disk. The dir is published to child processes via
    # TAC_COMPILE_CACHE. Empty (default) leaves jax's cache config
    # untouched.
    compile_cache: str = ""
    # `--emit-bundle` writes a warm-start bundle next to the Orbax
    # checkpoint at the FIRST update epoch (the earliest moment real
    # actor params exist): serve.py --warm-start auto then answers its
    # first /act with zero live compiles. Requires checkpointing
    # (save_every > 0) for the checkpoint-adjacent location.
    emit_bundle: bool = False
    # Serve bucket ladder ceiling the emitted bundle pre-compiles for
    # (must match the serve worker's --max-batch for the bundle to
    # cover its buckets; smokes shrink it to keep the build cheap).
    bundle_max_batch: int = 64
    # Run-wide observability plane (obs/, docs/OBSERVABILITY.md
    # "Run-wide plane"): `--obs` starts the ObsCollector — a scraper
    # thread folding every process's /metrics (learner telemetry,
    # staging transport + actors, any `--obs-scrape` extras like the
    # serve router) into one obs.jsonl time series, an aggregated
    # /metrics endpoint on `--obs-port` (0 = ephemeral), and `obs/`
    # columns in metrics.jsonl. Off by default: zero threads, zero
    # sockets, metric keys identical to a pre-PR-19 build (pinned by
    # tests/test_obs.py; bench.py `obs_overhead` holds the enabled
    # cost within the 5% bar).
    obs: bool = False
    obs_interval_s: float = 2.0
    obs_port: int = 0
    # Extra scrape targets, comma-separated `name=http://host:port`
    # pairs — how a training-side collector watches a separately
    # launched serving fleet's router.
    obs_scrape: str = ""
    # SLO rules over the aggregated series (obs/slo.py grammar); empty
    # = built-in defaults (goodput floor, p99 ceiling, shed-rate
    # ceiling, actor staleness, conservation, MFU floor).
    slo_config: str = ""
    # Size-based rotation for telemetry.jsonl / obs.jsonl (MB; 0 =
    # off, the append-only one-file-per-run default). Rotation keeps
    # one `.1` generation and writes a counted `sink_rotated` marker.
    telemetry_max_mb: float = 0.0
    # Training-plane elasticity (elastic/, docs/RESILIENCE.md
    # "Elasticity"): with `--elastic on`, an actor slot that exhausts
    # its restart budget becomes a counted `degrade` decision (the run
    # trains on the surviving slice; the conservation ledger's
    # dropped_dead_actor term absorbs the lost slice), and the slot is
    # re-admitted with a reset budget after `elastic_readmit_epochs`
    # degraded epochs — at an epoch boundary, so the slice rejoins at
    # a clean cut. Checkpoints carry the degraded topology. Off (the
    # default) constructs nothing: no decision log, no elastic/ metric
    # keys (key-pin, tests/test_elastic_controller.py).
    elastic: str = "off"
    elastic_readmit_epochs: int = 1

    def __post_init__(self):
        if not (len(self.filters) == len(self.kernel_sizes) == len(self.strides)):
            raise ValueError(
                "filters/kernel_sizes/strides must have equal length, got "
                f"{len(self.filters)}/{len(self.kernel_sizes)}/{len(self.strides)}"
            )
        # `--precision {f32,bf16}` aliases normalize to the long names
        # so stored configs/checkpoints carry one canonical spelling.
        self.compute_dtype = {"f32": "float32", "bf16": "bfloat16"}.get(
            self.compute_dtype, self.compute_dtype
        )
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype must be 'float32'/'f32' or "
                f"'bfloat16'/'bf16', got {self.compute_dtype!r}"
            )
        if self.pixel_pipeline not in ("reference", "fused"):
            raise ValueError(
                f"pixel_pipeline must be 'reference' or 'fused', got "
                f"{self.pixel_pipeline!r}"
            )
        if self.algorithm not in ("sac", "td3"):
            raise ValueError(
                f"algorithm must be 'sac' or 'td3', got {self.algorithm!r}"
            )
        if self.policy_delay < 1:
            raise ValueError(
                f"policy_delay must be >= 1, got {self.policy_delay}"
            )
        if self.algorithm == "td3" and (self.learn_alpha or self.parity_pi_obs):
            # Same fail-at-construction policy as the visual/sequence
            # gate: a SAC-only opt-in silently doing nothing would let a
            # user believe the feature is active.
            raise ValueError(
                "learn_alpha and parity_pi_obs are SAC-only options; "
                "algorithm='td3' has no entropy temperature and no "
                "pi-loss observation quirk"
            )
        if self.frame_augment not in ("none", "shift"):
            raise ValueError(
                "frame_augment must be 'none' or 'shift', got "
                f"{self.frame_augment!r}"
            )
        if self.augment_pad < 1:
            raise ValueError(
                f"augment_pad must be >= 1, got {self.augment_pad}"
            )
        if self.burst_unroll < 0:
            raise ValueError(
                f"burst_unroll must be >= 0 (0 = auto), got {self.burst_unroll}"
            )
        if self.utd <= 0 or round(self.update_every * self.utd) < 1:
            raise ValueError(
                f"utd={self.utd} with update_every={self.update_every} "
                "yields no gradient steps per window; raise utd or "
                "update_every"
            )
        if self.population < 1:
            raise ValueError(
                f"population must be >= 1, got {self.population}"
            )
        if self.pbt_every < 0:
            raise ValueError(
                f"pbt_every must be >= 0 (0 = off), got {self.pbt_every}"
            )
        if self.pbt_every > 0 and self.population < 2:
            raise ValueError(
                "pbt_every > 0 needs a population to exploit/explore "
                f"over; got population={self.population}"
            )
        if self.pbt_every > 0 and not self.on_device:
            raise ValueError(
                "PBT exploit/explore runs in-graph over the fused "
                "population loop; pass --on-device true (the host-loop "
                "population trains N fixed-hyperparam seeds)"
            )
        if not 0.0 < self.pbt_quantile <= 0.5:
            raise ValueError(
                f"pbt_quantile must be in (0, 0.5], got {self.pbt_quantile}"
            )
        if self.pbt_perturb <= 1.0:
            raise ValueError(
                f"pbt_perturb must be > 1 (multiplicative explore "
                f"factor), got {self.pbt_perturb}"
            )
        if not 0.0 < self.pbt_ema <= 1.0:
            raise ValueError(
                f"pbt_ema must be in (0, 1], got {self.pbt_ema}"
            )
        if self.ma_critic not in ("centralized", "per_agent"):
            raise ValueError(
                f"ma_critic must be 'centralized' or 'per_agent', got "
                f"{self.ma_critic!r}"
            )
        if self.task_embed_dim < 0:
            raise ValueError(
                f"task_embed_dim must be >= 0 (0 = raw one-hot), got "
                f"{self.task_embed_dim}"
            )
        if self.diagnostics not in ("off", "light", "full"):
            raise ValueError(
                f"diagnostics must be 'off', 'light' or 'full', got "
                f"{self.diagnostics!r}"
            )
        if self.sanitize not in ("off", "on"):
            raise ValueError(
                f"sanitize must be 'off' or 'on', got {self.sanitize!r}"
            )
        if self.max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {self.max_rollbacks}"
            )
        if self.staging_policy not in ("block", "drop_oldest", "shed"):
            raise ValueError(
                "staging_policy must be 'block', 'drop_oldest' or "
                f"'shed', got {self.staging_policy!r}"
            )
        if self.max_actor_lag < 0:
            raise ValueError(
                f"max_actor_lag must be >= 0, got {self.max_actor_lag}"
            )
        if self.staging_capacity < 0:
            raise ValueError(
                f"staging_capacity must be >= 0 (0 = auto), got "
                f"{self.staging_capacity}"
            )
        if self.actor_timeout_s <= 0:
            raise ValueError(
                f"actor_timeout_s must be > 0, got {self.actor_timeout_s}"
            )
        if self.actors < 0:
            raise ValueError(
                f"actors must be >= 0 (0 = no fleet), got {self.actors}"
            )
        if self.actors > 0:
            # --actors N is a decoupled-plane feature: the fleet feeds
            # the StagingBuffer and the learner's serving plane, so the
            # flag implies the split rather than erroring on it.
            self.decoupled = True
        if self.actor_max_restarts < 0:
            raise ValueError(
                f"actor_max_restarts must be >= 0, got "
                f"{self.actor_max_restarts}"
            )
        if self.heartbeat_interval_s <= 0:
            raise ValueError(
                f"heartbeat_interval_s must be > 0, got "
                f"{self.heartbeat_interval_s}"
            )
        if self.heartbeat_timeout_s <= self.heartbeat_interval_s:
            raise ValueError(
                f"heartbeat_timeout_s ({self.heartbeat_timeout_s}) must "
                f"exceed heartbeat_interval_s "
                f"({self.heartbeat_interval_s}); one missed beat is "
                "scheduling jitter, not death"
            )
        if self.actor_push_retry_s <= 0:
            raise ValueError(
                f"actor_push_retry_s must be > 0, got "
                f"{self.actor_push_retry_s}"
            )
        if not (0 <= self.fleet_port <= 65535):
            raise ValueError(
                f"fleet_port must be in [0, 65535], got {self.fleet_port}"
            )
        if self.obs_interval_s <= 0:
            raise ValueError(
                f"obs_interval_s must be > 0, got {self.obs_interval_s}"
            )
        if not (0 <= self.obs_port <= 65535):
            raise ValueError(
                f"obs_port must be in [0, 65535], got {self.obs_port}"
            )
        if self.telemetry_max_mb < 0:
            raise ValueError(
                f"telemetry_max_mb must be >= 0, got "
                f"{self.telemetry_max_mb}"
            )
        for pair in filter(None, self.obs_scrape.split(",")):
            if "=" not in pair:
                raise ValueError(
                    f"obs_scrape entries must be name=url pairs, got "
                    f"{pair!r}"
                )
        if self.elastic not in ("off", "on"):
            raise ValueError(
                f"elastic must be 'off' or 'on', got {self.elastic!r}"
            )
        if self.elastic == "on" and self.actors < 1:
            raise ValueError(
                "elastic is the fleet degrade/re-admit machinery; it "
                "needs an actor fleet (--actors >= 1)"
            )
        if self.elastic_readmit_epochs < 1:
            raise ValueError(
                f"elastic_readmit_epochs must be >= 1, got "
                f"{self.elastic_readmit_epochs}"
            )
        if self.decoupled:
            if self.on_device:
                raise ValueError(
                    "decoupled is the host-loop actor/learner split; "
                    "on_device fuses acting into the device program — "
                    "the two cannot compose. Pick one."
                )
            if self.population > 1:
                raise ValueError(
                    "decoupled does not compose with population > 1 "
                    "yet (per-member serving slots are not wired); run "
                    "members as separate decoupled processes instead"
                )
            if self.resolved_staging_capacity < self.update_every:
                raise ValueError(
                    f"staging_capacity={self.staging_capacity} is "
                    f"smaller than one update window "
                    f"(update_every={self.update_every}); the learner "
                    "could never drain a fixed-size window"
                )
        if self.replay_tiers not in ("off", "host", "disk"):
            raise ValueError(
                f"replay_tiers must be 'off', 'host' or 'disk', got "
                f"{self.replay_tiers!r}"
            )
        if self.replay_disk_policy not in ("fifo", "stop"):
            raise ValueError(
                f"replay_disk_policy must be 'fifo' or 'stop', got "
                f"{self.replay_disk_policy!r}"
            )
        if self.replay_priority not in ("uniform", "recent"):
            raise ValueError(
                f"replay_priority must be 'uniform' or 'recent', got "
                f"{self.replay_priority!r}"
            )
        if self.replay_host_capacity < 0:
            raise ValueError(
                f"replay_host_capacity must be >= 0 (0 = auto), got "
                f"{self.replay_host_capacity}"
            )
        if self.replay_disk_bytes < 0:
            raise ValueError(
                f"replay_disk_bytes must be >= 0 (0 = unbounded), got "
                f"{self.replay_disk_bytes}"
            )
        if self.replay_refill < 0:
            raise ValueError(
                f"replay_refill must be >= 0 (0 = archival only), got "
                f"{self.replay_refill}"
            )
        if self.replay_refill > 0 and self.replay_tiers == "off":
            raise ValueError(
                "replay_refill > 0 needs a tier stack to refill from; "
                "pass --replay-tiers host or disk"
            )
        if self.replay_tiers != "off":
            if self.on_device:
                raise ValueError(
                    "replay_tiers is the host-loop storage hierarchy; "
                    "on_device keeps the whole ring in the compiled "
                    "program — the two cannot compose"
                )
            if self.population > 1:
                raise ValueError(
                    "replay_tiers does not compose with population > 1 "
                    "(per-member tier stacks are not wired)"
                )
        if self.offline_reg not in ("none", "bc", "cql"):
            raise ValueError(
                f"offline_reg must be 'none', 'bc' or 'cql', got "
                f"{self.offline_reg!r}"
            )
        if self.offline_reg_weight < 0:
            raise ValueError(
                f"offline_reg_weight must be >= 0, got "
                f"{self.offline_reg_weight}"
            )
        if self.offline_steps < 1:
            raise ValueError(
                f"offline_steps must be >= 1, got {self.offline_steps}"
            )
        if self.offline:
            if self.on_device or self.decoupled or self.population > 1:
                raise ValueError(
                    "--offline trains from a disk tier with no env in "
                    "the loop; it does not compose with on_device, "
                    "decoupled or population > 1"
                )
        if self.actor_param_lag and not self.host_actor:
            raise ValueError(
                "actor_param_lag requires host_actor=True — the "
                "device-actor path reads post-burst params directly, so "
                "there is no mirror to run stale."
            )

    @property
    def updates_per_window(self) -> int:
        """Gradient steps per ``update_every``-step window:
        ``round(update_every * utd)``. At the default ``utd=1`` this is
        exactly the reference's one-update-per-env-step cadence."""
        return max(int(round(self.update_every * self.utd)), 1)

    @property
    def resolved_staging_capacity(self) -> int:
        """``staging_capacity`` with 0 resolved to ``4 x update_every``
        — enough headroom that the inline (same-thread) actor can
        always stage a full window past any gate-dropped leftovers
        without hitting its own backpressure policy."""
        return self.staging_capacity or 4 * self.update_every

    @property
    def resolved_burst_unroll(self) -> int:
        """``burst_unroll`` with 0 resolved by backend: 5 on TPU (the
        chip-measured best at the reference config), 1 elsewhere. The
        resolution happens at trace time, when the backend is known."""
        if self.burst_unroll:
            return self.burst_unroll
        import jax

        return 5 if jax.default_backend() == "tpu" else 1

    @property
    def model_dtype(self):
        """The jnp dtype models compute in (params always float32)."""
        import jax.numpy as jnp

        return jnp.bfloat16 if self.compute_dtype == "bfloat16" else jnp.float32

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, s: str) -> "SACConfig":
        raw = json.loads(s)
        field_names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in raw.items() if k in field_names}
        for tup in ("hidden_sizes", "filters", "kernel_sizes", "strides"):
            if tup in kwargs:
                kwargs[tup] = tuple(kwargs[tup])
        return cls(**kwargs)

    def replace(self, **kwargs) -> "SACConfig":
        return dataclasses.replace(self, **kwargs)
