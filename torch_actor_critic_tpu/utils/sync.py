"""Device-queue drain for honest wall-clock timing.

``jax.block_until_ready`` is the documented way to wait for async
dispatch, but on the tunneled ``axon`` TPU backend it can return before
the queued work actually executes — which silently inflates any
throughput measured as work/elapsed (observed as a physically
impossible "878 TFLOP/s" on a 197-TFLOP/s chip). A host *fetch* of a
scalar that data-depends on the work is a true barrier on every
backend: the bytes cannot arrive before the producer ran.

Every timing site in the framework (bench.py sections, the trainer's
per-epoch steps/sec metrics, the fused on-device loop benchmark) drains
through :func:`drain` instead of ``block_until_ready``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["drain"]


def drain(x) -> float:
    """Force execution of everything ``x`` depends on; return a float.

    ``x`` may be any array (it is reduced to one scalar on device, so
    only a few bytes cross the wire) or an already-scalar value. The
    returned float is the reduced value — usable as a checksum, but the
    point is the side effect: when this returns, the producer chain has
    executed.
    """
    if isinstance(x, jax.Array):
        if not x.is_fully_addressable:
            # Multi-host sharded array: a global reduce would need a
            # collective outside jit. Fetching this process's first
            # local shard drains the local device queue, which is all a
            # local wall-clock needs.
            shard = x.addressable_shards[0].data
            return float(jax.device_get(jnp.sum(shard, dtype=jnp.float32)))
        # Reduce in f32: summing in x's own dtype would overflow bf16
        # (max ~3.4e38 but 8-bit mantissa loses integer exactness past
        # 256) or wrap small ints, making the checksum claim false.
        # The fetch is an EXPLICIT jax.device_get: the drain is the
        # hot path's one intentional device->host transfer, so it must
        # stay legal under the --sanitize transfer guard
        # (docs/ANALYSIS.md "Runtime sanitizers").
        return float(jax.device_get(jnp.sum(x, dtype=jnp.float32)))
    return float(x)
