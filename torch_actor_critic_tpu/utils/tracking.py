"""File-based experiment tracking.

Capability twin of the reference's MLflow usage (params at run start,
per-epoch metrics, artifact storage, run-id resume — ref
``main.py:132-138,161-164``, ``sac/algorithm.py:291-296``) without the
MLflow dependency (not available in this image). Layout:

    <root>/<experiment>/<run_id>/
        params.json        # hyperparameters (typed, not stringly)
        metrics.jsonl      # one {"step": e, **metrics} line per log
        artifacts/         # checkpoints etc.

``Tracker.load`` resumes an existing run by id, the counterpart of
``mlflow.start_run(run_id)`` + ``load_session`` (ref ``main.py:28-51``).
If mlflow IS importable, :class:`Tracker` can mirror logs to it
(``mirror_mlflow=True``) for drop-in dashboard compatibility.
"""

from __future__ import annotations

import json
import logging
import math
import time
import typing as t
import uuid
from pathlib import Path

logger = logging.getLogger(__name__)


class Tracker:
    def __init__(
        self,
        experiment: str = "Default",
        run_id: str | None = None,
        root: str | Path = "runs",
        enabled: bool = True,
        mirror_mlflow: bool = False,
    ):
        self.enabled = enabled
        self.experiment = experiment
        self.run_id = run_id or uuid.uuid4().hex[:16]
        self.run_dir = Path(root) / experiment / self.run_id
        self.artifacts_dir = self.run_dir / "artifacts"
        self._mlflow = None
        if enabled:
            self.artifacts_dir.mkdir(parents=True, exist_ok=True)
            if mirror_mlflow:
                try:
                    import mlflow

                    mlflow.set_experiment(experiment)
                    mlflow.start_run(run_name=self.run_id)
                    self._mlflow = mlflow
                except ImportError:
                    pass

    @classmethod
    def load(cls, run_id: str, experiment: str = "Default", root="runs") -> "Tracker":
        t_ = cls(experiment=experiment, run_id=run_id, root=root)
        if not t_.run_dir.exists():
            raise FileNotFoundError(f"run {run_id} not found under {t_.run_dir}")
        return t_

    # ------------------------------------------------------------------ api

    def log_params(self, params: t.Mapping[str, t.Any]) -> None:
        if not self.enabled:
            return
        existing = self.params()
        existing.update(params)
        (self.run_dir / "params.json").write_text(json.dumps(existing, indent=2))
        if self._mlflow:
            self._mlflow.log_params(dict(params))

    def params(self) -> dict:
        p = self.run_dir / "params.json"
        return json.loads(p.read_text()) if p.exists() else {}

    @property
    def metrics_path(self) -> Path:
        """The append-only JSONL metrics mirror: one strict-JSON object
        per epoch, flushed per line — external pollers ``tail -f`` this
        instead of parsing MLflow state (docs/OBSERVABILITY.md)."""
        return self.run_dir / "metrics.jsonl"

    def log_metrics(self, metrics: t.Mapping[str, float], step: int) -> None:
        """Append one epoch row to the JSONL mirror (and best-effort to
        the MLflow mirror, when configured).

        The JSONL file is the source of truth: it is written FIRST and
        flushed per line, and a broken MLflow mirror is logged rather
        than allowed to lose the row. Non-finite values are mapped to
        ``null`` — Python's ``json`` would otherwise emit ``NaN``
        literals that strict JSON parsers (jq, serde, browsers) reject,
        breaking exactly the external pollers the mirror exists for."""
        if not self.enabled:
            return
        row: dict = {"step": int(step), "time": time.time()}
        for k, v in metrics.items():
            v = float(v)
            row[k] = v if math.isfinite(v) else None
        with open(self.metrics_path, "a") as f:
            f.write(json.dumps(row) + "\n")
            f.flush()
        if self._mlflow:
            try:
                self._mlflow.log_metrics(
                    {k: float(v) for k, v in metrics.items()}, step
                )
            except Exception as e:  # noqa: BLE001 — mirror, not truth
                logger.warning("mlflow mirror failed at step %d: %r", step, e)

    def metrics(self) -> t.List[dict]:
        p = self.metrics_path
        if not p.exists():
            return []
        return [json.loads(line) for line in p.read_text().splitlines() if line]

    def artifact_path(self, name: str) -> Path:
        return self.artifacts_dir / name
