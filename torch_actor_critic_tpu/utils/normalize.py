"""Online observation normalization (Welford).

The reference ships a ``WelfordVarianceEstimate`` normalizer as dead
code — defined with MLflow save/load hooks but never imported by the
training path (ref ``sac/utils.py:27-65``, SURVEY.md §2 "State
normalizers"). Here it is a *used* optional component
(``SACConfig.normalize_observations``) with correct Welford updates:
the reference's variance accumulator uses ``(x - old_mean)^2`` where
Welford's algorithm requires ``(x - old_mean) * (x - new_mean)``
(ref ``sac/utils.py:46-48``) — a deliberate fix, noted for parity
accounting.

Host-side numpy (it runs in the env loop on single observations);
state is a plain dict so it checkpoints with everything else.
"""

from __future__ import annotations

import typing as t

import numpy as np


class WelfordNormalizer:
    """y = (x - mean) / sqrt(var + eps), statistics updated online."""

    def __init__(self, dim: int, eps: float = 1e-8):
        self.mean = np.zeros(dim, np.float64)
        self.m2 = np.zeros(dim, np.float64)
        self.count = 0
        self.eps = eps

    def normalize(self, x: np.ndarray, update: bool = True) -> np.ndarray:
        """Accepts one observation ``(dim,)`` or a lockstep batch
        ``(n, dim)`` (the vectorized env pool path). The batched update
        is Chan's parallel merge, which reduces exactly to Welford's
        single-sample recurrence at n=1."""
        x = np.asarray(x, np.float64)
        if update:
            xb = x if x.ndim == 2 else x[None]
            n = xb.shape[0]
            b_mean = xb.mean(axis=0)
            b_m2 = ((xb - b_mean) ** 2).sum(axis=0)
            delta = b_mean - self.mean
            total = self.count + n
            self.mean = self.mean + delta * n / total
            self.m2 = self.m2 + b_m2 + delta**2 * self.count * n / total
            self.count = total
        var = self.m2 / max(self.count, 1)
        return ((x - self.mean) / np.sqrt(var + self.eps)).astype(np.float32)

    # ------------------------------------------------------- persistence

    def state_dict(self) -> dict:
        return {
            "mean": self.mean.tolist(),
            "m2": self.m2.tolist(),
            "count": self.count,
        }

    def load_state_dict(self, d: t.Mapping) -> None:
        self.mean = np.asarray(d["mean"], np.float64)
        self.m2 = np.asarray(d["m2"], np.float64)
        self.count = int(d["count"])


class IdentityNormalizer:
    """Pass-through (ref ``Identity``, ``sac/utils.py:68-79``)."""

    def normalize(self, x: np.ndarray, update: bool = True) -> np.ndarray:
        return np.asarray(x, np.float32)

    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, d) -> None:
        pass
