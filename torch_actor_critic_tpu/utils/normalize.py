"""Online observation normalization (Welford).

The reference ships a ``WelfordVarianceEstimate`` normalizer as dead
code — defined with MLflow save/load hooks but never imported by the
training path (ref ``sac/utils.py:27-65``, SURVEY.md §2 "State
normalizers"). Here it is a *used* optional component
(``SACConfig.normalize_observations``) with correct Welford updates:
the reference's variance accumulator uses ``(x - old_mean)^2`` where
Welford's algorithm requires ``(x - old_mean) * (x - new_mean)``
(ref ``sac/utils.py:46-48``) — a deliberate fix, noted for parity
accounting.

Host-side numpy (it runs in the env loop on single observations);
state is a plain dict so it checkpoints with everything else.
"""

from __future__ import annotations

import typing as t

import numpy as np


class WelfordNormalizer:
    """y = (x - mean) / sqrt(var + eps), statistics updated online."""

    def __init__(self, dim: int, eps: float = 1e-8):
        self.mean = np.zeros(dim, np.float64)
        self.m2 = np.zeros(dim, np.float64)
        self.count = 0
        self.eps = eps
        # Snapshot of the stats at the last cross-process sync; the
        # difference (current - base) is this process's UNSYNCED local
        # contribution (see sync_global).
        self._base = (self.mean.copy(), self.m2.copy(), 0)

    def normalize(
        self, x: np.ndarray, update: bool = True, member: int | None = None
    ) -> np.ndarray:
        """Accepts one observation ``(dim,)`` or a lockstep batch
        ``(n, dim)`` (the vectorized env pool path). The batched update
        is Chan's parallel merge, which reduces exactly to Welford's
        single-sample recurrence at n=1. ``member`` is accepted for
        interface parity with :class:`PerMemberNormalizer` and ignored:
        one pooled estimate serves every env slot."""
        x = np.asarray(x, np.float64)
        if update:
            xb = x if x.ndim == 2 else x[None]
            n = xb.shape[0]
            b_mean = xb.mean(axis=0)
            b_m2 = ((xb - b_mean) ** 2).sum(axis=0)
            delta = b_mean - self.mean
            total = self.count + n
            self.mean = self.mean + delta * n / total
            self.m2 = self.m2 + b_m2 + delta**2 * self.count * n / total
            self.count = total
        var = self.m2 / max(self.count, 1)
        return ((x - self.mean) / np.sqrt(var + self.eps)).astype(np.float32)

    # ------------------------------------------------ cross-process merge

    def merge(self, others: t.Sequence[t.Tuple[np.ndarray, np.ndarray, int]]):
        """Fold other processes' ``(mean, m2, count)`` triples into this
        normalizer (Chan's pairwise merge — the same formula as the
        batched update above). Used once per epoch in multi-host runs so
        every host normalizes with GLOBAL statistics; without it each
        host would drift to its own local-env statistics and the
        replicated networks would see differently-scaled inputs per
        host."""
        for o_mean, o_m2, o_count in others:
            if o_count == 0:
                continue
            o_mean = np.asarray(o_mean, np.float64)
            o_m2 = np.asarray(o_m2, np.float64)
            total = self.count + o_count
            delta = o_mean - self.mean
            self.mean = self.mean + delta * o_count / total
            self.m2 = self.m2 + o_m2 + delta**2 * self.count * o_count / total
            self.count = total

    def _local_delta(self) -> t.Tuple[np.ndarray, np.ndarray, int]:
        """This process's contribution since the last sync: the inverse
        of Chan's merge applied to (current, base)."""
        b_mean, b_m2, b_count = self._base
        d_count = self.count - b_count
        if d_count <= 0:
            return np.zeros_like(self.mean), np.zeros_like(self.m2), 0
        if b_count == 0:
            return self.mean.copy(), self.m2.copy(), d_count
        d_mean = (self.count * self.mean - b_count * b_mean) / d_count
        delta = d_mean - b_mean
        d_m2 = self.m2 - b_m2 - delta**2 * b_count * d_count / self.count
        return d_mean, np.maximum(d_m2, 0.0), d_count

    def sync_global(self) -> None:
        """All-gather every process's UNSYNCED local delta and fold all
        of them into the shared base, so every host holds the identical
        GLOBAL estimate afterwards (each sample enters exactly once,
        however many times this is called). No-op single-process;
        callers invoke it at epoch boundaries, off the hot path."""
        import jax

        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils

        d_mean, d_m2, d_count = self._local_delta()
        payload = np.concatenate([d_mean, d_m2, [float(d_count)]])
        gathered = np.asarray(multihost_utils.process_allgather(payload))
        dim = self.mean.shape[0]
        # Restart from the shared base and fold every process's delta in
        # process order — deterministic, so all hosts end bit-identical.
        self.mean, self.m2, self.count = (
            self._base[0].copy(), self._base[1].copy(), self._base[2],
        )
        self.merge(
            [
                (row[:dim], row[dim : 2 * dim], int(row[-1]))
                for row in gathered
            ]
        )
        self._base = (self.mean.copy(), self.m2.copy(), self.count)

    # ------------------------------------------------------- persistence

    def state_dict(self) -> dict:
        return {
            "mean": self.mean.tolist(),
            "m2": self.m2.tolist(),
            "count": self.count,
        }

    def load_state_dict(self, d: t.Mapping) -> None:
        self.mean = np.asarray(d["mean"], np.float64)
        self.m2 = np.asarray(d["m2"], np.float64)
        self.count = int(d["count"])
        # Every host restores the same checkpointed stats, so they are
        # the new shared sync base.
        self._base = (self.mean.copy(), self.m2.copy(), self.count)


class FeaturesNormalizer:
    """Welford normalization of the ``features`` leaf of a
    :class:`~torch_actor_critic_tpu.core.types.MultiObservation`;
    frames pass through untouched.

    The visual envs that want this most are exactly the mixed-obs ones:
    the wall-runner's 168 proprioceptive dims span heterogeneous scales
    (ref ``environments/wall_runner.py:21``) while its pixels already
    have a whitening path of their own (``normalize_pixels`` in the
    model, DrQ augmentation in the update) — so statistics are tracked
    for the feature vector only, and uint8 frames keep their replay
    layout. Same state_dict/sync surface as :class:`WelfordNormalizer`,
    so checkpointing and the multi-host epoch sync work unchanged.
    """

    def __init__(self, feature_dim: int, eps: float = 1e-8):
        self.inner = WelfordNormalizer(feature_dim, eps)

    def normalize(self, obs, update: bool = True, member: int | None = None):
        from torch_actor_critic_tpu.core.types import MultiObservation

        return MultiObservation(
            features=self.inner.normalize(obs.features, update=update),
            frame=obs.frame,
        )

    def sync_global(self) -> None:
        self.inner.sync_global()

    def state_dict(self) -> dict:
        return {"features": self.inner.state_dict()}

    def load_state_dict(self, d: t.Mapping) -> None:
        self.inner.load_state_dict(d["features"])


class PerMemberNormalizer:
    """One independent Welford estimate PER POPULATION MEMBER.

    Pooling one estimate across a population would couple the
    "independent" seeds through their input scaling (member i's
    observations would shift member j's normalization — exactly the
    leakage the population contract forbids), which is why
    ``population > 1`` used to reject ``normalize_observations``
    outright. Here the statistics carry a leading member axis and
    every update is vectorized across members: a lockstep ``(N, dim)``
    batch is N single-sample Welford updates, one per member's own
    estimate, in one numpy op.

    ``member=i`` normalizes a single ``(dim,)`` observation with (and
    optionally into) member ``i``'s statistics — the reset/eval path,
    where the trainer touches one member's env at a time. Same
    ``state_dict``/``sync_global`` surface as
    :class:`WelfordNormalizer` (populations are single-process, so the
    cross-host sync is a no-op by construction).
    """

    def __init__(self, n_members: int, dim: int, eps: float = 1e-8):
        if n_members < 1:
            raise ValueError(f"n_members must be >= 1, got {n_members}")
        self.n_members = n_members
        self.mean = np.zeros((n_members, dim), np.float64)
        self.m2 = np.zeros((n_members, dim), np.float64)
        self.count = np.zeros(n_members, np.int64)
        self.eps = eps

    def _apply(self, x, idx):
        var = self.m2[idx] / np.maximum(self.count[idx, None], 1)
        return ((x - self.mean[idx]) / np.sqrt(var + self.eps)).astype(
            np.float32
        )

    def normalize(
        self, x: np.ndarray, update: bool = True, member: int | None = None
    ) -> np.ndarray:
        x = np.asarray(x, np.float64)
        if member is not None:
            idx = np.array([member])
            xb = x[None]
        else:
            if x.ndim != 2 or x.shape[0] != self.n_members:
                raise ValueError(
                    f"expected a ({self.n_members}, dim) member-aligned "
                    f"batch or member=i with one observation; got shape "
                    f"{x.shape}"
                )
            idx = np.arange(self.n_members)
            xb = x
        if update:
            # Welford single-sample recurrence, vectorized over the
            # selected members (each row is ONE sample of its member).
            self.count[idx] += 1
            delta = xb - self.mean[idx]
            self.mean[idx] += delta / self.count[idx, None]
            self.m2[idx] += delta * (xb - self.mean[idx])
        out = self._apply(xb, idx)
        return out[0] if member is not None else out

    def sync_global(self) -> None:
        pass  # populations are single-process (PopulationLearner gate)

    def state_dict(self) -> dict:
        return {
            "mean": self.mean.tolist(),
            "m2": self.m2.tolist(),
            "count": self.count.tolist(),
        }

    def load_state_dict(self, d) -> None:
        self.mean = np.asarray(d["mean"], np.float64)
        self.m2 = np.asarray(d["m2"], np.float64)
        self.count = np.asarray(d["count"], np.int64)


class IdentityNormalizer:
    """Pass-through (ref ``Identity``, ``sac/utils.py:68-79``)."""

    def normalize(
        self, x: np.ndarray, update: bool = True, member: int | None = None
    ) -> np.ndarray:
        return np.asarray(x, np.float32)

    def sync_global(self) -> None:
        pass

    def state_dict(self) -> dict:
        return {}

    def load_state_dict(self, d) -> None:
        pass
