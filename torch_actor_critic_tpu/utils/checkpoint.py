"""Orbax checkpoint/resume of the COMPLETE training state.

The reference checkpoints actor/critic modules + optimizer state + epoch
through MLflow (ref ``sac/algorithm.py:164-180``) and on resume rebuilds
the target critic by deepcopy and restarts with an EMPTY replay buffer
(ref ``main.py:28-51``, SURVEY.md §3.5) — i.e. resumed runs are not the
same runs. Here one Orbax composite persists strictly more:

- the full :class:`TrainState` (params, target params, both opt states,
  learned-temperature state, PRNG key, step counter),
- optionally the full sharded replay :class:`BufferState`,
- the epoch + config JSON.

Restore round-trips device placement/sharding from abstract pytrees, so
a multi-chip run resumes onto the same mesh layout.
"""

from __future__ import annotations

import logging
import time
import typing as t
from pathlib import Path

import jax
import numpy as np
import orbax.checkpoint as ocp

from torch_actor_critic_tpu.core.types import BufferState, TrainState
from torch_actor_critic_tpu.resilience.retry import call_with_retries

logger = logging.getLogger(__name__)

# Checkpoint format version, bumped on any param-tree layout change.
# 2: Dense submodules are named by their tensor-parallel role
#    (``col``/``row``/``Dense_0``) instead of always ``Dense_0`` —
#    checkpoints written before that rename have a different tree
#    structure and cannot be restored into current models.
CKPT_FORMAT = 3  # 3: VisualDoubleCritic ensemble unrolled (ensemble_i
# submodules, dense convs) — visual param trees from format<=2 (vmapped
# 'ensemble' with a stacked leading axis) no longer restore


def _is_prng_key(x) -> bool:
    dt = getattr(x, "dtype", None)
    return dt is not None and jax.dtypes.issubdtype(dt, jax.dtypes.prng_key)


def _unwrap_prng_keys(tree):
    """Typed PRNG-key leaves -> raw uint32 key data.

    The orbax in this image cannot serialize extended-dtype (typed key)
    arrays (``np.array(key)`` raises inside its serializer), so key
    leaves cross the checkpoint boundary as their underlying uint32
    bits — same information, stable on-disk layout on every jax
    version. Applied symmetrically on save and on the abstract restore
    tree; :func:`_rewrap_prng_keys` restores the typed view.
    """
    return jax.tree_util.tree_map(
        lambda x: jax.random.key_data(x) if _is_prng_key(x) else x, tree
    )


def _rewrap_prng_keys(restored, reference):
    """Re-wrap raw uint32 key data as typed keys wherever ``reference``
    (the caller's abstract tree, pre-unwrap) holds a typed key."""

    def rewrap(r, ref):
        if not _is_prng_key(ref):
            return r
        try:
            impl = jax.random.key_impl(ref)
        except Exception:  # abstract leaf without impl info
            impl = None
        return jax.random.wrap_key_data(r, impl=impl)

    return jax.tree_util.tree_map(rewrap, restored, reference)


def _has_unrolled_visual_ensemble(train_state: TrainState) -> bool:
    """True when the critic tree is a format-3 unrolled visual ensemble
    (``ensemble_i`` submodules, models/visual.py) — the ONLY family
    whose layout changed between formats 2 and 3."""
    flat = jax.tree_util.tree_flatten_with_path(train_state.critic_params)[0]
    return any(
        getattr(k, "key", None) is not None
        and str(getattr(k, "key", "")).startswith("ensemble_")
        for path, _ in flat
        for k in path
    )


class CheckpointFormatError(ValueError):
    """The checkpoint's param-tree layout predates this build (see
    ``CKPT_FORMAT``). Deliberately NOT retried/fallen-back-from: every
    epoch in the directory shares the writer's format, so walking to an
    older step cannot fix it."""


class Checkpointer:
    def __init__(
        self,
        directory: str | Path,
        max_to_keep: int = 3,
        save_buffer: bool = True,
        retries: int = 2,
        retry_backoff_s: float = 0.5,
        sleep: t.Callable[[float], None] = time.sleep,
    ):
        self.directory = Path(directory).absolute()
        self.save_buffer = save_buffer
        # Transient-IO policy (resilience/retry.py): every Orbax
        # save/restore call gets `retries` extra attempts with
        # exponential backoff before the error surfaces. `sleep` is
        # injectable so tests drive the ladder without real waiting.
        self._retries = int(retries)
        self._retry_backoff_s = float(retry_backoff_s)
        self._sleep = sleep
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def _retry(self, fn: t.Callable[[], t.Any], what: str):
        return call_with_retries(
            fn,
            attempts=self._retries + 1,
            base_delay_s=self._retry_backoff_s,
            sleep=self._sleep,
            what=what,
        )

    def save(
        self,
        epoch: int,
        train_state: TrainState,
        buffer_state: BufferState | None = None,
        extra: t.Mapping[str, t.Any] | None = None,
        wait: bool = False,
        arrays: t.Any = None,
    ) -> None:
        """Write checkpoint for ``epoch`` (async unless ``wait``).

        ``arrays`` is an optional extra array pytree for state that is
        neither ``TrainState`` nor replay — the population-fused loop
        persists its member env states, acting keys and PBT
        bookkeeping here so resume continues bitwise. Typed PRNG-key
        leaves round-trip like the train state's.
        """
        items = {
            "train_state": ocp.args.StandardSave(_unwrap_prng_keys(train_state)),
            "meta": ocp.args.JsonSave(
                dict(extra or {}, epoch=int(epoch), ckpt_format=CKPT_FORMAT)
            ),
        }
        if buffer_state is not None and self.save_buffer:
            items["buffer"] = ocp.args.StandardSave(buffer_state)
        if arrays is not None:
            items["arrays"] = ocp.args.StandardSave(_unwrap_prng_keys(arrays))
        self._retry(
            lambda: self._mgr.save(epoch, args=ocp.args.Composite(**items)),
            what=f"checkpoint save (epoch {epoch})",
        )
        if wait:
            self._retry(
                self._mgr.wait_until_finished,
                what=f"checkpoint save finalize (epoch {epoch})",
            )

    def latest_epoch(self) -> int | None:
        """Newest *readable* checkpoint step.

        An interrupted async save (preemption mid-write, full disk) can
        leave a step directory whose metadata never landed; treating it
        as "latest" would kill every subsequent resume. Steps whose
        metadata cannot be read are skipped (with a warning) in favor
        of the newest valid epoch — exactly what resume wants.
        """
        for step in self._valid_candidates():
            return step
        return None

    def _valid_candidates(self) -> t.Iterator[int]:
        """All steps newest-first whose JSON metadata is readable."""
        for step in sorted(self._mgr.all_steps(), reverse=True):
            try:
                self._peek_meta_at(step)
            except Exception as e:  # noqa: BLE001 — any unreadable step
                # is a skip, whatever Orbax raises for it
                logger.warning(
                    "checkpoint epoch %s under %s is unreadable (%s: %s); "
                    "skipping it",
                    step, self.directory, type(e).__name__, e,
                )
                continue
            yield step

    def _peek_meta_at(self, epoch: int) -> dict:
        return dict(
            self._retry(
                lambda: self._mgr.restore(
                    epoch,
                    args=ocp.args.Composite(meta=ocp.args.JsonRestore()),
                ),
                what=f"checkpoint metadata read (epoch {epoch})",
            )["meta"]
        )

    def peek_meta(self, epoch: int | None = None) -> dict:
        """The checkpoint's JSON metadata alone (no array restore) —
        lets callers validate compatibility (e.g. which algorithm wrote
        it) BEFORE a tree-structure mismatch surfaces as an opaque
        Orbax error. ``epoch=None`` reads the newest *valid* epoch."""
        epoch = epoch if epoch is not None else self.latest_epoch()
        if epoch is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        return self._peek_meta_at(epoch)

    def restore(
        self,
        abstract_train_state: TrainState,
        abstract_buffer: BufferState | None = None,
        epoch: int | None = None,
        meta_probe: dict | None = None,
        abstract_arrays: t.Any = None,
    ) -> t.Tuple[TrainState, BufferState | None, dict]:
        """Restore ``(train_state, buffer_state, meta)``.

        With ``abstract_arrays`` given, returns a 4-tuple whose last
        element is the restored extra-array pytree (``None`` when the
        checkpoint predates the ``arrays`` item) — the counterpart of
        :meth:`save`'s ``arrays``.

        Abstract pytrees come from ``jax.eval_shape`` over the init
        functions (plus shardings); buffer restore is skipped if the
        checkpoint has none. A caller that already ran
        :meth:`peek_meta` (for its own compatibility checks) can pass
        the result as ``meta_probe`` to skip the redundant metadata
        round-trip.

        With ``epoch=None`` (resume), a corrupt or partial newest step
        — interrupted async save, truncated arrays — falls back to the
        next older epoch instead of killing the resume: losing one
        ``save_every`` interval beats losing the run. An explicitly
        requested ``epoch`` never falls back (the caller asked for that
        state, substituting another would be silent corruption).
        """
        if epoch is not None:
            return self._restore_at(
                epoch, abstract_train_state, abstract_buffer, meta_probe,
                abstract_arrays,
            )
        last_err: Exception | None = None
        tried = 0
        for step in self._valid_candidates():
            try:
                return self._restore_at(
                    step,
                    abstract_train_state,
                    abstract_buffer,
                    # The probe the caller took describes the newest
                    # valid epoch only; older fallback epochs re-probe.
                    meta_probe if tried == 0 else None,
                    abstract_arrays,
                )
            except CheckpointFormatError:
                raise  # every epoch shares the writer's format
            except Exception as e:  # noqa: BLE001 — corrupt step: any
                # Orbax error class means "this epoch is unusable"
                logger.warning(
                    "checkpoint epoch %d under %s failed to restore "
                    "(%s: %s); falling back to the previous epoch",
                    step, self.directory, type(e).__name__, e,
                )
                last_err = e
                tried += 1
        if last_err is not None:
            raise last_err
        raise FileNotFoundError(f"no checkpoints under {self.directory}")

    def _restore_at(
        self,
        epoch: int,
        abstract_train_state: TrainState,
        abstract_buffer: BufferState | None,
        meta_probe: dict | None,
        abstract_arrays: t.Any = None,
    ) -> t.Tuple[TrainState, BufferState | None, dict]:
        # Check the format version BEFORE the array restore, so a layout
        # change surfaces as this message instead of an opaque Orbax
        # tree-structure mismatch.
        if meta_probe is None:
            meta_probe = self._peek_meta_at(epoch)
        found = int(meta_probe.get("ckpt_format", 1))
        if found != CKPT_FORMAT and not (
            found == 2 and not _has_unrolled_visual_ensemble(abstract_train_state)
        ):
            # Format 3 only changed VisualDoubleCritic trees (ensemble
            # unroll); format-2 checkpoints of every other family
            # (flat MLP, TD3, sequence) restore unchanged — rejecting
            # them would invalidate working checkpoints for no reason.
            raise CheckpointFormatError(
                f"checkpoint at {self.directory} epoch {epoch} has format "
                f"{found}, this build reads format {CKPT_FORMAT}: the model "
                "parameter tree layout changed (see CKPT_FORMAT in "
                "utils/checkpoint.py). Re-train, or restore with the "
                "framework version that wrote it."
            )
        items = {
            "train_state": ocp.args.StandardRestore(
                _unwrap_prng_keys(abstract_train_state)
            ),
            "meta": ocp.args.JsonRestore(),
        }
        # Only request the buffer if this checkpoint actually contains
        # one (save_buffer may have been off). A shape/sharding mismatch
        # on a present buffer must surface, not silently resume with an
        # empty buffer — that is exactly the reference flaw (SURVEY.md
        # §3.5) this module exists to fix. The metadata probe alone
        # (keys, no arrays) makes Orbax warn that items "could not be
        # restored" without a handler registry — misleading noise for a
        # keys-only query, silenced here; the real restore below still
        # surfaces every error.
        import logging as _logging

        absl_logger = _logging.getLogger("absl")
        prev_level = absl_logger.level
        absl_logger.setLevel(_logging.ERROR)
        try:
            saved_items = set(self._mgr.item_metadata(epoch).keys())
        finally:
            absl_logger.setLevel(prev_level)
        if abstract_buffer is not None and "buffer" in saved_items:
            items["buffer"] = ocp.args.StandardRestore(abstract_buffer)
        if abstract_arrays is not None and "arrays" in saved_items:
            items["arrays"] = ocp.args.StandardRestore(
                _unwrap_prng_keys(abstract_arrays)
            )
        out = self._retry(
            lambda: self._mgr.restore(
                epoch, args=ocp.args.Composite(**items)
            ),
            what=f"checkpoint restore (epoch {epoch})",
        )
        train_state = _rewrap_prng_keys(
            out["train_state"], abstract_train_state
        )
        if abstract_arrays is None:
            return train_state, out.get("buffer"), dict(out["meta"])
        arrays = out.get("arrays")
        if arrays is not None:
            arrays = _rewrap_prng_keys(arrays, abstract_arrays)
        return train_state, out.get("buffer"), dict(out["meta"]), arrays

    def restore_actor_params(
        self, epoch: int | None = None, shardings: t.Any = None
    ) -> t.Tuple[t.Any, dict]:
        """``(actor_params, meta)`` of a checkpoint — the serving path.

        Unlike :meth:`restore` this needs NO abstract tree: the policy
        service knows only the actor module, not the critic/optimizer
        structure, so the ``train_state`` item is restored shape-from-
        disk (the replay ``buffer`` item is never requested — for a
        1M-transition run that is the difference between touching a few
        MB and tens of GB) and the actor subtree extracted. Params come
        back as a plain nested dict, which is exactly what
        ``actor_def.apply`` takes.

        ``shardings`` is the sub-mesh serving path (docs/SERVING.md
        "Sharded serving & precision tiers"): a callable taking the
        actor-params abstract tree (``ShapeDtypeStruct`` leaves, built
        from the checkpoint's OWN metadata — still no caller-side
        abstract tree) and returning a matching
        :class:`jax.sharding.Sharding` tree, or that sharding tree
        directly. Orbax then restores every actor array **straight
        into its sharded layout** — each device reads exactly its
        shards, and no host-RAM copy of the full (possibly
        bigger-than-one-host) actor is ever materialized. Non-actor
        subtrees restore as before.

        As with :meth:`restore`, ``epoch=None`` falls back past corrupt
        newest steps (a serving replica must come up on the last good
        weights, not crash-loop on a half-written save).
        """
        if epoch is None:
            last_err: Exception | None = None
            for step in self._valid_candidates():
                try:
                    return self.restore_actor_params(
                        step, shardings=shardings
                    )
                except Exception as e:  # noqa: BLE001 — corrupt step
                    logger.warning(
                        "actor restore from epoch %d under %s failed "
                        "(%s: %s); falling back to the previous epoch",
                        step, self.directory, type(e).__name__, e,
                    )
                    last_err = e
            if last_err is not None:
                raise last_err
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        # The shape-from-disk restore makes Orbax warn that a target
        # tree "is generally UNSAFE" — for serving the disk layout IS
        # the contract (the engine validates by applying the params),
        # so the warning is noise; silenced as in restore() above.
        import logging as _logging

        absl_logger = _logging.getLogger("absl")
        prev_level = absl_logger.level
        absl_logger.setLevel(_logging.ERROR)
        try:
            restore_args = (
                ocp.args.StandardRestore()
                if shardings is None
                else ocp.args.StandardRestore(
                    self._sharded_abstract_state(epoch, shardings)
                )
            )

            def _restore():
                import warnings

                with warnings.catch_warnings():
                    # The non-actor subtrees carry no shardings on
                    # purpose (only the actor is served); Orbax warns
                    # per such leaf that it falls back to the sharding
                    # file — noise for this deliberate partial layout.
                    warnings.filterwarnings(
                        "ignore",
                        message=".*sharding info.*",
                        category=UserWarning,
                    )
                    return self._mgr.restore(
                        epoch,
                        args=ocp.args.Composite(
                            train_state=restore_args,
                            meta=ocp.args.JsonRestore(),
                        ),
                    )

            out = self._retry(
                _restore, what=f"actor restore (epoch {epoch})"
            )
        finally:
            absl_logger.setLevel(prev_level)
        train_state = out["train_state"]
        if "actor_params" not in train_state:
            raise KeyError(
                f"checkpoint at {self.directory} epoch {epoch} has no "
                "actor_params item — not a TrainState checkpoint?"
            )
        return train_state["actor_params"], dict(out["meta"], epoch=epoch)

    def _sharded_abstract_state(self, epoch: int, shardings: t.Any):
        """Abstract ``train_state`` tree for a direct-to-sharded actor
        restore, built from the checkpoint's OWN array metadata (so
        serving still needs no caller-side abstract tree): the
        ``actor_params`` subtree carries the requested shardings,
        every other subtree restores unconstrained. Orbax cannot
        partially restore a ``StandardSave`` item, so the full tree is
        described — but only the actor arrays get layouts; the rest
        land exactly as the plain shape-from-disk path lands them."""
        ts_meta = self._retry(
            lambda: self._mgr.item_metadata(epoch),
            what=f"checkpoint array-metadata read (epoch {epoch})",
        )["train_state"]
        if ts_meta is None:
            # A manager that never SAVED this item (the serving
            # process — the trainer wrote the checkpoint) has no
            # handler registered for it and reports None; read the
            # item's array metadata straight off its directory.
            from etils import epath

            ts_meta = self._retry(
                lambda: ocp.StandardCheckpointHandler().metadata(
                    epath.Path(self.directory) / str(epoch) / "train_state"
                ),
                what=f"checkpoint array-metadata read (epoch {epoch})",
            )
        if ts_meta is None or "actor_params" not in ts_meta:
            raise KeyError(
                f"checkpoint at {self.directory} epoch {epoch} has no "
                "actor_params item — not a TrainState checkpoint?"
            )

        def sds(m, sharding=None):
            return jax.ShapeDtypeStruct(
                tuple(m.shape), m.dtype, sharding=sharding
            )

        abstract = {
            k: jax.tree_util.tree_map(sds, v) for k, v in ts_meta.items()
        }
        if callable(shardings):
            shardings = shardings(abstract["actor_params"])
        abstract["actor_params"] = jax.tree_util.tree_map(
            sds, ts_meta["actor_params"], shardings
        )
        return abstract

    def refresh(self) -> None:
        """Re-read the checkpoint directory. The manager caches its
        step list at construction and only updates it through its OWN
        saves — a reader polling for steps written by ANOTHER process
        (the serving hot-reload path) must refresh first or
        ``latest_epoch`` stays frozen at construction time."""
        self._mgr.reload()

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


# ------------------------------------------------------- population export


def extract_member(tree: t.Any, member: int) -> t.Any:
    """Slice one member off every leaf's leading population axis —
    a stacked population ``TrainState`` (or raw checkpoint dict)
    becomes the single-learner state of member ``member``."""
    return jax.tree_util.tree_map(lambda x: x[member], tree)


def export_member_checkpoint(
    src_directory: str | Path,
    dst_directory: str | Path,
    member: int | None = None,
    epoch: int | None = None,
) -> t.Tuple[int, int]:
    """Export ONE member of a population checkpoint as a standalone
    single-learner checkpoint — the population -> serving bridge: the
    result restores through :meth:`Checkpointer.restore_actor_params`,
    so ``serve.py`` (and its hot-reload poller) can serve the winner
    of a PBT run directly.

    ``member=None`` picks the best member by the checkpoint's recorded
    PBT return EMA (falling back to member 0 when the run kept no
    ranking). Like :meth:`restore_actor_params` this is shape-from-disk:
    no abstract tree needed, and the replay rings are never touched.
    Returns ``(member, epoch)`` actually exported.
    """
    src = Checkpointer(src_directory, save_buffer=False)
    try:
        if epoch is None:
            epoch = src.latest_epoch()
        if epoch is None:
            raise FileNotFoundError(
                f"no checkpoints under {src.directory}"
            )
        import logging as _logging

        absl_logger = _logging.getLogger("absl")
        prev_level = absl_logger.level
        absl_logger.setLevel(_logging.ERROR)
        try:
            out = src._retry(
                lambda: src._mgr.restore(
                    epoch,
                    args=ocp.args.Composite(
                        train_state=ocp.args.StandardRestore(),
                        meta=ocp.args.JsonRestore(),
                    ),
                ),
                what=f"population restore (epoch {epoch})",
            )
        finally:
            absl_logger.setLevel(prev_level)
        meta = dict(out["meta"])
        population = int(meta.get("population", 1))
        if population < 2:
            raise ValueError(
                f"checkpoint at {src.directory} epoch {epoch} is not a "
                f"population checkpoint (population={population})"
            )
        if member is None:
            ema = (meta.get("pbt") or {}).get("return_ema")
            member = int(np.argmax(ema)) if ema else 0
        if not 0 <= member < population:
            raise ValueError(
                f"member {member} out of range for population "
                f"{population}"
            )
        member_state = extract_member(out["train_state"], member)
    finally:
        src.close()

    extra = {
        k: v for k, v in meta.items()
        if k not in ("epoch", "ckpt_format", "population", "pbt")
    }
    if "config" in extra:
        from torch_actor_critic_tpu.utils.config import SACConfig

        extra["config"] = SACConfig.from_json(extra["config"]).replace(
            population=1, pbt_every=0
        ).to_json()
    extra["exported_member"] = member
    extra["source_population"] = population
    dst = Checkpointer(dst_directory, save_buffer=False)
    try:
        dst.save(epoch, member_state, extra=extra, wait=True)
    finally:
        dst.close()
    return member, epoch


def _export_member_main(argv=None):
    """CLI: ``python -m torch_actor_critic_tpu.utils.checkpoint SRC DST
    [--member I] [--epoch E]`` — export a (best-by-default) population
    member for serving (docs/SCALING.md "Population training")."""
    import argparse

    p = argparse.ArgumentParser(
        description="Export one member of a population checkpoint as a "
        "standalone single-learner checkpoint."
    )
    p.add_argument("src", help="population checkpoint directory")
    p.add_argument("dst", help="output checkpoint directory")
    p.add_argument(
        "--member", type=int, default=None,
        help="member index (default: best by PBT return EMA)",
    )
    p.add_argument("--epoch", type=int, default=None)
    args = p.parse_args(argv)
    member, epoch = export_member_checkpoint(
        args.src, args.dst, member=args.member, epoch=args.epoch
    )
    print(f"exported member {member} (epoch {epoch}) -> {args.dst}")
    return member, epoch


if __name__ == "__main__":
    _export_member_main()
