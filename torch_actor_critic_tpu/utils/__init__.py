from torch_actor_critic_tpu.utils.config import SACConfig  # noqa: F401
