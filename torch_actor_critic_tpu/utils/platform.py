"""Backend-selection hygiene for CLI entry points.

``JAX_PLATFORMS=cpu`` alone is not sufficient in environments whose
sitecustomize hooks re-register an accelerator platform after jax
import (the tunneled-TPU setup does); the config value must be
re-asserted post-import or "CPU" runs silently build the accelerator
client — and hang if its link is down. The graft/driver entry points
and the test conftest already do this; CLIs route through here.
"""

from __future__ import annotations

import os


def honor_platform_env() -> None:
    """Re-assert ``JAX_PLATFORMS`` from the environment after import.

    No-op when the var is unset or already names the active backend.
    Call before any other jax API in a CLI ``main()``.
    """
    import jax

    want = os.environ.get("JAX_PLATFORMS", "")
    if want and "axon" not in want:
        jax.config.update("jax_platforms", want)
