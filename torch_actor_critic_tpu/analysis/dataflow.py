"""Per-function def-use chains for the dataflow rule families.

PR 11's rules are *syntactic* (a call shape, a lock scope); the
donation-safety and PRNG-discipline families need to know what happens
to a VALUE after a program point — "is this buffer read after it was
donated", "is this key consumed twice without a split". This module is
the shared engine: per-function, in-lexical-order event streams
(binds/reads of a tracked name) plus a conservative reachability
predicate between two events that understands the two control-flow
facts straight-line order gets wrong:

* **branch exclusivity** — events in the two arms of one ``if`` (or
  ``try``/``except``) never execute in sequence, so a key split in the
  ``if`` arm does not conflict with a split of the same key in the
  ``else`` arm;
* **early termination** — an arm that ends in ``return``/``raise``
  (/``continue``/``break``) never falls through, so an event inside it
  cannot reach an event after the ``if`` (the idiomatic
  ``if trivial: return early_result`` guard).

Everything stays within one function scope (nested ``def``/``lambda``
bodies are separate scopes, surfaced as ``closure`` events at the def
site — a closure capturing a donated buffer is exactly the "captured
afterwards" hazard). No interprocedural propagation: the rule families
stay conservative and their findings stay explainable.
"""

from __future__ import annotations

import ast
import dataclasses
import typing as t

from torch_actor_critic_tpu.analysis.walker import FileContext

__all__ = [
    "NameEvent",
    "function_events",
    "tracked_key",
    "FlowScope",
]

# Statement types that terminate an arm: control never falls through
# to the statement after the enclosing if/try.
_TERMINATORS = (ast.Return, ast.Raise, ast.Continue, ast.Break)


def tracked_key(node: ast.AST) -> str | None:
    """The dataflow name of an expression we can track: a bare name
    (``buf``) or a depth-1 attribute (``self.state``, ``obj.buffer``).
    Deeper paths (``a.b.c``) are untracked — reads through them are
    views whose aliasing we cannot reason about conservatively."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    return None


@dataclasses.dataclass
class NameEvent:
    """One occurrence of a tracked name inside a function body."""

    key: str
    node: ast.AST          # the Name/Attribute occurrence
    stmt: ast.stmt         # enclosing statement (within the function)
    kind: str              # "store" | "load"
    closure: bool          # occurs inside a nested def/lambda


def _arm_of(stmts: t.Sequence[ast.stmt], node: ast.AST, parents) -> bool:
    """Is ``node`` (or an ancestor of it) one of ``stmts``?"""
    cur: ast.AST | None = node
    while cur is not None:
        if any(cur is s for s in stmts):
            return True
        cur = parents.get(cur)
    return False


def _arm_terminates(stmts: t.Sequence[ast.stmt]) -> bool:
    """Does this arm end without falling through?"""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, _TERMINATORS):
        return True
    if isinstance(last, ast.If) and last.orelse:
        return _arm_terminates(last.body) and _arm_terminates(last.orelse)
    return False


class FlowScope:
    """Control-flow context for one function body.

    ``reaches(a, b)`` answers: can control flow from event/node ``a``
    to the *lexically later* event/node ``b`` in one pass through the
    function? False when they sit in mutually exclusive branch arms, or
    when every path from ``a`` terminates before ``b``'s position.
    """

    def __init__(self, ctx: FileContext, fn_node: ast.AST):
        self.ctx = ctx
        self.fn = fn_node
        self._parents = {}
        for parent in ast.walk(fn_node):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    # ------------------------------------------------------------ paths

    def _branch_path(
        self, node: ast.AST
    ) -> t.List[t.Tuple[ast.AST, str, t.Sequence[ast.stmt]]]:
        """(branch_node, arm_label, arm_stmts) for every enclosing
        if/try arm between ``node`` and the function root, outermost
        first."""
        out = []
        cur = self._parents.get(node)
        child = node
        while cur is not None and cur is not self.fn:
            if isinstance(cur, ast.If):
                if _arm_of(cur.body, child, self._parents):
                    out.append((cur, "body", cur.body))
                elif _arm_of(cur.orelse, child, self._parents):
                    out.append((cur, "orelse", cur.orelse))
            elif isinstance(cur, ast.Try):
                for label in ("body", "orelse", "finalbody"):
                    if _arm_of(getattr(cur, label), child, self._parents):
                        out.append((cur, label, getattr(cur, label)))
                        break
                else:
                    for h in cur.handlers:
                        if _arm_of(h.body, child, self._parents):
                            out.append((cur, f"handler:{id(h)}", h.body))
                            break
            child = cur
            cur = self._parents.get(cur)
        out.reverse()
        return out

    def statement_of(self, node: ast.AST) -> ast.stmt | None:
        cur: ast.AST | None = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self._parents.get(cur)
        return t.cast("ast.stmt | None", cur)

    def loops_enclosing(self, node: ast.AST) -> t.List[ast.AST]:
        """For/While loops between ``node`` and the function root,
        innermost first (``for``'s iter expression is evaluated once
        and is not part of the body)."""
        out = []
        cur = self._parents.get(node)
        child = node
        while cur is not None and cur is not self.fn:
            if isinstance(cur, (ast.For, ast.While)) and not (
                isinstance(cur, ast.For) and _arm_of(
                    [cur.iter], child, self._parents  # type: ignore[list-item]
                )
            ):
                out.append(cur)
            child = cur
            cur = self._parents.get(cur)
        return out

    # ---------------------------------------------------------- reaches

    def reaches(self, a: ast.AST, b: ast.AST) -> bool:
        """Can control pass from ``a`` to the lexically later ``b``?"""
        pa = self._branch_path(a)
        pb = self._branch_path(b)
        ib = {id(n): (arm, stmts) for n, arm, stmts in pb}
        for branch, arm, stmts in pa:
            hit = ib.get(id(branch))
            if hit is not None:
                if hit[0] != arm:
                    return False  # sibling arms: mutually exclusive
                continue
            # a's arm does not contain b: control must fall out of the
            # arm to reach b; a terminating arm never does. (A plain
            # `if` with a terminating body still reaches code after it
            # via the implicit else — but only for events NOT inside
            # the body, and a IS inside it.)
            if _arm_terminates(stmts):
                return False
        return True


def _in_closure(parents, fn_node: ast.AST, node: ast.AST) -> bool:
    cur = parents.get(node)
    while cur is not None and cur is not fn_node:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return True
        cur = parents.get(cur)
    return False


def function_events(
    scope: FlowScope, keys: t.Collection[str] | None = None
) -> t.List[NameEvent]:
    """Every bind/read of tracked names in the function, in lexical
    order. ``keys`` filters to a name set (None = all tracked names).
    Parameter bindings are emitted as stores at the ``def`` line."""
    fn = scope.fn
    events: t.List[NameEvent] = []
    args = getattr(fn, "args", None)
    if args is not None:
        all_args = (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        )
        for a in all_args:
            if keys is None or a.arg in keys:
                events.append(NameEvent(
                    a.arg, a, t.cast(ast.stmt, fn), "store", False
                ))
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            if not isinstance(node.value, ast.Name):
                continue
            key = f"{node.value.id}.{node.attr}"
        elif isinstance(node, ast.Name):
            key = node.id
        else:
            continue
        if keys is not None and key not in keys:
            continue
        if isinstance(node, ast.Name):
            parent = scope._parents.get(node)
            if isinstance(parent, ast.Attribute) and parent.value is node:
                # `buffer.size` surfaces BOTH as the depth-1 attribute
                # event and as a LOAD of `buffer` — reading any
                # attribute of a tracked value reads the value (what
                # use-after-donation must see). Skip only `self`
                # receivers (`self.x` is tracked as the attribute).
                if node.id == "self":
                    continue
                stmt = scope.statement_of(node)
                if stmt is None:
                    continue
                events.append(NameEvent(
                    key, node, stmt, "load",
                    _in_closure(scope._parents, fn, node),
                ))
                continue
        ctx_ = getattr(node, "ctx", None)
        kind = "store" if isinstance(ctx_, (ast.Store, ast.Del)) else "load"
        stmt = scope.statement_of(node)
        if stmt is None:
            continue
        events.append(NameEvent(
            key, node, stmt, kind,
            _in_closure(scope._parents, fn, node),
        ))
    # Within ONE statement, loads order before stores: Python evaluates
    # the RHS first, so `key, sub = split(key)` reads the old key and
    # THEN rebinds it — lexical column order would get that backwards.
    events.sort(key=lambda e: (
        getattr(e.stmt, "lineno", 0), getattr(e.stmt, "col_offset", 0),
        e.kind == "store",
        getattr(e.node, "lineno", 0), getattr(e.node, "col_offset", 0),
    ))
    return events
