"""Traced-code reachability: which functions run under a jax trace.

The jit-hygiene family only makes sense inside code that is traced —
host code is free to call ``time.perf_counter`` or ``.item()``. This
module computes the traced set:

* **Roots** — functions passed to a jit/scan/vmap-style wrapper
  (``jax.jit(f)``, ``jax.lax.scan(body, ...)``, ``@jax.jit``,
  ``functools.partial(jax.jit, ...)`` decorators, Pallas kernels), in
  any file. Lambdas passed to wrappers are roots too.
* **Seeds** — the walk is anchored on the CostRegistry/watchdog source
  names (``train/update_burst``, ``train/ondevice_epoch``,
  ``train/population_epoch``, ``train/scenario_epoch``,
  ``serve/forward``): the builders that
  register those programs are listed in :data:`ENTRY_POINTS`, and the
  pass verifies each one still exists and still constructs a jit root
  — a renamed builder raises ``stale-entry-point`` instead of the walk
  silently going blind (the table is checked, never trusted).
* **Closure** — call edges out of traced functions: plain local calls,
  ``self.method``, package-internal ``module.func`` via the import
  table, and a bounded last-resort heuristic for ``obj.method`` calls
  (every package class defining that method, when at most 3 do and the
  candidate contains no overt host-side constructs — low-confidence
  edges buy recall into the algorithm layer without tainting host
  drivers).

Functions passed to host-callback escapes (``jax.pure_callback``,
``jax.debug.callback``, ``io_callback``) are explicitly *host* code
and excluded from the traced set.
"""

from __future__ import annotations

import ast
import typing as t

from torch_actor_critic_tpu.analysis.walker import (
    FileContext,
    Finding,
    FunctionInfo,
    dotted_name,
)

__all__ = ["Project", "ENTRY_POINTS", "JIT_WRAPPERS"]

PACKAGE = "torch_actor_critic_tpu"

# Wrapper call names whose function-valued arguments are traced.
# Matched against the full dotted callee name and its last two
# segments (``jax.lax.scan`` and ``lax.scan`` both count).
JIT_WRAPPERS: t.FrozenSet[str] = frozenset({
    "jax.jit", "jit", "pjit", "jax.pmap", "pmap", "jax.vmap", "vmap",
    "jax.lax.scan", "lax.scan", "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop", "jax.lax.cond", "lax.cond",
    "jax.lax.switch", "lax.switch", "jax.lax.map", "lax.map",
    "jax.lax.associative_scan", "lax.associative_scan",
    "jax.checkpoint", "jax.remat", "jax.grad", "jax.value_and_grad",
    "jax.custom_vjp", "jax.custom_jvp",
    "shard_map", "manual_shard_map", "jax.shard_map",
    "pl.pallas_call", "pallas_call", "pltpu.pallas_call",
})

# Host-callback escapes: their function argument runs on the HOST even
# though the call site is traced code.
CALLBACK_WRAPPERS: t.FrozenSet[str] = frozenset({
    "jax.pure_callback", "pure_callback",
    "jax.debug.callback", "debug.callback",
    "jax.experimental.io_callback", "io_callback",
})

# CostRegistry/watchdog source name -> (path suffix, builder qualname).
# The builder is the host function whose body constructs the jit
# program registered under that name; the nested functions it hands to
# a wrapper are the walk's seeds. Verified every run (stale-entry-point).
ENTRY_POINTS: t.Dict[str, t.Tuple[str, str]] = {
    "train/update_burst": ("parallel/dp.py", "DataParallelSAC._build_burst"),
    "train/ondevice_epoch": ("sac/ondevice.py", "OnDeviceLoop._build_epoch"),
    "train/population_epoch": (
        "sac/ondevice.py", "PopulationOnDeviceLoop._build_epoch",
    ),
    "train/scenario_epoch": (
        "scenarios/loop.py", "ScenarioOnDeviceLoop._build_epoch",
    ),
    # The population burst builds its jit inline in the dispatch
    # method (no separate _build_*): the method IS the builder.
    "train/population_burst": (
        "parallel/population.py", "PopulationLearner.update_burst",
    ),
    "replay/prefetch_push": (
        "replay/prefetch.py", "RefillPrefetcher._build_push",
    ),
    "train/offline_burst": (
        "replay/offline.py", "OfflineLearner._build_burst",
    ),
    "serve/forward": ("serve/engine.py", "PolicyEngine._build_forwards"),
    "serve/sharded_forward": (
        "serve/sharded.py", "ShardedPolicyEngine._build_forwards",
    ),
}

# Method names too generic for the cross-class fallback resolution.
_NOISE_METHODS = frozenset({
    "append", "extend", "get", "pop", "popleft", "items", "keys",
    "values", "update", "copy", "clear", "add", "remove", "join",
    "read", "write", "close", "record", "result", "put", "send",
    "recv", "start", "stop", "item", "mean", "max", "min", "sum",
    "reshape", "astype", "replace", "apply", "init", "split", "view",
    "snapshot", "format",
})

# Calls that mark a function as overtly host-side; a low-confidence
# (heuristic) edge into such a function is dropped.
_HOST_MARKERS = frozenset({
    "jax.jit", "jit", "time.perf_counter", "time.time",
    "time.monotonic", "time.sleep", "print", "open", "get_watchdog",
    "jax.device_put", "logger.info", "logger.warning", "logger.debug",
    "logger.error",
})


def _call_names(node: ast.AST) -> t.Set[str]:
    out: t.Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func)
            if name:
                out.add(name)
    return out


def _is_wrapper(name: str | None, table: t.FrozenSet[str]) -> bool:
    if not name:
        return False
    if name in table:
        return True
    parts = name.split(".")
    return len(parts) >= 2 and ".".join(parts[-2:]) in table


class _ModuleIndex:
    """Per-file resolution tables."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.by_qualname: t.Dict[str, FunctionInfo] = {
            f.qualname: f for f in ctx.functions
        }
        self.by_last: t.Dict[str, t.List[FunctionInfo]] = {}
        for f in ctx.functions:
            self.by_last.setdefault(f.qualname.rsplit(".", 1)[-1], []).append(f)
        self.qual_of: t.Dict[ast.AST, str] = {
            f.node: f.qualname for f in ctx.functions
        }
        # alias -> package-internal module path ("a/b.py"), and
        # imported symbol -> (module path, symbol name).
        self.module_aliases: t.Dict[str, str] = {}
        self.symbol_imports: t.Dict[str, t.Tuple[str, str]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith(PACKAGE):
                        bound = alias.asname or alias.name.split(".")[0]
                        self.module_aliases[bound] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                if not node.module.startswith(PACKAGE):
                    continue
                for alias in node.names:
                    full = f"{node.module}.{alias.name}"
                    bound = alias.asname or alias.name
                    # `from pkg.x import y` binds y as either module
                    # pkg/x/y.py or symbol y in pkg/x.py; record both
                    # candidates, resolution tries module first.
                    self.module_aliases.setdefault(bound, full)
                    self.symbol_imports[bound] = (node.module, alias.name)


class Project:
    """All parsed files plus the project-level traced-set analysis."""

    def __init__(self, files: t.Sequence[FileContext]):
        self.files = list(files)
        self.by_path: t.Dict[str, FileContext] = {f.path: f for f in self.files}
        self.indexes: t.Dict[str, _ModuleIndex] = {
            f.path: _ModuleIndex(f) for f in self.files
        }
        # module dotted name -> path, for import resolution.
        self.module_paths: t.Dict[str, str] = {}
        for path in self.by_path:
            mod = path[:-3].replace("/", ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            self.module_paths[mod] = path
        self.method_index: t.Dict[str, t.List[t.Tuple[str, FunctionInfo]]] = {}
        for path, ctx in self.by_path.items():
            for f in ctx.functions:
                if f.class_name and f.qualname == f"{f.class_name}.{f.node.name}":
                    self.method_index.setdefault(f.node.name, []).append(
                        (path, f)
                    )
        self._traced: t.Dict[t.Tuple[str, str], FunctionInfo] | None = None
        self._host_callbacks: t.Set[t.Tuple[str, str]] = set()

    # --------------------------------------------------------------- roots

    def _resolve_plain(
        self, path: str, site: ast.AST, name: str
    ) -> t.List[t.Tuple[str, FunctionInfo]]:
        """Scope-aware resolution of a bare-name function reference:
        a sibling/enclosing-scope nested def wins over module level;
        class methods never match a bare name (they need ``self.``);
        a ``from pkg.x import f`` symbol resolves cross-module."""
        idx = self.indexes[path]
        ctx = self.by_path[path]
        cands = idx.by_last.get(name, [])
        enclosing: t.List[str] = []
        for anc in ctx.ancestors(site):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = idx.qual_of.get(anc)
                if q:
                    enclosing.append(q)
        for q in enclosing:
            hits = [f for f in cands if f.qualname == f"{q}.{name}"]
            if hits:
                return [(path, f) for f in hits]
        hits = [f for f in cands if f.qualname == name]
        if hits:
            return [(path, f) for f in hits]
        sym = idx.symbol_imports.get(name)
        if sym is not None:
            mod, symbol = sym
            target = self.module_paths.get(f"{mod}.{symbol}")
            if target is None:
                target = self.module_paths.get(mod)
                if target is not None:
                    tf = self.indexes[target].by_qualname.get(symbol)
                    if tf is not None:
                        return [(target, tf)]
            return []
        return []

    def _function_for_arg(
        self, path: str, arg: ast.AST, site: ast.AST | None = None
    ) -> t.List[t.Tuple[str, FunctionInfo]]:
        """Resolve a wrapper's function-valued argument."""
        if isinstance(arg, ast.Call):
            name = dotted_name(arg.func)
            if name and name.rsplit(".", 1)[-1] in ("partial", "wraps"):
                if arg.args:
                    return self._function_for_arg(path, arg.args[0], site)
            if _is_wrapper(name, JIT_WRAPPERS) and arg.args:
                # nested wrappers: jax.jit(jax.vmap(f))
                return self._function_for_arg(path, arg.args[0], site)
            return []
        name = dotted_name(arg)
        if name is None:
            return []
        if "." not in name:
            return self._resolve_plain(path, site if site is not None else arg, name)
        if name.startswith("self."):
            meth = name.split(".", 1)[1]
            return self._resolve_self(path, arg, meth)
        return self._resolve_dotted(path, name)

    def _resolve_dotted(
        self, path: str, name: str
    ) -> t.List[t.Tuple[str, FunctionInfo]]:
        """``alias.f`` / ``alias.sub.f`` / ``ClassName.m`` through the
        file's import table and class index."""
        idx = self.indexes[path]
        parts = name.split(".")
        hit = idx.by_qualname.get(name)
        if hit is not None:
            return [(path, hit)]
        head, meth = parts[0], parts[-1]
        mod = idx.module_aliases.get(head)
        if mod is not None:
            dotted = ".".join([mod] + parts[1:-1])
            target = self.module_paths.get(dotted)
            if target is not None:
                tf = self.indexes[target].by_qualname.get(meth)
                if tf is not None:
                    return [(target, tf)]
        if head in idx.symbol_imports and len(parts) == 2:
            mod_name, sym = idx.symbol_imports[head]
            target = self.module_paths.get(f"{mod_name}.{sym}")
            if target is not None:
                tf = self.indexes[target].by_qualname.get(meth)
                if tf is not None:
                    return [(target, tf)]
        return []

    def _roots_in_file(self, path: str) -> t.List[t.Tuple[str, FunctionInfo]]:
        ctx = self.by_path[path]
        roots: t.List[t.Tuple[str, FunctionInfo]] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    name = dotted_name(dec)
                    if isinstance(dec, ast.Call):
                        name = dotted_name(dec.func)
                        if name and name.rsplit(".", 1)[-1] == "partial":
                            name = dotted_name(dec.args[0]) if dec.args else None
                    if _is_wrapper(name, JIT_WRAPPERS):
                        info = next(
                            (f for f in ctx.functions if f.node is node), None
                        )
                        if info:
                            roots.append((path, info))
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if _is_wrapper(callee, CALLBACK_WRAPPERS):
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    for loc in self._function_for_arg(path, arg, node):
                        self._host_callbacks.add((loc[0], loc[1].qualname))
                continue
            if not _is_wrapper(callee, JIT_WRAPPERS):
                continue
            cands = list(node.args) + [
                k.value for k in node.keywords
                if k.arg in ("f", "fun", "body_fun", "cond_fun", "kernel")
            ]
            for arg in cands:
                if isinstance(arg, ast.Lambda):
                    # Treat the lambda body as traced by attaching a
                    # synthetic FunctionInfo; rules walk `.node`.
                    roots.append((path, FunctionInfo(
                        f"<lambda:{arg.lineno}>", arg, None
                    )))
                    continue
                roots.extend(self._function_for_arg(path, arg, node))
        return roots

    # ------------------------------------------------------------ resolve

    def _resolve_self(
        self, path: str, node: ast.AST, meth: str
    ) -> t.List[t.Tuple[str, FunctionInfo]]:
        return self._resolve_self2(path, node, meth)[0]

    def _resolve_self2(
        self, path: str, node: ast.AST, meth: str
    ) -> t.Tuple[t.List[t.Tuple[str, FunctionInfo]], bool]:
        """Resolve ``self.meth``; the bool says whether the hit is
        exact (own class) or a cross-class heuristic fallback."""
        ctx = self.by_path[path]
        cls = None
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                cls = anc.name
                break
        if cls is not None:
            hit = self.indexes[path].by_qualname.get(f"{cls}.{meth}")
            if hit is not None:
                return [(path, hit)], True
        return self._resolve_heuristic(meth), False

    @staticmethod
    def _looks_host_side(fn: FunctionInfo) -> bool:
        """Overtly host-side: constructs jits (directly or via a
        ``_build*`` helper), takes wall-clock readings, places
        buffers, logs. Used to prune LOW-CONFIDENCE (heuristic)
        reachability — exact edges are never pruned."""
        names = _call_names(fn.node)
        if names & _HOST_MARKERS:
            return True
        return any(
            n.rsplit(".", 1)[-1].startswith("_build") for n in names
        )

    def _resolve_heuristic(
        self, meth: str
    ) -> t.List[t.Tuple[str, FunctionInfo]]:
        if meth.startswith("__") or meth in _NOISE_METHODS:
            return []
        cands = self.method_index.get(meth, [])
        if not 1 <= len(cands) <= 5:
            return []
        return [
            (path, f) for path, f in cands if not self._looks_host_side(f)
        ]

    def _callees(
        self, path: str, fn: FunctionInfo
    ) -> t.Tuple[
        t.List[t.Tuple[str, FunctionInfo]],
        t.List[t.Tuple[str, FunctionInfo]],
    ]:
        """(exact_edges, heuristic_edges) out of ``fn``."""
        exact: t.List[t.Tuple[str, FunctionInfo]] = []
        heur: t.List[t.Tuple[str, FunctionInfo]] = []
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is None or _is_wrapper(name, JIT_WRAPPERS):
                continue
            if "." not in name:
                exact.extend(self._resolve_plain(path, node, name))
                continue
            parts = name.split(".")
            if parts[0] == "self" and len(parts) == 2:
                hits, confident = self._resolve_self2(path, node, parts[1])
                (exact if confident else heur).extend(hits)
                continue
            resolved = self._resolve_dotted(path, name)
            if resolved:
                exact.extend(resolved)
                continue
            heur.extend(self._resolve_heuristic(parts[-1]))
        return exact, heur

    # -------------------------------------------------------------- traced

    def traced(self) -> t.Dict[t.Tuple[str, str], FunctionInfo]:
        """(path, qualname) -> FunctionInfo for every traced function.

        Two-tier closure: exact edges (same-scope names, own-class
        ``self.method``, import-resolved ``module.func``) propagate
        unconditionally from the jit roots; heuristic (cross-class
        method-name) edges only admit functions that don't look
        host-side, and everything downstream of a heuristic edge stays
        under that filter — one low-confidence hop must not taint a
        whole host subsystem as traced."""
        if self._traced is not None:
            return self._traced
        seen: t.Dict[t.Tuple[str, str], FunctionInfo] = {}
        confident: t.Set[t.Tuple[str, str]] = set()
        work: t.List[t.Tuple[str, FunctionInfo, bool]] = []
        for path in self.by_path:
            work.extend((p, f, True) for p, f in self._roots_in_file(path))
        while work:
            path, fn, exact = work.pop()
            key = (path, fn.qualname)
            if key in self._host_callbacks:
                continue
            if key in seen and (not exact or key in confident):
                continue
            if not exact and self._looks_host_side(fn):
                continue
            seen[key] = fn
            if exact:
                confident.add(key)
            if isinstance(fn.node, ast.Lambda):
                continue
            exact_edges, heur_edges = self._callees(path, fn)
            work.extend((p, f, exact) for p, f in exact_edges)
            work.extend((p, f, False) for p, f in heur_edges)
        self._traced = seen
        return seen

    def is_traced_file(self, path: str) -> bool:
        return any(p == path for p, _ in self.traced())

    # --------------------------------------------------------- entry seeds

    def entry_point_findings(self) -> t.List[Finding]:
        """Verify the checked seed table: every CostRegistry source
        name must still map to an existing builder that constructs at
        least one jit root."""
        out: t.List[Finding] = []
        if not any(
            p.endswith(f"{PACKAGE}/__init__.py") for p in self.by_path
        ):
            # The seed table only applies to whole-package runs; a
            # partial run (fixtures, a single file) can't tell a
            # renamed builder from an un-linted one.
            return out
        traced = self.traced()
        for cost_name, (suffix, builder) in ENTRY_POINTS.items():
            path = next(
                (p for p in self.by_path if p.endswith(suffix)), None
            )
            if path is None:
                out.append(Finding(
                    "stale-entry-point", suffix, 1, 0,
                    f"entry point {cost_name!r}: file {suffix!r} not found",
                    "update analysis/reachability.py ENTRY_POINTS",
                ))
                continue
            ctx = self.by_path[path]
            fn = next(
                (f for f in ctx.functions if f.qualname == builder), None
            )
            if fn is None:
                out.append(Finding(
                    "stale-entry-point", path, 1, 0,
                    f"entry point {cost_name!r}: builder {builder!r} "
                    "no longer exists",
                    "update analysis/reachability.py ENTRY_POINTS to the "
                    "renamed builder",
                ))
                continue
            lo = fn.node.lineno
            hi = max(
                (n.end_lineno or lo) for n in ast.walk(fn.node)
                if hasattr(n, "end_lineno") and n.end_lineno
            )
            seeded = any(
                p == path and lo <= info.node.lineno <= hi
                for (p, _), info in traced.items()
            )
            if not seeded:
                out.append(Finding(
                    "stale-entry-point", path, fn.node.lineno, 0,
                    f"entry point {cost_name!r}: builder {builder!r} no "
                    "longer constructs a jit program the walk can seed from",
                    "check that the builder still passes a function to a "
                    "jit/scan wrapper, or update ENTRY_POINTS",
                ))
        return out
