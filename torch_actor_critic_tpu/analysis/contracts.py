"""contract-drift rules: the jit entry-point wiring, checked as one table.

Every jit entry point in this codebase owes three things beyond the
program itself: a **recompilation-watchdog scope** (so a steady-state
compile is attributed and flagged, PR 4), a **CostRegistry
registration** (so roofline/MFU accounting sees it, PR 7), and — for
sharded programs — **shardings derived from the shared
``param_specs``/``fsdp_spec`` planners** (so the jit's at-rest layout
cannot drift from what ``init_state`` placed, PR 8/14). Until now each
PR wired these by hand and only a human reviewer noticed a missing
piece; :data:`ENTRY_POINT_CONTRACTS` makes the wiring a checked table:

* ``stale-contract`` — the table and ``reachability.ENTRY_POINTS``
  must cover exactly the same identities, and each row's cost-name
  literal must still be bound where the row says (a ``*_cost_name`` /
  ``TRACE_PREFIX`` attribute, or a direct literal). A renamed
  identity fails the run instead of silently losing its telemetry.
* ``missing-watchdog-scope`` — the dispatch site the row names no
  longer wraps the call in ``watchdog.source(...)`` with that
  identity.
* ``missing-cost-registration`` — the registering function the row
  names no longer calls ``register_jit``/``register`` with that
  identity.
* ``incoherent-sharding`` — a sharded entry's builder no longer
  derives its shardings from the shared planners
  (``param_specs``/``fsdp_spec``/``named_param_shardings`` — directly
  or through one same-class helper hop).
* ``stale-bundle-manifest`` — every row must carry an **explicit**
  ``bundleable=`` literal (the AOT warm-start manifest,
  ``aot/manifest.py``, is derived from this column): a new entry
  point cannot ship without declaring whether it is AOT-bundled.

All checks run on whole-package runs only (a fixture or single-file
run cannot tell missing wiring from un-linted wiring).
"""

from __future__ import annotations

import ast
import typing as t

from torch_actor_critic_tpu.analysis.reachability import (
    ENTRY_POINTS,
    Project,
)
from torch_actor_critic_tpu.analysis.walker import (
    FileContext,
    Finding,
)

__all__ = ["check", "ENTRY_POINT_CONTRACTS"]

FAMILY = "contract-drift"

# Names that count as deriving shardings from the shared planners.
_SHARDING_PLANNERS = frozenset({
    "param_specs", "fsdp_spec", "named_param_shardings",
})


class ContractRow(t.NamedTuple):
    name_file: str                      # file binding the identity
    name_attr: str | None               # attr assigned the literal
    #                                     (None: literal used directly)
    scope_file: str                     # file with the .source(...) call
    scope_ref: str | None               # attr the source arg reads
    #                                     (None: the literal itself)
    register_fn: t.Tuple[str, str]      # (file, qualname) registering
    register_ref: str | None            # attr the registration reads
    #                                     (None: the literal itself)
    sharded_builder: t.Tuple[str, str] | None  # (file, qualname) whose
    #                                     shardings must come from the
    #                                     shared planners
    bundleable: bool                    # AOT warm-start manifest
    #                                     column: True iff the program
    #                                     is serialized into the
    #                                     warm_start bundle
    #                                     (aot/manifest.py reads this;
    #                                     stale-bundle-manifest requires
    #                                     it be an explicit literal)


# The checked wiring table, one row per reachability.ENTRY_POINTS
# identity (key sets must match — stale-contract otherwise).
ENTRY_POINT_CONTRACTS: t.Dict[str, ContractRow] = {
    "train/update_burst": ContractRow(
        name_file="parallel/dp.py", name_attr="burst_cost_name",
        scope_file="sac/trainer.py", scope_ref=None,
        register_fn=("sac/trainer.py", "Trainer._note_epoch_cost"),
        register_ref="burst_cost_name",
        sharded_builder=("parallel/dp.py", "DataParallelSAC._build_burst"),
        # Train-plane programs ride the shared persistent compilation
        # cache instead of the serialized bundle (their shapes depend
        # on run config, not the fixed serve bucket ladder).
        bundleable=False,
    ),
    "train/population_burst": ContractRow(
        name_file="parallel/population.py", name_attr="burst_cost_name",
        scope_file="parallel/population.py", scope_ref=None,
        register_fn=("sac/trainer.py", "Trainer._note_epoch_cost"),
        register_ref="burst_cost_name",
        sharded_builder=None,
        bundleable=False,
    ),
    "train/ondevice_epoch": ContractRow(
        name_file="sac/ondevice.py", name_attr="epoch_cost_name",
        scope_file="sac/ondevice.py", scope_ref="epoch_cost_name",
        register_fn=("sac/ondevice.py", "_note_epoch_cost"),
        register_ref="epoch_cost_name",
        sharded_builder=None,
        bundleable=False,
    ),
    "train/population_epoch": ContractRow(
        name_file="sac/ondevice.py", name_attr="epoch_cost_name",
        scope_file="sac/ondevice.py", scope_ref="epoch_cost_name",
        register_fn=("sac/ondevice.py", "_note_epoch_cost"),
        register_ref="epoch_cost_name",
        sharded_builder=None,
        bundleable=False,
    ),
    "train/scenario_epoch": ContractRow(
        name_file="scenarios/loop.py", name_attr="epoch_cost_name",
        scope_file="sac/ondevice.py", scope_ref="epoch_cost_name",
        register_fn=("sac/ondevice.py", "_note_epoch_cost"),
        register_ref="epoch_cost_name",
        sharded_builder=None,
        bundleable=False,
    ),
    "replay/prefetch_push": ContractRow(
        name_file="replay/prefetch.py", name_attr="push_cost_name",
        scope_file="replay/prefetch.py", scope_ref="push_cost_name",
        register_fn=("replay/prefetch.py", "RefillPrefetcher.maybe_register_cost"),
        register_ref="push_cost_name",
        sharded_builder=None,
        bundleable=False,
    ),
    "train/offline_burst": ContractRow(
        name_file="replay/offline.py", name_attr="burst_cost_name",
        scope_file="replay/offline.py", scope_ref="burst_cost_name",
        register_fn=("replay/offline.py", "OfflineLearner.maybe_register_cost"),
        register_ref="burst_cost_name",
        sharded_builder=None,
        bundleable=False,
    ),
    "serve/forward": ContractRow(
        name_file="serve/engine.py", name_attr="TRACE_PREFIX",
        scope_file="serve/engine.py", scope_ref="_trace_names",
        register_fn=("serve/engine.py", "PolicyEngine.warmup"),
        register_ref="_trace_names",
        sharded_builder=None,
        # The single-device serve program is exactly what a fresh
        # worker jit-dispatches — the bundle's raison d'être.
        bundleable=True,
    ),
    "serve/sharded_forward": ContractRow(
        name_file="serve/sharded.py", name_attr="TRACE_PREFIX",
        scope_file="serve/engine.py", scope_ref="_trace_names",
        register_fn=("serve/engine.py", "PolicyEngine.warmup"),
        register_ref="_trace_names",
        sharded_builder=(
            "serve/sharded.py", "ShardedPolicyEngine._build_forwards",
        ),
        # Mesh-shaped: the executable is only valid for one concrete
        # sub-mesh carving, so it is honestly NOT bundled — sharded
        # workers ride the persistent cache.
        bundleable=False,
    ),
}


def _find(project: Project, suffix: str) -> FileContext | None:
    path = next((p for p in project.by_path if p.endswith(suffix)), None)
    return project.by_path.get(path) if path else None


def _binds_literal(ctx: FileContext, attr: str, literal: str) -> bool:
    """Is ``<attr> = "<literal>"`` assigned anywhere in the file (a
    class-level identity attribute)?"""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (
            isinstance(node.value, ast.Constant)
            and node.value.value == literal
        ):
            continue
        for target in node.targets:
            name = target.attr if isinstance(target, ast.Attribute) else (
                target.id if isinstance(target, ast.Name) else None
            )
            if name == attr:
                return True
    return False


def _mentions_ref(node: ast.AST, ref: str | None, literal: str) -> bool:
    """Does the expression read the identity — the attr ``ref``
    (``self.epoch_cost_name``, ``self._trace_names[b]``) or the
    literal itself?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and sub.value == literal:
            return True
        if ref is not None and (
            (isinstance(sub, ast.Attribute) and sub.attr == ref)
            or (isinstance(sub, ast.Name) and sub.id == ref)
        ):
            return True
    return False


def _has_source_scope(
    ctx: FileContext, ref: str | None, literal: str
) -> bool:
    """A ``<x>.source(ARG)`` call whose ARG reads the identity."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        # `get_watchdog().source(...)` has a Call receiver, which
        # dotted_name cannot flatten — match on the attribute name.
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "source"
        ):
            continue
        if node.args and _mentions_ref(node.args[0], ref, literal):
            return True
    return False


def _has_registration(
    ctx: FileContext, qualname: str, ref: str | None, literal: str
) -> t.Tuple[bool, bool]:
    """(fn_exists, registers): the named function exists and calls
    ``register_jit``/``register`` with an identity-reading name arg."""
    fn = next((f for f in ctx.functions if f.qualname == qualname), None)
    if fn is None:
        return False, False
    for node in ast.walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        # `get_cost_registry().register_jit(...)` has a Call receiver;
        # match on the attribute name like _has_source_scope.
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("register_jit", "register")
        ):
            continue
        if node.args and _mentions_ref(node.args[0], ref, literal):
            return True, True
        # The name may be hoisted into a local (`name = self.dp
        # .burst_cost_name; registry.register_jit(name, ...)`):
        # accept when the registering function reads the ref anywhere.
        if _mentions_ref(fn.node, ref, literal):
            return True, True
    return True, False


def _builder_uses_planners(ctx: FileContext, qualname: str) -> bool:
    """Does the builder reference a shared sharding planner — directly
    or through one same-class helper method hop?"""
    fn = next((f for f in ctx.functions if f.qualname == qualname), None)
    if fn is None:
        return False

    def refs(node: ast.AST) -> t.Set[str]:
        out: t.Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                out.add(sub.attr)
            elif isinstance(sub, ast.Name):
                out.add(sub.id)
        return out

    direct = refs(fn.node)
    if direct & _SHARDING_PLANNERS:
        return True
    # The builder may consume pre-planned layouts through instance
    # state (`self._replicated`, the at-rest placement from
    # place_params): accept a planner reference anywhere in the
    # builder's class — the drift being checked is the CLASS deriving
    # layouts ad-hoc instead of from the shared planners.
    cls = qualname.rsplit(".", 1)[0] if "." in qualname else None
    if cls is None:
        return False
    for other in ctx.functions:
        if other.qualname.startswith(f"{cls}."):
            if refs(other.node) & _SHARDING_PLANNERS:
                return True
    return False


def _check_bundle_manifest(project: Project) -> t.List[Finding]:
    """stale-bundle-manifest: every ContractRow(...) literal in this
    file must pass ``bundleable=`` as an explicit keyword with a bool
    constant. The AOT manifest (aot/manifest.py) is derived from that
    column at import time; a row relying on a positional slip or a
    computed value would let an entry point ship without an auditable
    bundleability decision."""
    findings: t.List[Finding] = []
    ctx = _find(project, "analysis/contracts.py")
    if ctx is None:
        return findings
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        name = callee.attr if isinstance(callee, ast.Attribute) else (
            callee.id if isinstance(callee, ast.Name) else None
        )
        if name != "ContractRow":
            continue
        kw = next(
            (k for k in node.keywords if k.arg == "bundleable"), None
        )
        if kw is not None and (
            isinstance(kw.value, ast.Constant)
            and isinstance(kw.value.value, bool)
        ):
            continue
        findings.append(Finding(
            "stale-bundle-manifest", "analysis/contracts.py",
            node.lineno, node.col_offset,
            "ENTRY_POINT_CONTRACTS row without an explicit "
            "`bundleable=True/False` literal — the AOT warm-start "
            "manifest cannot tell whether this entry point is "
            "pre-compiled into the bundle",
            "add `bundleable=` to the ContractRow with a literal bool "
            "(True only if aot/bundle.py serializes the program; see "
            "docs/SERVING.md 'Cold start & warm-start bundles')",
        ))
    return findings


def check(project: Project) -> t.List[Finding]:
    findings: t.List[Finding] = []
    if not any(
        p.endswith("torch_actor_critic_tpu/__init__.py")
        for p in project.by_path
    ):
        return findings
    findings.extend(_check_bundle_manifest(project))
    table_keys = set(ENTRY_POINT_CONTRACTS)
    entry_keys = set(ENTRY_POINTS)
    for missing in sorted(entry_keys - table_keys):
        findings.append(Finding(
            "stale-contract", "analysis/contracts.py", 1, 0,
            f"entry point {missing!r} has no ENTRY_POINT_CONTRACTS row "
            "(watchdog/cost/sharding wiring unchecked)",
            "add the row to analysis/contracts.py — the table replaces "
            "per-PR ad-hoc wiring",
        ))
    for extra in sorted(table_keys - entry_keys):
        findings.append(Finding(
            "stale-contract", "analysis/contracts.py", 1, 0,
            f"ENTRY_POINT_CONTRACTS row {extra!r} matches no "
            "reachability.ENTRY_POINTS identity; the entry it wired "
            "is gone",
            "remove the row, or restore the ENTRY_POINTS identity it "
            "describes",
        ))
    for cost_name in sorted(table_keys & entry_keys):
        row = ENTRY_POINT_CONTRACTS[cost_name]
        # --- identity binding -----------------------------------------
        name_ctx = _find(project, row.name_file)
        if name_ctx is None or (
            row.name_attr is not None
            and not _binds_literal(name_ctx, row.name_attr, cost_name)
        ):
            findings.append(Finding(
                "stale-contract", row.name_file, 1, 0,
                f"entry point {cost_name!r}: identity is not bound as "
                f"{row.name_attr!r} in {row.name_file!r} any more",
                "update the attribute (or the ENTRY_POINT_CONTRACTS "
                "row) so the identity has exactly one source of truth",
            ))
            continue
        # --- watchdog scope -------------------------------------------
        scope_ctx = _find(project, row.scope_file)
        if scope_ctx is None or not _has_source_scope(
            scope_ctx, row.scope_ref, cost_name
        ):
            findings.append(Finding(
                "missing-watchdog-scope", row.scope_file, 1, 0,
                f"entry point {cost_name!r}: no watchdog.source(...) "
                f"scope reading the identity in {row.scope_file!r} — "
                "steady-state recompiles of this program would be "
                "unattributed",
                "wrap the dispatch in `with get_watchdog()"
                ".source(<identity>)` (see docs/OBSERVABILITY.md)",
            ))
        # --- cost registration ----------------------------------------
        reg_ctx = _find(project, row.register_fn[0])
        fn_exists, registers = (False, False) if reg_ctx is None else (
            _has_registration(
                reg_ctx, row.register_fn[1], row.register_ref, cost_name
            )
        )
        if not fn_exists or not registers:
            findings.append(Finding(
                "missing-cost-registration", row.register_fn[0], 1, 0,
                f"entry point {cost_name!r}: "
                f"{row.register_fn[1]!r} no longer registers the "
                "program's XLA cost analysis under the identity — "
                "roofline/MFU accounting goes blind for it",
                "call get_cost_registry().register_jit(<identity>, "
                "...) from the dispatch/warmup path "
                "(docs/OBSERVABILITY.md 'Cost attribution')",
            ))
        # --- sharding coherence ---------------------------------------
        if row.sharded_builder is not None:
            b_ctx = _find(project, row.sharded_builder[0])
            if b_ctx is None or not _builder_uses_planners(
                b_ctx, row.sharded_builder[1]
            ):
                findings.append(Finding(
                    "incoherent-sharding", row.sharded_builder[0], 1, 0,
                    f"entry point {cost_name!r}: builder "
                    f"{row.sharded_builder[1]!r} no longer derives its "
                    "shardings from the shared param_specs/fsdp_spec "
                    "planners — the jit layout can drift from the "
                    "at-rest placement and every burst pays a reshard",
                    "derive in_shardings/out_shardings from parallel/"
                    "sharding.py's param_specs/fsdp_spec (directly or "
                    "via the class's sharding helper)",
                ))
    return findings
