"""Shared AST infrastructure for the tac-lint pass.

One parse per file feeds every rule family: the AST itself, a parent
map (ast gives children only), a qualname index over every function
and class, the comment side-channel (``ast`` drops comments, so
suppressions and ``guarded-by`` annotations come from ``tokenize``),
and the per-line suppression table.

Suppression policy (docs/ANALYSIS.md): ``# tac-lint: disable=<rule>``
on the offending line, and every suppression MUST name at least one
known rule — a bare ``# tac-lint: disable`` (or one naming an unknown
rule) is itself a finding (``bare-suppression``), so suppressions can
never silently rot into blanket waivers.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
import typing as t

__all__ = [
    "Finding",
    "FileContext",
    "FunctionInfo",
    "dotted_name",
    "iter_file_functions",
]

# Every rule id the pass can emit, grouped by family. conventions.py /
# jit_hygiene.py / recompile.py / locks.py each own their families;
# the walker owns the suppression meta-rule. Kept in one table so the
# CLI's --list-rules and the suppression validator share a source of
# truth.
RULE_FAMILIES: t.Dict[str, t.Tuple[str, ...]] = {
    "jit-hygiene": (
        "host-sync-in-jit",
        "wallclock-in-jit",
        "host-random-in-jit",
        "stale-entry-point",
        "frame-f32-materialize",
    ),
    "recompile-risk": (
        "jit-cache-discard",
        "jit-in-loop",
        "varying-shape-arg",
        "donated-reuse",
        "shard-map-hot-path",
        "stale-allowlist",
    ),
    "lock-discipline": (
        "unlocked-guarded-access",
        "unguarded-shared-attr",
        "unknown-guard",
    ),
    "donation-safety": (
        "use-after-donation",
        "undonated-push",
        "stale-donation-table",
    ),
    "prng-discipline": (
        "key-reuse",
        "key-split-nondestructive",
        "key-loop-reuse",
    ),
    "contract-drift": (
        "missing-watchdog-scope",
        "missing-cost-registration",
        "incoherent-sharding",
        "stale-contract",
        "stale-bundle-manifest",
    ),
    "conventions": (
        "silent-exception-swallow",
        "mutable-default-arg",
        "suffix-reduction-mismatch",
    ),
    "meta": ("bare-suppression",),
}

ALL_RULES: t.FrozenSet[str] = frozenset(
    rule for rules in RULE_FAMILIES.values() for rule in rules
)


def family_of(rule: str) -> str:
    for family, rules in RULE_FAMILIES.items():
        if rule in rules:
            return family
    raise KeyError(f"unknown rule id {rule!r}")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding: ``file:line``, the rule id, what is wrong,
    and how to fix it (the hint is part of the contract — a finding
    without a next action just stalls the author)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    hint: str = ""

    def format(self) -> str:
        out = f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"
        if self.hint:
            out += f"  [fix: {self.hint}]"
        return out

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FunctionInfo:
    """One function/method with its dotted qualname
    (``Class.method.inner`` — module level is just ``name``)."""

    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    class_name: str | None  # innermost enclosing class, if any


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: t.List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_file_functions(tree: ast.Module) -> t.List[FunctionInfo]:
    out: t.List[FunctionInfo] = []

    def visit(node: ast.AST, prefix: str, cls: str | None):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append(FunctionInfo(q, child, cls))
                visit(child, q + ".", cls)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", child.name)
            else:
                visit(child, prefix, cls)

    visit(tree, "", None)
    return out


_SUPPRESS_RE = re.compile(
    r"#\s*tac-lint:\s*disable\s*(?:=\s*(?P<rules>[A-Za-z0-9_\-, ]*))?"
)
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>[A-Za-z_][A-Za-z0-9_]*)")


class FileContext:
    """Everything the rules need about one file, parsed once."""

    def __init__(self, path: str, source: str):
        # `path` is the display/relative path findings carry.
        self.path = path
        self.source = source
        self.tree: ast.Module = ast.parse(source, filename=path)
        self.functions = iter_file_functions(self.tree)
        self._parents: t.Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        # ---- comment side-channel -------------------------------------
        self.comments: t.Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:  # pragma: no cover - ast.parse ok'd it
            pass
        # line -> rules suppressed on that line; meta findings for
        # malformed suppressions are collected here (a suppression that
        # names nothing must not be able to suppress its own finding).
        self.suppressions: t.Dict[int, t.Set[str]] = {}
        self.meta_findings: t.List[Finding] = []
        self.guarded_by: t.Dict[int, str] = {}
        for line, comment in self.comments.items():
            g = _GUARDED_BY_RE.search(comment)
            if g:
                self.guarded_by[line] = g.group("lock")
            m = _SUPPRESS_RE.search(comment)
            if m is None:
                continue
            raw = m.group("rules") or ""
            names = [n.strip() for n in raw.split(",") if n.strip()]
            if not names:
                self.meta_findings.append(Finding(
                    "bare-suppression", path, line, 0,
                    "suppression names no rule",
                    "write `# tac-lint: disable=<rule-id>`; blanket "
                    "suppressions are not allowed",
                ))
                continue
            unknown = [n for n in names if n not in ALL_RULES]
            if unknown:
                self.meta_findings.append(Finding(
                    "bare-suppression", path, line, 0,
                    f"suppression names unknown rule(s): "
                    f"{', '.join(sorted(unknown))}",
                    "use a rule id from `python -m "
                    "torch_actor_critic_tpu.analysis --list-rules`",
                ))
            known = {n for n in names if n in ALL_RULES}
            if known:
                self.suppressions.setdefault(line, set()).update(known)

    # ------------------------------------------------------------- helpers

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> t.Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_function(
        self, node: ast.AST
    ) -> t.Union[ast.FunctionDef, ast.AsyncFunctionDef, None]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def enclosing_function_names(self, node: ast.AST) -> t.List[str]:
        """Names of every enclosing function, innermost first."""
        return [
            anc.name for anc in self.ancestors(node)
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]

    def is_suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line)
        return rules is not None and finding.rule in rules
