"""Convention lints: codebase-wide hygiene the planes rely on.

* ``silent-exception-swallow`` — ``except Exception: pass`` (or bare
  ``except:``) outside a shutdown path discards the only evidence a
  fault ever happened. The worked example is the vec_env worker
  jax-config guard (envs/vec_env.py): a worker that silently failed to
  pin its CPU backend could grab the parent's accelerator and deadlock
  the handshake — the swallow hid exactly the context (worker index,
  exitcode) needed to debug it. Narrow the exception type or log it
  with enough context to act on; ``OSError``-narrow handlers and
  shutdown/teardown paths are exempt.
* ``mutable-default-arg`` — the classic: a list/dict/set default is
  evaluated once and shared across every call.
* ``suffix-reduction-mismatch`` — the telemetry suffix-key schema
  (diagnostics/ingraph.py ``reduction_for``): a ``*_max`` key
  aggregates by ``max`` downstream (scan-axis reduce, cross-replica
  collectives, host merges). Populating it with ``min(...)``/
  ``mean(...)`` (or ``*_min`` with ``max``, ``*_sum`` with ``mean``)
  produces a value whose downstream aggregation is incoherent — the
  number in metrics.jsonl is neither the max nor the mean of anything.
"""

from __future__ import annotations

import ast
import typing as t

from torch_actor_critic_tpu.analysis.reachability import Project
from torch_actor_critic_tpu.analysis.walker import (
    FileContext,
    Finding,
    dotted_name,
)

__all__ = ["check"]

FAMILY = "conventions"

_BROAD = frozenset({"Exception", "BaseException"})
_SHUTDOWN_MARKERS = (
    "close", "shutdown", "stop", "teardown", "drain", "kill",
    "cleanup", "__del__", "__exit__", "atexit", "terminate",
)
_MUTABLE_CTORS = frozenset({"list", "dict", "set", "collections.deque", "deque"})

# suffix -> reduction spellings that contradict it (the value feeding
# a *_max key may be anything, but a top-level call to one of these is
# an outright contradiction).
_SUFFIX_CONFLICTS: t.Dict[str, t.FrozenSet[str]] = {
    "_max": frozenset({"min", "mean", "average"}),
    "_min": frozenset({"max", "mean", "average"}),
    "_sum": frozenset({"mean", "average", "max", "min"}),
}


def check(project: Project) -> t.List[Finding]:
    findings: t.List[Finding] = []
    for ctx in project.files:
        _check_swallows(ctx, findings)
        _check_mutable_defaults(ctx, findings)
        _check_suffix_schema(ctx, findings)
    return findings


# ---------------------------------------------------------------- except


def _is_broad(handler: ast.ExceptHandler) -> bool:
    tp = handler.type
    if tp is None:
        return True
    names: t.List[str] = []
    if isinstance(tp, ast.Tuple):
        names = [dotted_name(e) or "" for e in tp.elts]
    else:
        names = [dotted_name(tp) or ""]
    return any(n.split(".")[-1] in _BROAD for n in names)


def _check_swallows(ctx: FileContext, findings: t.List[Finding]):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad(node):
            continue
        if not all(isinstance(s, ast.Pass) for s in node.body):
            continue
        enclosing = ctx.enclosing_function_names(node)
        if any(
            marker in name
            for name in enclosing
            for marker in _SHUTDOWN_MARKERS
        ):
            continue
        caught = "bare except" if node.type is None else (
            f"except {ast.unparse(node.type)}"
        )
        findings.append(Finding(
            "silent-exception-swallow", ctx.path,
            node.lineno, node.col_offset,
            f"{caught}: pass silently discards every failure on a "
            "non-shutdown path",
            "narrow the exception type, or log it with enough context "
            "to act on (see the envs/vec_env.py worker-config guard "
            "worked example in docs/ANALYSIS.md)",
        ))


# ------------------------------------------------------ mutable defaults


def _check_mutable_defaults(ctx: FileContext, findings: t.List[Finding]):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and dotted_name(d.func) in _MUTABLE_CTORS
            )
            if mutable:
                findings.append(Finding(
                    "mutable-default-arg", ctx.path, d.lineno, d.col_offset,
                    f"mutable default argument in {node.name}(): evaluated "
                    "once at def time and shared across every call",
                    "default to None and construct inside the body",
                ))


# -------------------------------------------------------- suffix schema


def _reduction_of(value: ast.AST) -> str | None:
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    if name is None:
        return None
    return name.split(".")[-1]


def _check_key_value(
    ctx: FileContext, key: str, value: ast.AST, findings: t.List[Finding]
):
    for suffix, conflicts in _SUFFIX_CONFLICTS.items():
        if not key.endswith(suffix):
            continue
        red = _reduction_of(value)
        if red in conflicts:
            findings.append(Finding(
                "suffix-reduction-mismatch", ctx.path,
                value.lineno, value.col_offset,
                f"metric key {key!r} aggregates by "
                f"{suffix[1:]!r} downstream (suffix convention, "
                f"diagnostics/ingraph.py) but is populated with "
                f"{red}(...)",
                f"rename the key or use the matching {suffix[1:]} "
                "reduction",
            ))
        return


def _check_suffix_schema(ctx: FileContext, findings: t.List[Finding]):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and v is not None
                ):
                    _check_key_value(ctx, k.value, v, findings)
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    isinstance(target, ast.Subscript)
                    and isinstance(target.slice, ast.Constant)
                    and isinstance(target.slice.value, str)
                ):
                    _check_key_value(
                        ctx, target.slice.value, node.value, findings
                    )
