"""recompile-risk rules: call patterns that retrace or recompile.

The recompilation watchdog (diagnostics/watchdog.py) catches these at
runtime as ``recompile_anomaly`` events; this family catches the same
hazards before a single test runs (docs/OBSERVABILITY.md
"Recompilation-watchdog runbook" cross-references both directions):

* ``jit-cache-discard`` — ``jax.jit(f)(...)`` invoked immediately:
  the wrapper (and its compile cache) is thrown away after one call,
  so every execution pays a full retrace+compile.
* ``jit-in-loop`` — constructing ``jax.jit(...)`` inside a for/while
  body: a fresh wrapper (fresh cache) per iteration.
* ``varying-shape-arg`` — passing a dynamically-bounded slice
  (``x[:n]`` with non-constant ``n``) to a known-jitted callable:
  every distinct length is a new shape, a new trace, a new compile.
* ``donated-reuse`` — reading a buffer after passing it at a donated
  position (``donate_argnums``): the callee may have aliased its
  memory; on TPU the read returns garbage, on CPU it silently works
  (donation is a no-op) and the bug ships.
* ``shard-map-hot-path`` — the PR-8 invariant, promoted from the
  retired source-regex pin in tests/test_mesh_gspmd.py: ``shard_map``
  belongs only in ``parallel/context.py`` (the manual-mapping home)
  and ``parallel/compat.py`` (the deprecation stub). Every other
  reference must sit in :data:`SHARD_MAP_ALLOWLIST`, and every
  allowlist entry must still match a real reference
  (``stale-allowlist``) — the allowlist is checked, never trusted.
"""

from __future__ import annotations

import ast
import typing as t

from torch_actor_critic_tpu.analysis.reachability import Project, _is_wrapper
from torch_actor_critic_tpu.analysis.walker import (
    FileContext,
    Finding,
    dotted_name,
)

__all__ = ["check", "SHARD_MAP_ALLOWLIST"]

FAMILY = "recompile-risk"

_JIT_MAKERS = frozenset({"jax.jit", "jit", "pjit", "jax.pmap", "pmap"})

# Files where shard_map lives by definition (the rule text itself).
SHARD_MAP_HOME = ("parallel/context.py", "parallel/compat.py")

# (path suffix, scope qualname) pairs allowed to reference shard_map
# outside its home. Scope "<module>" means module level. Every entry
# must match at least one live reference or the run fails with
# stale-allowlist. Justifications live in docs/ANALYSIS.md.
SHARD_MAP_ALLOWLIST: t.FrozenSet[t.Tuple[str, str]] = frozenset({
    # Public re-export of the manual-mapping helper.
    ("parallel/__init__.py", "<module>"),
    # The sp ring-attention burst is manual by nature (a real named
    # axis for the K/V rotation); it routes through
    # context.manual_shard_map — the one sanctioned hot-path use.
    ("parallel/dp.py", "DataParallelSAC._build_ring_burst"),
})

_SHARD_NAMES = frozenset({"shard_map", "manual_shard_map"})


def _is_jit_maker(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _is_wrapper(
        dotted_name(node.func), _JIT_MAKERS
    )


def _donated_positions(call: ast.Call) -> t.Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    out.append(elt.value)
            return tuple(out)
        # Conditional donation (e.g. `(1,) if donate else ()`) can't be
        # resolved statically; skip rather than guess.
    return ()


def _scope_qualname(ctx: FileContext, node: ast.AST) -> str:
    fn = ctx.enclosing_function(node)
    if fn is None:
        return "<module>"
    for info in ctx.functions:
        if info.node is fn:
            return info.qualname
    return fn.name  # pragma: no cover - every function is indexed


def _target_key(node: ast.AST) -> str | None:
    """'name' or 'self.attr' for jitted-callable tracking."""
    if isinstance(node, ast.Name):
        return node.id
    name = dotted_name(node)
    if name and name.startswith("self.") and name.count(".") == 1:
        return name
    return None


def check(project: Project) -> t.List[Finding]:
    findings: t.List[Finding] = []
    allow_hits: t.Set[t.Tuple[str, str]] = set()

    for ctx in project.files:
        _check_jit_construction(ctx, findings)
        jitted = _collect_jitted(ctx, findings)
        _check_call_sites(ctx, jitted, findings)
        _check_shard_map(ctx, findings, allow_hits)

    for entry in sorted(SHARD_MAP_ALLOWLIST - allow_hits):
        # Only report staleness when the allowlisted file was actually
        # part of this run (linting a single unrelated file must not
        # fail on the whole-package allowlist).
        if any(f.path.endswith(entry[0]) for f in project.files):
            findings.append(Finding(
                "stale-allowlist", entry[0], 1, 0,
                f"shard-map allowlist entry {entry!r} matches no "
                "reference; the code it excused is gone",
                "remove the entry from analysis/recompile.py "
                "SHARD_MAP_ALLOWLIST",
            ))
    return findings


# ------------------------------------------------------ jit construction


def _check_jit_construction(ctx: FileContext, findings: t.List[Finding]):
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_jit_maker(node.func):
            findings.append(Finding(
                "jit-cache-discard", ctx.path, node.lineno, node.col_offset,
                "jax.jit(...) invoked immediately: the wrapper and its "
                "compile cache are discarded after this one call, so every "
                "execution retraces and recompiles",
                "bind the jitted callable once (module/attr) and call the "
                "binding",
            ))
        if not _is_jit_maker(node):
            continue
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(anc, (ast.For, ast.While)):
                findings.append(Finding(
                    "jit-in-loop", ctx.path, node.lineno, node.col_offset,
                    "jax.jit(...) constructed inside a loop body: a fresh "
                    "wrapper (and fresh compile cache) per iteration",
                    "hoist the jit construction out of the loop",
                ))
                break


# -------------------------------------------------- call-site analysis


def _collect_jitted(
    ctx: FileContext, findings: t.List[Finding]
) -> t.Dict[str, t.Tuple[int, ...]]:
    """'name' / 'self.attr' -> donated positions, for every
    ``x = jax.jit(...)`` assignment in the file (positions are () when
    nothing is donated — the name is still a known-jitted callable for
    varying-shape-arg)."""
    jitted: t.Dict[str, t.Tuple[int, ...]] = {}
    for node in ast.walk(ctx.tree):
        value: ast.AST | None = None
        targets: t.List[ast.AST] = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, list(node.targets)
        elif isinstance(node, ast.Return) and node.value is not None:
            # `return jax.jit(f, donate_argnums=...)` from a builder:
            # track the builder itself as producing a donating callable
            # is out of scope (the binding happens elsewhere); skip.
            continue
        if value is None or not _is_jit_maker(value):
            continue
        donated = _donated_positions(t.cast(ast.Call, value))
        for target in targets:
            key = _target_key(target)
            if key is not None:
                jitted[key] = donated
    return jitted


def _statement_of(ctx: FileContext, node: ast.AST) -> ast.stmt | None:
    cur: ast.AST | None = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = ctx.parent(cur)
    return t.cast("ast.stmt | None", cur)


def _check_call_sites(
    ctx: FileContext,
    jitted: t.Dict[str, t.Tuple[int, ...]],
    findings: t.List[Finding],
):
    if not jitted:
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        key = _target_key(node.func)
        if key is None or key not in jitted:
            continue
        _check_varying_shape(ctx, node, findings)
        donated = jitted[key]
        if donated:
            _check_donated_reuse(ctx, node, donated, findings)


def _check_varying_shape(
    ctx: FileContext, call: ast.Call, findings: t.List[Finding]
):
    for arg in call.args:
        for sub in ast.walk(arg):
            if not (
                isinstance(sub, ast.Subscript)
                and isinstance(sub.slice, ast.Slice)
            ):
                continue
            bounds = [
                b for b in (sub.slice.lower, sub.slice.upper)
                if b is not None and not isinstance(b, ast.Constant)
            ]
            if bounds:
                findings.append(Finding(
                    "varying-shape-arg", ctx.path,
                    sub.lineno, sub.col_offset,
                    "dynamically-bounded slice passed to a jitted "
                    "callable: every distinct length is a new shape and "
                    "a full recompile",
                    "pad to a fixed (bucketed) shape, or mark the bound "
                    "static if it takes few values",
                ))


def _check_donated_reuse(
    ctx: FileContext,
    call: ast.Call,
    donated: t.Tuple[int, ...],
    findings: t.List[Finding],
):
    fn = ctx.enclosing_function(call)
    if fn is None:
        return
    stmt = _statement_of(ctx, call)
    if stmt is None:
        return
    for pos in donated:
        if pos >= len(call.args):
            continue
        arg = call.args[pos]
        if not isinstance(arg, ast.Name):
            continue
        name = arg.id
        # The statement holding the call often rebinds the donated
        # name (`state, buf, m = burst(state, buf, chunk)`): collect
        # names stored by that statement — reads of those afterwards
        # see the NEW buffer, which is fine.
        rebound = {
            n.id for n in ast.walk(stmt)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
        }
        if name in rebound:
            continue
        end = stmt.end_lineno or stmt.lineno
        next_store = min(
            (
                n.lineno for n in ast.walk(fn)
                if isinstance(n, ast.Name) and n.id == name
                and isinstance(n.ctx, ast.Store) and n.lineno > end
            ),
            default=None,
        )
        for n in ast.walk(fn):
            if not (
                isinstance(n, ast.Name) and n.id == name
                and isinstance(n.ctx, ast.Load) and n.lineno > end
            ):
                continue
            if next_store is not None and n.lineno >= next_store:
                continue
            findings.append(Finding(
                "donated-reuse", ctx.path, n.lineno, n.col_offset,
                f"{name!r} is read after being passed at a donated "
                f"position (arg {pos}) on line {call.lineno}: its buffer "
                "may already be aliased by the callee (garbage on TPU; "
                "silently fine on CPU where donation is a no-op)",
                "use the callee's returned value, or stop donating this "
                "argument",
            ))
            break  # one finding per donated arg per call site


# ------------------------------------------------------------ shard_map


def _check_shard_map(
    ctx: FileContext,
    findings: t.List[Finding],
    allow_hits: t.Set[t.Tuple[str, str]],
):
    if any(ctx.path.endswith(home) for home in SHARD_MAP_HOME):
        return
    for node in ast.walk(ctx.tree):
        name: str | None = None
        if isinstance(node, ast.Name) and node.id in _SHARD_NAMES:
            name = node.id
        elif isinstance(node, ast.Attribute) and node.attr in _SHARD_NAMES:
            name = node.attr
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            hit = next(
                (
                    a for a in node.names
                    if (a.asname or a.name).split(".")[-1] in _SHARD_NAMES
                    or a.name.split(".")[-1] in _SHARD_NAMES
                ),
                None,
            )
            if hit is not None:
                name = hit.name
        if name is None:
            continue
        scope = _scope_qualname(ctx, node)
        entry = next(
            (
                e for e in SHARD_MAP_ALLOWLIST
                if ctx.path.endswith(e[0]) and e[1] in (scope, "*")
            ),
            None,
        )
        if entry is not None:
            allow_hits.add(entry)
            continue
        findings.append(Finding(
            "shard-map-hot-path", ctx.path, node.lineno, node.col_offset,
            f"{name!r} referenced outside parallel/context.py + "
            "parallel/compat.py (PR-8 invariant: hot paths are plain "
            "GSPMD jit-with-sharding)",
            "route manual mapping through context.manual_shard_map from "
            "an allowlisted scope, or add a justified entry to "
            "SHARD_MAP_ALLOWLIST (analysis/recompile.py) and "
            "docs/ANALYSIS.md",
        ))
