"""CLI for the tac-lint pass: ``python -m torch_actor_critic_tpu.analysis``.

Exit codes (text mode): 0 clean, 1 findings, 2 usage/parse error.
``--json`` mode is the machine contract ``make lint``/CI diff against:
one JSON object ``{"clean", "findings", "families", "exit_code"}`` on
stdout, and a STABLE per-family exit code — 0 clean, 2 usage/parse
error, ``FAMILY_EXIT_CODES[family]`` when exactly one family has
findings, 1 when several do. The codes are part of the contract
(docs/ANALYSIS.md): a CI gate can route "donation-safety regressed"
(14) differently from "conventions slipped" (13) without parsing
anything.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from torch_actor_critic_tpu.analysis import (
    ALL_RULES,
    RULE_FAMILIES,
    family_of,
    lint_paths,
)

# Stable per-family exit codes for --json mode. Append-only: new
# families take the next free code; renumbering breaks CI routing.
FAMILY_EXIT_CODES = {
    "jit-hygiene": 10,
    "recompile-risk": 11,
    "lock-discipline": 12,
    "conventions": 13,
    "donation-safety": 14,
    "prng-discipline": 15,
    "contract-drift": 16,
    "meta": 17,
}


def exit_code_for(families: "dict[str, int]") -> int:
    """0 clean; the family's stable code when exactly one family has
    findings; 1 for a mixed set."""
    hit = [f for f, n in families.items() if n]
    if not hit:
        return 0
    if len(hit) == 1:
        return FAMILY_EXIT_CODES[hit[0]]
    return 1


def _default_paths() -> list:
    pkg = pathlib.Path(__file__).resolve().parent.parent
    root = pkg.parent
    out = [pkg]
    if (root / "scripts").is_dir():
        out.append(root / "scripts")
    # Prefer repo-relative display paths when running from the root.
    cwd = pathlib.Path.cwd()
    disp = []
    for p in out:
        try:
            disp.append(p.relative_to(cwd).as_posix())
        except ValueError:
            disp.append(p.as_posix())
    return disp


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torch_actor_critic_tpu.analysis",
        description="tac-lint: jit-hygiene, recompile-risk, "
        "lock-discipline and convention checks (docs/ANALYSIS.md)",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the package and "
        "scripts/)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="finding output format (json: the raw findings list)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="json_mode",
        help="machine-readable mode: one JSON object {clean, findings, "
        "families, exit_code} and stable per-family exit codes "
        "(docs/ANALYSIS.md) — what `make lint`/CI diff against",
    )
    parser.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--disable", default=None, metavar="RULES",
        help="comma-separated rule ids to skip",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for family, rules in RULE_FAMILIES.items():
            print(f"{family}:")
            for rule in rules:
                print(f"  {rule}")
        return 0

    rules = set(ALL_RULES)
    for raw, keep in ((args.select, True), (args.disable, False)):
        if raw is None:
            continue
        names = {n.strip() for n in raw.split(",") if n.strip()}
        unknown = names - ALL_RULES
        if unknown:
            print(
                f"unknown rule id(s): {', '.join(sorted(unknown))} "
                "(see --list-rules)", file=sys.stderr,
            )
            return 2
        rules = (rules & names) if keep else (rules - names)

    paths = args.paths or _default_paths()
    try:
        findings = lint_paths(paths, rules=rules)
    except SyntaxError as e:
        print(f"parse error: {e}", file=sys.stderr)
        return 2

    if args.json_mode:
        families = {name: 0 for name in RULE_FAMILIES}
        for f in findings:
            families[family_of(f.rule)] += 1
        code = exit_code_for(families)
        print(json.dumps({
            "clean": not findings,
            "findings": [f.as_dict() for f in findings],
            "families": families,
            "exit_code": code,
        }, indent=2, sort_keys=True))
        return code
    if args.format == "json":
        print(json.dumps([f.as_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        print(f"tac-lint: {n} finding{'s' if n != 1 else ''}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
