"""jit-hygiene rules: host-side constructs inside traced code.

Inside a function reachable from a jit/scan entry point
(:mod:`~torch_actor_critic_tpu.analysis.reachability`), host-device
sync points and host-state reads are silent performance/correctness
hazards:

* ``host-sync-in-jit`` — ``.item()`` / ``.tolist()`` /
  ``.block_until_ready()`` / ``jax.device_get`` anywhere in traced
  code, and ``float()``/``int()``/``bool()`` casts or ``np.*`` calls
  applied to *traced values* (approximated as values derived from the
  traced function's parameters — closure variables are typically
  trace-time constants and stay exempt). Each of these either forces a
  device->host transfer per step or raises a ``TracerArrayConversion``
  at trace time; on the fused Podracer-style loops one stray sync is
  the difference between 0.70 and 0.02 MFU (PAPERS.md, BENCH_r03-r05).
* ``wallclock-in-jit`` — ``time.*`` / ``datetime.now`` in traced code
  reads the clock ONCE at trace time and bakes the value into the
  compiled program: the metric it feeds goes silently constant.
* ``host-random-in-jit`` — stdlib ``random.*`` / ``np.random.*`` in
  traced code is the same bug for randomness (``jax.random`` with
  explicit keys is the traced-safe spelling and is never flagged).
"""

from __future__ import annotations

import ast
import typing as t

from torch_actor_critic_tpu.analysis.reachability import (
    CALLBACK_WRAPPERS,
    Project,
    _is_wrapper,
)
from torch_actor_critic_tpu.analysis.walker import (
    Finding,
    FunctionInfo,
    dotted_name,
)

__all__ = ["check"]

FAMILY = "jit-hygiene"

# Attribute-call syncs flagged on ANY receiver inside traced code.
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
_SYNC_CALLS = frozenset({"jax.device_get", "device_get"})
_CAST_BUILTINS = frozenset({"float", "int", "bool", "complex"})
_WALLCLOCK = frozenset({
    "time.time", "time.perf_counter", "time.monotonic", "time.sleep",
    "time.process_time", "time.time_ns", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
})
_NP_ALIASES = ("np", "numpy")


def _is_host_random(name: str) -> bool:
    parts = name.split(".")
    if parts[0] == "random" and len(parts) > 1:
        return True
    return len(parts) >= 3 and parts[-3] in _NP_ALIASES and parts[-2] == "random"


def _param_names(node: ast.AST) -> t.Set[str]:
    if isinstance(node, ast.Lambda):
        args = node.args
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        args = node.args
    else:  # pragma: no cover - defensive
        return set()
    names = {
        a.arg
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        )
    }
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


def _tainted_names(fn_node: ast.AST) -> t.Set[str]:
    """Parameters plus names assigned from param-derived expressions
    (two fixed-point passes — enough for the straight-line bodies jit
    functions have)."""
    tainted = _param_names(fn_node)
    body = getattr(fn_node, "body", None)
    if body is None or isinstance(body, ast.AST):  # Lambda
        return tainted
    for _ in range(2):
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Assign):
                continue
            if _derives_from(node.value, tainted):
                for target in node.targets:
                    for n in ast.walk(target):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
    return tainted


# Attribute reads that are static under trace: a tracer's .shape /
# .dtype / .ndim are Python values at trace time, so host math over
# them is fine (and idiomatic — bucket ladders, fsdp spec planning).
_STATIC_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "sharding",
})


def _derives_from(node: ast.AST, tainted: t.Set[str]) -> bool:
    """Does the expression read a tainted name through a non-static
    path? ``x`` and ``x[0]`` taint; ``x.shape`` / ``np.prod(x.shape)``
    do not."""
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False
    if isinstance(node, ast.Name):
        return node.id in tainted and isinstance(node.ctx, ast.Load)
    return any(
        _derives_from(child, tainted)
        for child in ast.iter_child_nodes(node)
    )


def _callback_subtrees(fn_node: ast.AST) -> t.Set[ast.AST]:
    """Function/lambda nodes inside ``fn_node`` that are host-callback
    bodies (their code runs on the host; hygiene rules skip them)."""
    out: t.Set[ast.AST] = set()
    local_defs = {
        n.name: n for n in ast.walk(fn_node)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        if not _is_wrapper(dotted_name(node.func), CALLBACK_WRAPPERS):
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(arg, ast.Lambda):
                out.add(arg)
            name = dotted_name(arg)
            if name in local_defs:
                out.add(local_defs[name])
    return out


def _walk_skipping(root: ast.AST, skip: t.Set[ast.AST]) -> t.Iterator[ast.AST]:
    stack = [root]
    while stack:
        node = stack.pop()
        if node in skip:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check(project: Project) -> t.List[Finding]:
    findings: t.List[Finding] = []
    seen: t.Set[t.Tuple[str, int, int, str]] = set()

    def emit(rule, path, node, message, hint):
        key = (path, node.lineno, node.col_offset, rule)
        if key in seen:
            return
        seen.add(key)
        findings.append(
            Finding(rule, path, node.lineno, node.col_offset, message, hint)
        )

    findings.extend(project.entry_point_findings())

    for (path, _), fn in sorted(
        project.traced().items(), key=lambda kv: (kv[0][0], kv[0][1])
    ):
        fn_node: ast.AST = fn.node
        tainted = _tainted_names(fn_node)
        skip = _callback_subtrees(fn_node)
        where = f"traced function {fn.qualname!r}"
        for node in _walk_skipping(fn_node, skip):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _SYNC_METHODS and not node.args:
                    emit(
                        "host-sync-in-jit", path, node,
                        f".{node.func.attr}() inside {where} forces a "
                        "device->host sync every trace execution",
                        "keep the value on device (jnp reductions) or move "
                        "the read outside the jit boundary",
                    )
                    continue
            if name is None:
                continue
            if name in _SYNC_CALLS:
                emit(
                    "host-sync-in-jit", path, node,
                    f"{name}() inside {where} is a host transfer",
                    "return the array and read it outside the trace",
                )
            elif name in _CAST_BUILTINS and len(node.args) == 1 and (
                _derives_from(node.args[0], tainted)
            ):
                emit(
                    "host-sync-in-jit", path, node,
                    f"{name}() on a traced value inside {where} "
                    "(concretization error or silent host sync)",
                    "use jnp casts (.astype) on device, or mark the "
                    "argument static at the jit boundary",
                )
            elif name.split(".")[0] in _NP_ALIASES and (
                not _is_host_random(name)
                and len(node.args) >= 1
                and _derives_from(node.args[0], tainted)
            ):
                emit(
                    "host-sync-in-jit", path, node,
                    f"{name}() on a traced value inside {where} "
                    "materializes on host",
                    "use the jnp equivalent so the op stays in the trace",
                )
            if name in _WALLCLOCK:
                emit(
                    "wallclock-in-jit", path, node,
                    f"{name}() inside {where} is evaluated ONCE at trace "
                    "time; the compiled program sees a constant",
                    "take timings on the host around the jit call "
                    "(telemetry phase spans), not inside it",
                )
            elif _is_host_random(name):
                emit(
                    "host-random-in-jit", path, node,
                    f"{name}() inside {where} draws host randomness at "
                    "trace time (constant in the compiled program)",
                    "thread a jax.random key through the trace instead",
                )
    return findings
