"""jit-hygiene rules: host-side constructs inside traced code.

Inside a function reachable from a jit/scan entry point
(:mod:`~torch_actor_critic_tpu.analysis.reachability`), host-device
sync points and host-state reads are silent performance/correctness
hazards:

* ``host-sync-in-jit`` — ``.item()`` / ``.tolist()`` /
  ``.block_until_ready()`` / ``jax.device_get`` anywhere in traced
  code, and ``float()``/``int()``/``bool()`` casts or ``np.*`` calls
  applied to *traced values* (approximated as values derived from the
  traced function's parameters — closure variables are typically
  trace-time constants and stay exempt). Each of these either forces a
  device->host transfer per step or raises a ``TracerArrayConversion``
  at trace time; on the fused Podracer-style loops one stray sync is
  the difference between 0.70 and 0.02 MFU (PAPERS.md, BENCH_r03-r05).
* ``wallclock-in-jit`` — ``time.*`` / ``datetime.now`` in traced code
  reads the clock ONCE at trace time and bakes the value into the
  compiled program: the metric it feeds goes silently constant.
* ``host-random-in-jit`` — stdlib ``random.*`` / ``np.random.*`` in
  traced code is the same bug for randomness (``jax.random`` with
  explicit keys is the traced-safe spelling and is never flagged).
* ``frame-f32-materialize`` — ``astype(float32)`` or division by 255
  applied to a frame-derived value outside the fused pixel pipeline
  (``ops/pixels.py``, the decode's home) or the checked
  :data:`FRAME_DECODE_ALLOWLIST`. Frames live in HBM as uint8 by
  design (4x smaller replay, ``buffer/replay.py``); decoding them to
  f32 anywhere but the fused gather re-creates the 4x-width frame
  batch the pixel-pipeline work removed — the silent regression this
  rule exists to stop. Like the shard-map allowlist, every entry must
  still match a real decode (``stale-allowlist``).
"""

from __future__ import annotations

import ast
import typing as t

from torch_actor_critic_tpu.analysis.reachability import (
    CALLBACK_WRAPPERS,
    Project,
    _is_wrapper,
)
from torch_actor_critic_tpu.analysis.walker import (
    Finding,
    FunctionInfo,
    dotted_name,
)

__all__ = ["check", "FRAME_DECODE_ALLOWLIST"]

FAMILY = "jit-hygiene"

# The fused pixel pipeline: uint8 frame decode lives here by
# definition (both the Pallas kernel and its jnp reference path).
FRAME_DECODE_HOME = ("ops/pixels.py",)

# (path suffix, scope qualname) pairs allowed to decode uint8 frames
# to f32 outside the pipeline home. Scope "*" means anywhere in the
# file. Every entry must match at least one live decode or the run
# fails with stale-allowlist. Justifications live in docs/ANALYSIS.md.
FRAME_DECODE_ALLOWLIST: t.FrozenSet[t.Tuple[str, str]] = frozenset({
    # The legacy in-model decode — pixel_pipeline="reference"'s
    # bit-pinned parity path (uint8 frames cast + normalized inside
    # SimpleCNN). It must keep existing verbatim: precision=f32 on the
    # reference pipeline is graph- and bit-identical to the pre-fusion
    # builds by contract.
    ("models/visual.py", "SimpleCNN.__call__"),
})

# Attribute-call syncs flagged on ANY receiver inside traced code.
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
_SYNC_CALLS = frozenset({"jax.device_get", "device_get"})
_CAST_BUILTINS = frozenset({"float", "int", "bool", "complex"})
_WALLCLOCK = frozenset({
    "time.time", "time.perf_counter", "time.monotonic", "time.sleep",
    "time.process_time", "time.time_ns", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
})
_NP_ALIASES = ("np", "numpy")


def _is_host_random(name: str) -> bool:
    parts = name.split(".")
    if parts[0] == "random" and len(parts) > 1:
        return True
    return len(parts) >= 3 and parts[-3] in _NP_ALIASES and parts[-2] == "random"


def _param_names(node: ast.AST) -> t.Set[str]:
    if isinstance(node, ast.Lambda):
        args = node.args
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        args = node.args
    else:  # pragma: no cover - defensive
        return set()
    names = {
        a.arg
        for a in (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        )
    }
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


def _tainted_names(fn_node: ast.AST) -> t.Set[str]:
    """Parameters plus names assigned from param-derived expressions
    (two fixed-point passes — enough for the straight-line bodies jit
    functions have)."""
    tainted = _param_names(fn_node)
    body = getattr(fn_node, "body", None)
    if body is None or isinstance(body, ast.AST):  # Lambda
        return tainted
    for _ in range(2):
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Assign):
                continue
            if _derives_from(node.value, tainted):
                for target in node.targets:
                    for n in ast.walk(target):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
    return tainted


# Attribute reads that are static under trace: a tracer's .shape /
# .dtype / .ndim are Python values at trace time, so host math over
# them is fine (and idiomatic — bucket ladders, fsdp spec planning).
_STATIC_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "sharding",
})


def _derives_from(node: ast.AST, tainted: t.Set[str]) -> bool:
    """Does the expression read a tainted name through a non-static
    path? ``x`` and ``x[0]`` taint; ``x.shape`` / ``np.prod(x.shape)``
    do not."""
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False
    if isinstance(node, ast.Name):
        return node.id in tainted and isinstance(node.ctx, ast.Load)
    return any(
        _derives_from(child, tainted)
        for child in ast.iter_child_nodes(node)
    )


def _callback_subtrees(fn_node: ast.AST) -> t.Set[ast.AST]:
    """Function/lambda nodes inside ``fn_node`` that are host-callback
    bodies (their code runs on the host; hygiene rules skip them)."""
    out: t.Set[ast.AST] = set()
    local_defs = {
        n.name: n for n in ast.walk(fn_node)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        if not _is_wrapper(dotted_name(node.func), CALLBACK_WRAPPERS):
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(arg, ast.Lambda):
                out.add(arg)
            name = dotted_name(arg)
            if name in local_defs:
                out.add(local_defs[name])
    return out


def _walk_skipping(root: ast.AST, skip: t.Set[ast.AST]) -> t.Iterator[ast.AST]:
    stack = [root]
    while stack:
        node = stack.pop()
        if node in skip:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# -------------------------------------------------- frame decode rule

_F32_NAMES = frozenset({
    "jnp.float32", "np.float32", "jax.numpy.float32", "numpy.float32",
    "float32",
})


def _is_f32_spelling(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and node.value == "float32":
        return True
    name = dotted_name(node)
    return name is not None and name in _F32_NAMES


def _is_255(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value in (255, 255.0)


def _mentions_frame(node: ast.AST) -> bool:
    """Does the expression DIRECTLY read a frame value — a name or
    attribute spelled with 'frame' (``frames``, ``batch.states.frame``,
    ``frame_batch``)? Deliberately no dataflow propagation: once frames
    enter the network, everything downstream derives from them, and
    casting *activations* to f32 is the mixed-precision policy (the
    heads do exactly that), not a frame materialization."""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "frame" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "frame" in n.attr.lower():
            return True
    return False


def _frame_scope_qualname(ctx, node: ast.AST) -> str:
    fn = ctx.enclosing_function(node)
    if fn is None:
        return "<module>"
    for info in ctx.functions:
        if info.node is fn:
            return info.qualname
    return fn.name  # pragma: no cover - every function is indexed


def _check_frame_decode(
    project: Project,
    findings: t.List[Finding],
    emit: t.Callable,
) -> None:
    allow_hits: t.Set[t.Tuple[str, str]] = set()
    for ctx in project.files:
        if any(ctx.path.endswith(home) for home in FRAME_DECODE_HOME):
            continue
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                decoded = (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"
                    and len(node.args) == 1
                    and _is_f32_spelling(node.args[0])
                    and _mentions_frame(node.func.value)
                )
                what = "astype(float32)"
            elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
                decoded = _is_255(node.right) and _mentions_frame(node.left)
                what = "division by 255"
            else:
                continue
            if not decoded:
                continue
            scope = _frame_scope_qualname(ctx, node)
            entry = next(
                (
                    e for e in FRAME_DECODE_ALLOWLIST
                    if ctx.path.endswith(e[0]) and e[1] in (scope, "*")
                ),
                None,
            )
            if entry is not None:
                allow_hits.add(entry)
                continue
            emit(
                "frame-f32-materialize", ctx.path, node,
                f"{what} on a frame-derived value materializes the "
                "4x-width f32 frame batch the fused pixel pipeline "
                "exists to avoid (frames are uint8 in HBM by design)",
                "route sampling through pixel_pipeline='fused' "
                "(ops/pixels.py decodes in-kernel), or add a justified "
                "entry to FRAME_DECODE_ALLOWLIST (analysis/"
                "jit_hygiene.py) and docs/ANALYSIS.md",
            )
    for entry in sorted(FRAME_DECODE_ALLOWLIST - allow_hits):
        if any(f.path.endswith(entry[0]) for f in project.files):
            findings.append(Finding(
                "stale-allowlist", entry[0], 1, 0,
                f"frame-decode allowlist entry {entry!r} matches no "
                "decode; the code it excused is gone",
                "remove the entry from analysis/jit_hygiene.py "
                "FRAME_DECODE_ALLOWLIST",
            ))


def check(project: Project) -> t.List[Finding]:
    findings: t.List[Finding] = []
    seen: t.Set[t.Tuple[str, int, int, str]] = set()

    def emit(rule, path, node, message, hint):
        key = (path, node.lineno, node.col_offset, rule)
        if key in seen:
            return
        seen.add(key)
        findings.append(
            Finding(rule, path, node.lineno, node.col_offset, message, hint)
        )

    findings.extend(project.entry_point_findings())
    _check_frame_decode(project, findings, emit)

    for (path, _), fn in sorted(
        project.traced().items(), key=lambda kv: (kv[0][0], kv[0][1])
    ):
        fn_node: ast.AST = fn.node
        tainted = _tainted_names(fn_node)
        skip = _callback_subtrees(fn_node)
        where = f"traced function {fn.qualname!r}"
        for node in _walk_skipping(fn_node, skip):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _SYNC_METHODS and not node.args:
                    emit(
                        "host-sync-in-jit", path, node,
                        f".{node.func.attr}() inside {where} forces a "
                        "device->host sync every trace execution",
                        "keep the value on device (jnp reductions) or move "
                        "the read outside the jit boundary",
                    )
                    continue
            if name is None:
                continue
            if name in _SYNC_CALLS:
                emit(
                    "host-sync-in-jit", path, node,
                    f"{name}() inside {where} is a host transfer",
                    "return the array and read it outside the trace",
                )
            elif name in _CAST_BUILTINS and len(node.args) == 1 and (
                _derives_from(node.args[0], tainted)
            ):
                emit(
                    "host-sync-in-jit", path, node,
                    f"{name}() on a traced value inside {where} "
                    "(concretization error or silent host sync)",
                    "use jnp casts (.astype) on device, or mark the "
                    "argument static at the jit boundary",
                )
            elif name.split(".")[0] in _NP_ALIASES and (
                not _is_host_random(name)
                and len(node.args) >= 1
                and _derives_from(node.args[0], tainted)
            ):
                emit(
                    "host-sync-in-jit", path, node,
                    f"{name}() on a traced value inside {where} "
                    "materializes on host",
                    "use the jnp equivalent so the op stays in the trace",
                )
            if name in _WALLCLOCK:
                emit(
                    "wallclock-in-jit", path, node,
                    f"{name}() inside {where} is evaluated ONCE at trace "
                    "time; the compiled program sees a constant",
                    "take timings on the host around the jit call "
                    "(telemetry phase spans), not inside it",
                )
            elif _is_host_random(name):
                emit(
                    "host-random-in-jit", path, node,
                    f"{name}() inside {where} draws host randomness at "
                    "trace time (constant in the compiled program)",
                    "thread a jax.random key through the trace instead",
                )
    return findings
