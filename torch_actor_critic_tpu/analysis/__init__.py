"""tac-lint: the codebase-native static-analysis pass.

``python -m torch_actor_critic_tpu.analysis`` (or ``make lint``) runs
four rule families over the package — jit-hygiene (host syncs and
host state inside traced code, seeded from the CostRegistry/watchdog
source names), recompile-risk (jit cache discards, donated-buffer
reuse, the shard_map hot-path invariant), lock-discipline (the
``# guarded-by:`` annotation convention on the threaded serving/
decoupled classes), and convention lints (telemetry suffix-key
schema, silent exception swallows, mutable defaults). Rule catalog,
annotation convention and suppression policy: docs/ANALYSIS.md.

The tier-1 wiring is tests/test_analysis.py's whole-package clean-run
test: a new violation anywhere in the package or scripts/ fails
``pytest tests/``.
"""

from __future__ import annotations

import pathlib
import typing as t

from torch_actor_critic_tpu.analysis import (
    contracts,
    conventions,
    donation,
    jit_hygiene,
    locks,
    prng,
    recompile,
)
from torch_actor_critic_tpu.analysis.reachability import (
    ENTRY_POINTS,
    Project,
)
from torch_actor_critic_tpu.analysis.walker import (
    ALL_RULES,
    RULE_FAMILIES,
    FileContext,
    Finding,
    family_of,
)

__all__ = [
    "ALL_RULES",
    "ENTRY_POINTS",
    "Finding",
    "RULE_FAMILIES",
    "family_of",
    "lint_paths",
    "lint_sources",
]

_FAMILY_CHECKS = (
    jit_hygiene.check,
    recompile.check,
    locks.check,
    conventions.check,
    donation.check,
    prng.check,
    contracts.check,
)


def _collect_files(paths: t.Sequence[str]) -> t.List[pathlib.Path]:
    out: t.List[pathlib.Path] = []
    for raw in paths:
        p = pathlib.Path(raw)
        if p.is_dir():
            out.extend(sorted(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            ))
        elif p.suffix == ".py":
            out.append(p)
    return out


def lint_sources(
    sources: t.Mapping[str, str],
    rules: t.Collection[str] | None = None,
) -> t.List[Finding]:
    """Lint in-memory sources (``{display_path: source}``). The unit
    the fixture tests drive; :func:`lint_paths` is a thin file-reading
    wrapper around it."""
    enabled = set(ALL_RULES if rules is None else rules)
    contexts = [
        FileContext(path, src) for path, src in sorted(sources.items())
    ]
    project = Project(contexts)
    findings: t.List[Finding] = []
    for check in _FAMILY_CHECKS:
        findings.extend(check(project))
    by_path = {c.path: c for c in contexts}
    kept = [
        f for f in findings
        if f.rule in enabled
        and (f.path not in by_path or not by_path[f.path].is_suppressed(f))
    ]
    # Malformed suppressions can never suppress themselves.
    if "bare-suppression" in enabled:
        for ctx in contexts:
            kept.extend(ctx.meta_findings)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def lint_paths(
    paths: t.Sequence[str],
    rules: t.Collection[str] | None = None,
) -> t.List[Finding]:
    """Lint files/directories on disk; paths in findings are as given
    (relative stays relative, so ``file:line`` is clickable from the
    repo root)."""
    files = _collect_files(paths)
    sources = {f.as_posix(): f.read_text() for f in files}
    return lint_sources(sources, rules=rules)
