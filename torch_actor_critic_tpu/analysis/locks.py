"""lock-discipline race detection for the threaded planes.

The serving/decoupled planes (serve/batcher.py, serve/router.py,
serve/registry.py, serve/fleet.py, decoupled/staging.py) coordinate
threads through ``threading.Lock``/``Condition`` attributes. Lock
bugs are exactly the class a chaos smoke can't reliably reproduce —
so the discipline is declared in source and verified statically:

* Annotate a shared mutable attribute where it is initialized::

      self._queue = collections.deque()  # guarded-by: _lock

* Every later read/write of ``self._queue`` (outside ``__init__``
  statements, which run happens-before any thread start) must then be
  lexically inside ``with self._lock:`` (a ``Condition`` constructed
  over a lock counts as that lock; a bare ``Condition()`` is its own
  lock), or inside a **lock-holding method**: one whose name ends in
  ``_locked``, whose def-line carries ``# guarded-by: <lock>``, or
  whose docstring says ``Callers hold self.<lock>`` (the conventions
  this codebase already uses). Violations are
  ``unlocked-guarded-access``.
* In lock-owning classes, an *unannotated* attribute mutated from
  more than one method with at least one mutation outside any lock is
  ``unguarded-shared-attr`` — annotate it, or guard the stray write.
* ``unknown-guard`` — an annotation naming a lock the class never
  constructs is a typo that would silently verify nothing.

Known limitation (docs/ANALYSIS.md): the checker reasons about
``self``-attribute access within the declaring class. Discipline on
foreign objects (``with slot.lock: slot.state = ...``) is out of
scope for the static pass and stays on the chaos smokes.
"""

from __future__ import annotations

import ast
import re
import typing as t

from torch_actor_critic_tpu.analysis.reachability import Project
from torch_actor_critic_tpu.analysis.walker import (
    FileContext,
    Finding,
    dotted_name,
)

__all__ = ["check"]

FAMILY = "lock-discipline"

_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
})
_HOLDS_DOC_RE = re.compile(
    r"[Cc]allers?\s+hold(?:s)?\s+(?:``)?self\.([A-Za-z_][A-Za-z0-9_]*)"
)


class _ClassModel:
    def __init__(self, ctx: FileContext, node: ast.ClassDef):
        self.ctx = ctx
        self.node = node
        self.name = node.name
        # lock attr -> canonical lock attr (Condition(self._lock)
        # aliases _lock; a bare Condition() is its own canonical lock).
        self.locks: t.Dict[str, str] = {}
        # guarded attr -> canonical lock attr (from annotations).
        self.guarded: t.Dict[str, t.Tuple[str, int]] = {}
        self.methods: t.List[ast.FunctionDef] = [
            n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self.spawns_thread = any(
            isinstance(n, ast.Call)
            and (dotted_name(n.func) or "").endswith("Thread")
            for n in ast.walk(node)
        )
        self._collect_locks()
        self._collect_annotations()

    def owns(self, node: ast.AST) -> bool:
        """True when ``node``'s nearest enclosing class is this class —
        a nested ClassDef's ``self`` is NOT ours and is modeled
        separately."""
        for anc in self.ctx.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc is self.node
        return False  # pragma: no cover - we only walk our subtree

    def _self_assigns(self) -> t.Iterator[t.Tuple[str, ast.stmt]]:
        for node in ast.walk(self.node):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            if not self.owns(node):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for target in targets:
                name = dotted_name(target)
                if name and name.startswith("self.") and name.count(".") == 1:
                    yield name.split(".", 1)[1], node

    def _collect_locks(self):
        for attr, assign in self._self_assigns():
            v = assign.value
            if not isinstance(v, ast.Call):
                continue
            ctor = dotted_name(v.func)
            if ctor not in _LOCK_CTORS:
                continue
            canonical = attr
            if ctor.endswith("Condition") and v.args:
                inner = dotted_name(v.args[0])
                if inner and inner.startswith("self."):
                    canonical = inner.split(".", 1)[1]
            self.locks[attr] = canonical

    def _collect_annotations(self):
        for attr, assign in self._self_assigns():
            lock = self.ctx.guarded_by.get(assign.lineno)
            if lock is None and assign.end_lineno != assign.lineno:
                lock = self.ctx.guarded_by.get(assign.end_lineno or 0)
            if lock is None:
                continue
            self.guarded[attr] = (lock, assign.lineno)

    def canonical(self, lock: str) -> str:
        return self.locks.get(lock, lock)

    def holds(self, fn: ast.AST) -> t.Set[str]:
        """Canonical locks a method/function declares it is called
        under (name suffix, def-line annotation, docstring convention)."""
        out: t.Set[str] = set()
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return out
        if fn.name.endswith("_locked") and "_lock" in self.locks:
            out.add(self.canonical("_lock"))
        first_body_line = fn.body[0].lineno if fn.body else fn.lineno + 1
        for line in range(fn.lineno, first_body_line + 1):
            lock = self.ctx.guarded_by.get(line)
            if lock is not None:
                out.add(self.canonical(lock))
        doc = ast.get_docstring(fn)
        if doc:
            for m in _HOLDS_DOC_RE.finditer(doc):
                out.add(self.canonical(m.group(1)))
        return out


def _with_locks(ctx: FileContext, model: _ClassModel, node: ast.AST) -> t.Set[str]:
    """Canonical locks held lexically at ``node`` via enclosing
    ``with self.<lock>:`` blocks and lock-holding enclosing functions."""
    held: t.Set[str] = set()
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                name = dotted_name(item.context_expr)
                if name and name.startswith("self."):
                    attr = name.split(".", 1)[1]
                    if attr in model.locks:
                        held.add(model.canonical(attr))
        elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            held.update(model.holds(anc))
        elif isinstance(anc, ast.ClassDef):
            break
    return held


def _innermost_fn(ctx: FileContext, node: ast.AST) -> ast.AST | None:
    return ctx.enclosing_function(node)


def _method_of(model: _ClassModel, ctx: FileContext, node: ast.AST) -> str | None:
    """Name of the class-level method whose subtree contains node."""
    last = None
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            last = anc
        elif isinstance(anc, ast.ClassDef):
            return last.name if (last is not None and last in model.node.body) else None
    return None


def check(project: Project) -> t.List[Finding]:
    findings: t.List[Finding] = []
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                _check_class(ctx, _ClassModel(ctx, node), findings)
    return findings


def _check_class(ctx: FileContext, model: _ClassModel, findings: t.List[Finding]):
    if not model.locks:
        # guarded-by annotations without any lock in the class are
        # reported; otherwise nothing to verify here.
        for attr, (lock, line) in model.guarded.items():
            findings.append(Finding(
                "unknown-guard", ctx.path, line, 0,
                f"{model.name}.{attr} declares guarded-by: {lock} but the "
                "class constructs no threading.Lock/RLock/Condition",
                "construct the lock, or drop the stale annotation",
            ))
        return

    canonical_locks = set(model.locks.values()) | set(model.locks)
    for attr, (lock, line) in model.guarded.items():
        if lock not in canonical_locks:
            findings.append(Finding(
                "unknown-guard", ctx.path, line, 0,
                f"{model.name}.{attr} declares guarded-by: {lock} but the "
                f"class only constructs {sorted(model.locks)}",
                "fix the annotation to name a real lock attribute",
            ))

    # -------------------------------------------- annotated-attr accesses
    mutations: t.Dict[str, t.Dict[str, t.List[t.Tuple[ast.AST, bool]]]] = {}
    for node in ast.walk(model.node):
        attr_node: ast.Attribute | None = None
        if isinstance(node, ast.Attribute):
            attr_node = node
        if attr_node is None or not model.owns(attr_node):
            continue
        name = dotted_name(attr_node)
        if not name or not name.startswith("self.") or name.count(".") != 1:
            continue
        attr = name.split(".", 1)[1]
        if attr in model.locks:
            continue
        fn = _innermost_fn(ctx, attr_node)
        in_init_body = (
            isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            and fn.name == "__init__"
            and fn in model.node.body
        )
        is_store = isinstance(attr_node.ctx, (ast.Store, ast.Del))
        held = _with_locks(ctx, model, attr_node)

        if attr in model.guarded:
            lock, _ = model.guarded[attr]
            want = model.canonical(lock)
            if in_init_body:
                continue  # construction happens-before thread start
            if want not in held:
                access = "write of" if is_store else "read of"
                findings.append(Finding(
                    "unlocked-guarded-access", ctx.path,
                    attr_node.lineno, attr_node.col_offset,
                    f"{access} {model.name}.{attr} (guarded-by: {lock}) "
                    f"outside `with self.{lock}`",
                    f"take the lock, or mark the enclosing method "
                    f"lock-holding (`# guarded-by: {lock}` on the def "
                    "line / a 'Callers hold self."
                    f"{lock}' docstring) if every caller already holds it",
                ))
        elif is_store and not in_init_body:
            meth = _method_of(model, ctx, attr_node)
            if meth is not None and meth != "__init__":
                mutations.setdefault(attr, {}).setdefault(meth, []).append(
                    (attr_node, bool(held))
                )

    # ----------------------------------------------- unannotated shared
    for attr, by_method in sorted(mutations.items()):
        if len(by_method) < 2:
            continue
        unlocked = [
            node
            for sites in by_method.values()
            for node, held in sites
            if not held
        ]
        if not unlocked:
            continue  # every write is already lock-protected; the
            # annotation sweep picks these up, the rule stays quiet
        node = min(unlocked, key=lambda n: n.lineno)
        findings.append(Finding(
            "unguarded-shared-attr", ctx.path, node.lineno, node.col_offset,
            f"{model.name}.{attr} is mutated from "
            f"{len(by_method)} methods ({', '.join(sorted(by_method))}) "
            "with at least one write outside any lock, and carries no "
            "guarded-by annotation",
            "annotate the attribute (`# guarded-by: <lock>`) where it is "
            "initialized and guard every access, or confine mutation to "
            "one thread",
        ))
