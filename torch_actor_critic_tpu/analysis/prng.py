"""prng-discipline rules: key hygiene over the def-use chains.

``jax.random`` guarantees independent streams only for DISTINCT keys;
every hazard in this family produces correlated (often identical)
randomness that no test asserting "is finite / has the right shape"
will ever catch — replay rows sampled twice, exploration noise
repeating per epoch, population members collapsing onto one stream.
The engine-key bug PR 1's review caught (warmup reusing one key across
buckets after donation deleted it) sat in exactly this class.

Three rules over :mod:`~torch_actor_critic_tpu.analysis.dataflow`'s
per-function event streams (branch-exclusivity aware — arms of one
``if`` never execute in sequence):

* ``key-reuse`` — a key consumed by two sinks without an intervening
  rebind. A *sink* is any use that derives randomness or hands the key
  on (a ``jax.random.<dist>`` draw, an ``apply(..., key, ...)`` call,
  a capture into a carry/return). The sound idiom is destructive:
  ``key, sub = jax.random.split(key)`` — the rebind kills the old
  value in the same statement.
* ``key-split-nondestructive`` — ``sub = jax.random.split(key)``
  spelling that silently keeps ``key`` live, followed by another
  consumption of ``key``: ``split`` is deterministic, so the children
  overlap with any later use of the parent (and a second
  ``split(key)`` yields the SAME children). Splitting without
  rebinding is fine only when the parent is never touched again.
* ``key-loop-reuse`` — a key consumed inside a loop while bound
  outside it and never rebound in the body: every iteration draws from
  the identical key (the warmup-across-buckets shape of the PR-1 bug).

``jax.random.fold_in(key, data)`` is exempt as a consumer: deriving
per-step/per-device subkeys from one parent with distinct fold data is
the sanctioned decorrelation idiom on every fused loop (``fold_in(rng,
dev)``), and whether the data differs per call is not statically
decidable. Reads of key *metadata* (``key.shape``) and subscripted
reads of key ARRAYS (``keys[i]`` — distinct rows are distinct keys)
are not consumption either.

A name is a key if it is spelled like one (``key``, ``rng``,
``*_key``/``*_keys``, ``k_*``) or assigned from a key-producing call
(``jax.random.key/PRNGKey/split/fold_in/wrap_key_data``) — both
checked per function, no interprocedural guessing.
"""

from __future__ import annotations

import ast
import typing as t

from torch_actor_critic_tpu.analysis.dataflow import (
    FlowScope,
    NameEvent,
    function_events,
    tracked_key,
)
from torch_actor_critic_tpu.analysis.reachability import Project
from torch_actor_critic_tpu.analysis.walker import (
    FileContext,
    Finding,
    dotted_name,
)

__all__ = ["check"]

FAMILY = "prng-discipline"

# Key producers: assignment from these marks the target as a key.
_KEY_PRODUCERS = frozenset({
    "key", "PRNGKey", "split", "fold_in", "wrap_key_data", "clone",
})
# Spelling-based key detection (exact names / affixes). Deliberately
# excludes bare `k` (ubiquitous dict-iteration name); spelling only
# counts in functions that touch jax.random at all — `for key in
# metrics:` in a pure-host module is a dict key, not a PRNG key.
_KEY_NAMES = frozenset({"key", "rng", "subkey", "act_key"})
_KEY_SUFFIXES = ("_key", "_keys", "_rng")
_KEY_PREFIXES = ("k_",)

_RANDOM_HEADS = frozenset({"jax", "random", "jrandom", "jr"})

# Callees that read key METADATA or raw bytes without consuming the
# stream: `key_data`/`key_impl` (serialization, utils/checkpoint.py),
# the repo's `_is_prng_key` predicate, and `_abstract_args` (the
# ShapeDtypeStruct capture the cost registry lowers with — shapes
# only, docs/ANALYSIS.md). Passing a key to these is not a sink.
_METADATA_SINKS = frozenset({
    "key_data", "key_impl", "_is_prng_key", "_abstract_args",
})


def _random_call_kind(name: str | None) -> str | None:
    """'split' / 'fold_in' for jax.random.{split,fold_in} spellings,
    None for anything else."""
    if not name:
        return None
    parts = name.split(".")
    last = parts[-1]
    if last not in ("split", "fold_in"):
        return None
    if len(parts) == 1:
        return None  # bare split() is almost always str.split
    if parts[0] in _RANDOM_HEADS or parts[-2] == "random":
        return last
    return None


def _is_key_name(key: str) -> bool:
    last = key.rsplit(".", 1)[-1].lower()
    if last in _KEY_NAMES:
        return True
    return last.endswith(_KEY_SUFFIXES) or last.startswith(_KEY_PREFIXES)


def _assigned_keys(fn_node: ast.AST) -> t.Set[str]:
    """Names assigned from key-producing jax.random calls."""
    out: t.Set[str] = set()
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if isinstance(value, ast.Subscript):
            value = value.value  # split(k, n)[0]
        if not isinstance(value, ast.Call):
            continue
        name = dotted_name(value.func)
        if not name:
            continue
        parts = name.split(".")
        if parts[-1] not in _KEY_PRODUCERS:
            continue
        if len(parts) >= 2 and not (
            parts[0] in _RANDOM_HEADS or parts[-2] == "random"
        ):
            continue
        if len(parts) == 1 and parts[-1] not in ("PRNGKey",):
            continue
        for target in node.targets:
            if isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    k = tracked_key(elt)
                    if k:
                        out.add(k)
            else:
                k = tracked_key(target)
                if k:
                    out.add(k)
    return out


def _classify_load(
    scope: FlowScope, event: NameEvent
) -> t.Tuple[str, ast.Call | None]:
    """('exempt'|'split'|'sink', enclosing call) for one key read.

    Only CALL ARGUMENTS consume a key: comparisons (``key is None``),
    metadata reads (``key.dtype``), key-array indexing (``keys[i]`` —
    distinct rows are distinct keys) and plain captures are not
    consumption (precision over recall: the sound split idiom rebinds,
    so an unsound capture resurfaces at its eventual call site)."""
    node = event.node
    parent = scope._parents.get(node)
    if isinstance(parent, ast.Attribute) and parent.value is node:
        return "exempt", None
    if isinstance(parent, ast.Subscript) and parent.value is node:
        return "exempt", None
    # A keyword argument under a key-spelled name (`EnvState(rng=...)`,
    # `replace(rng=...)`) is a carry — the key rides a struct onward,
    # it is not drawn from here.
    if isinstance(parent, ast.keyword) and parent.arg is not None and (
        _is_key_name(parent.arg)
    ):
        return "exempt", None
    # Innermost call whose ARGUMENT list carries the read.
    cur: ast.AST | None = node
    while cur is not None and not isinstance(cur, ast.stmt):
        up = scope._parents.get(cur)
        if isinstance(up, ast.Call) and cur is not up.func:
            name = dotted_name(up.func)
            kind = _random_call_kind(name)
            if kind == "fold_in":
                return "exempt", up
            if kind == "split":
                return "split", up
            if name and name.rsplit(".", 1)[-1] in _METADATA_SINKS:
                return "exempt", up
            return "sink", up
        if isinstance(up, ast.Call):  # the read IS the callee
            return "exempt", None
        cur = up
    return "exempt", None


def _check_function(
    ctx: FileContext,
    scope: FlowScope,
    keys: t.Set[str],
    findings: t.List[Finding],
) -> None:
    for key in sorted(keys):
        events = [
            e for e in function_events(scope, {key}) if not e.closure
        ]
        if not events:
            continue
        consumes: t.List[t.Tuple[NameEvent, str]] = []
        flagged = False
        for e in events:
            if e.kind == "store":
                # Destructive rebind: earlier consumes are dead on
                # every path through this store. Conservative: any
                # store clears the slate for reads it reaches; reads
                # on incompatible paths are handled by `reaches`.
                consumes = [
                    (c, k) for c, k in consumes
                    if not scope.reaches(e.node, c.node)
                    and not scope.reaches(c.node, e.node)
                ]
                continue
            kind, _call = _classify_load(scope, e)
            if kind == "exempt":
                continue
            # ---- loop rule: consumed each iteration, never rebound
            loops = scope.loops_enclosing(e.node)
            if loops and not flagged:
                loop = loops[0]
                stored_in_loop = any(
                    s.kind == "store"
                    and any(
                        l2 is loop
                        for l2 in scope.loops_enclosing(s.node)
                    )
                    for s in events
                )
                if not stored_in_loop:
                    findings.append(Finding(
                        "key-loop-reuse", ctx.path,
                        getattr(e.node, "lineno", 0),
                        getattr(e.node, "col_offset", 0),
                        f"PRNG key {key!r} is consumed inside a loop "
                        "but bound outside it and never rebound in the "
                        "body: every iteration draws from the "
                        "IDENTICAL key (identical randomness)",
                        "split per iteration — `key, sub = "
                        "jax.random.split(key)` inside the loop, or "
                        "fold_in the loop index",
                    ))
                    flagged = True
                    continue
            # ---- pair rule
            if not flagged:
                for prev, prev_kind in consumes:
                    if not scope.reaches(prev.node, e.node):
                        continue
                    if prev_kind == "split":
                        findings.append(Finding(
                            "key-split-nondestructive", ctx.path,
                            getattr(e.node, "lineno", 0),
                            getattr(e.node, "col_offset", 0),
                            f"PRNG key {key!r} was split "
                            f"non-destructively on line "
                            f"{getattr(prev.node, 'lineno', 0)} (the "
                            "split did not rebind it) and is consumed "
                            "again here: the parent's later use "
                            "overlaps the children's streams",
                            "rebind at the split — `key, sub = "
                            "jax.random.split(key)` — so the stale "
                            "parent cannot leak forward",
                        ))
                    else:
                        findings.append(Finding(
                            "key-reuse", ctx.path,
                            getattr(e.node, "lineno", 0),
                            getattr(e.node, "col_offset", 0),
                            f"PRNG key {key!r} is consumed a second "
                            "time without an intervening split "
                            f"(first consumed on line "
                            f"{getattr(prev.node, 'lineno', 0)}): both "
                            "sinks draw IDENTICAL randomness",
                            "split before each sink — `key, sub = "
                            "jax.random.split(key)` — and hand each "
                            "consumer its own subkey",
                        ))
                    flagged = True
                    break
            consumes.append((e, kind))


def _fn_key_facts(
    fn: ast.AST,
) -> t.Tuple[bool, t.Set[str], t.Set[str]]:
    """(mentions jax.random, names passed to jax.random.* calls,
    names ever used as a callee) — the provenance evidence key-ness
    gating needs."""
    mentions_random = False
    random_args: t.Set[str] = set()
    called: t.Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        ck = tracked_key(node.func)
        if ck is not None:
            called.add(ck)
        if not name:
            continue
        parts = name.split(".")
        if "random" not in parts or parts[0] not in ("jax", "jrandom", "jr"):
            continue  # np.random/stdlib random are host-random, not keys
        mentions_random = True
        # Only the KEY argument position marks key-ness: arg 0 of a
        # jax.random consumer (split/fold_in/normal/...), or `key=`.
        # Producers take seeds/raw data there, not keys.
        if parts[-1] in ("key", "PRNGKey", "wrap_key_data"):
            continue
        if parts[-1] in _METADATA_SINKS:
            continue  # metadata reads neither consume nor confer key-ness
        if node.args:
            k = tracked_key(node.args[0])
            if k is not None:
                random_args.add(k)
        for kw in node.keywords:
            if kw.arg == "key":
                k = tracked_key(kw.value)
                if k is not None:
                    random_args.add(k)
    return mentions_random, random_args, called


def check(project: Project) -> t.List[Finding]:
    findings: t.List[Finding] = []
    for ctx in project.files:
        for info in ctx.functions:
            fn = info.node
            scope = FlowScope(ctx, fn)
            mentions_random, random_args, called = _fn_key_facts(fn)
            # Key-ness needs provenance: produced by jax.random, fed to
            # jax.random, or key-spelled in a function that uses
            # jax.random at all. Names the function CALLS are
            # callables, never keys (`self._next_key()`).
            keys = _assigned_keys(fn) | random_args
            if mentions_random:
                for node in ast.walk(fn):
                    k = tracked_key(node)
                    if k is not None and _is_key_name(k):
                        keys.add(k)
            keys -= called
            keys.discard("self")
            if keys:
                _check_function(ctx, scope, keys, findings)
    return findings
