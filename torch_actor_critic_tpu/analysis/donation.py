"""donation-safety rules: dataflow over donated buffers.

PR 1's very first review bug — the serving engine donating the PRNG
key buffer warmup then reused across buckets — is the canonical member
of this family: a value passed at a ``donate_argnums`` position whose
buffer the callee aliases, then read again by the caller. On TPU the
read returns garbage (or XLA raises a deleted-buffer error); on CPU
donation is a no-op and the bug ships silently, which is why only a
static check catches it before chip time. PR 11's ``donated-reuse``
already covers the syntactic core (a locally-bound ``x = jax.jit(f,
donate_argnums=...)`` called and then a straight-line read); this
module adds the *dataflow* tier over ``analysis/dataflow.py``:

* ``use-after-donation`` — a value passed at a donated position of a
  donating callable and then read, returned, or captured afterwards in
  the caller. Donating callables resolve three ways the syntactic rule
  cannot: the checked :data:`DONATING_ENTRY_POINTS` table (host
  dispatch methods of the jit entry points — ``update_burst``,
  ``push_chunk``, ``epoch``), dict-of-jit bindings
  (``self._fwd = {True: jax.jit(...), ...}`` called through a
  subscript), and **conditionally** donating constructions
  (``donate_argnums=(1,) if donate else ()`` — donation happens on
  accelerators exactly where the bug bites, so the union of both
  branches is what must be safe). Loop-carried reuse is included: a
  donated value bound outside a loop and never rebound inside it is
  re-donated dead on the second iteration.
* ``undonated-push`` — ``buffer/replay.py``'s ``push`` docstring is a
  contract ("callers should jit push with ``donate_argnums=(0,)``"):
  a 1e6-slot HBM ring copied per store because one call site forgot
  the donation is a silent 2x-residency, 0.5x-throughput tax. Every
  ``jax.jit`` construction over the replay ``push`` must donate the
  ring argument.
* ``stale-donation-table`` — :data:`DONATING_ENTRY_POINTS` is checked,
  never trusted (the shard-map-allowlist precedent): every row's
  builder must still exist and still construct jits donating exactly
  the positions the row claims, so the table cannot drift from the
  code it describes.
"""

from __future__ import annotations

import ast
import typing as t

from torch_actor_critic_tpu.analysis.dataflow import (
    FlowScope,
    function_events,
    tracked_key,
)
from torch_actor_critic_tpu.analysis.reachability import Project, _is_wrapper
from torch_actor_critic_tpu.analysis.walker import (
    FileContext,
    Finding,
    dotted_name,
)

__all__ = ["check", "DONATING_ENTRY_POINTS"]

FAMILY = "donation-safety"

_JIT_MAKERS = frozenset({"jax.jit", "jit", "pjit", "jax.pmap", "pmap"})
_UNWRAP = frozenset({
    "jax.vmap", "vmap", "jax.pmap", "pmap", "partial", "functools.partial",
})


class DonationRow(t.NamedTuple):
    """One checked entry: where the donating program is built and how
    host code dispatches into it."""

    file: str                       # path suffix of the builder's file
    builder: str                    # builder qualname in that file
    method: str | None              # host dispatch method name (None =
    #                                 dispatched through a local jit
    #                                 binding the local collector sees)
    donated: t.Tuple[int, ...]      # positions the builder must donate


# Derived from reachability.ENTRY_POINTS: the donate_argnums contract
# of every jit entry point, plus the warmup-path push wrappers that
# share the same rings. `method` names how the host trainer/driver
# dispatches into the program — any `<recv>.<method>(...)` call site in
# the package is held to the donated positions. Verified every
# whole-package run (stale-donation-table).
DONATING_ENTRY_POINTS: t.Dict[str, DonationRow] = {
    "train/update_burst": DonationRow(
        "parallel/dp.py", "DataParallelSAC._build_burst",
        "update_burst", (0, 1),
    ),
    "train/population_burst": DonationRow(
        "parallel/population.py", "PopulationLearner.update_burst",
        "update_burst", (0, 1),
    ),
    "train/ondevice_epoch": DonationRow(
        "sac/ondevice.py", "OnDeviceLoop._build_epoch", "epoch", (0, 1),
    ),
    "train/population_epoch": DonationRow(
        "sac/ondevice.py", "PopulationOnDeviceLoop._build_epoch",
        "epoch", (0, 1),
    ),
    "train/scenario_epoch": DonationRow(
        "scenarios/loop.py", "ScenarioOnDeviceLoop._build_epoch",
        "epoch", (0, 1),
    ),
    "train/push_chunk": DonationRow(
        "parallel/dp.py", "DataParallelSAC.push_chunk",
        "push_chunk", (0,),
    ),
    "train/population_push_chunk": DonationRow(
        "parallel/population.py", "PopulationLearner.push_chunk",
        "push_chunk", (0,),
    ),
    "replay/prefetch_push": DonationRow(
        "replay/prefetch.py", "RefillPrefetcher._build_push",
        "push_into", (0,),
    ),
    "train/offline_burst": DonationRow(
        "replay/offline.py", "OfflineLearner._build_burst",
        "burst", (0,),
    ),
    "serve/forward": DonationRow(
        "serve/engine.py", "PolicyEngine._build_forwards", None, (1,),
    ),
    "serve/sharded_forward": DonationRow(
        "serve/sharded.py", "ShardedPolicyEngine._build_forwards",
        None, (1,),
    ),
}

# method name -> donated positions, for call-site matching.
_METHOD_DONATIONS: t.Dict[str, t.Tuple[int, ...]] = {
    row.method: row.donated
    for row in DONATING_ENTRY_POINTS.values()
    if row.method is not None
}


def _is_jit_maker(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and _is_wrapper(
        dotted_name(node.func), _JIT_MAKERS
    )


def _positions_of(value: ast.AST) -> t.Tuple[int, ...]:
    """Donated positions of a donate_argnums value expression, with
    IfExp branches UNIONED: `(1,) if donate else ()` donates on
    accelerators, which is exactly where use-after-donation bites."""
    if isinstance(value, ast.IfExp):
        return tuple(sorted(
            set(_positions_of(value.body)) | set(_positions_of(value.orelse))
        ))
    if isinstance(value, ast.Constant) and isinstance(value.value, int):
        return (value.value,)
    if isinstance(value, (ast.Tuple, ast.List)):
        out = []
        for elt in value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _donations_of_call(call: ast.Call) -> t.Tuple[t.Tuple[int, ...], bool]:
    """(positions, static): positions donated by a jit construction;
    ``static`` True when the spelling is an unconditional literal (the
    recompile-risk family's domain — skipped here to avoid flagging
    one hazard under two rule ids)."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        positions = _positions_of(kw.value)
        static = not isinstance(kw.value, ast.IfExp) and bool(positions)
        return positions, static
    return (), True


# --------------------------------------------------------- local sources


def _collect_donating_bindings(
    ctx: FileContext,
) -> t.Dict[str, t.Tuple[int, ...]]:
    """Bindings of donating callables the syntactic ``donated-reuse``
    rule cannot see: conditional donate spellings and dict-of-jit
    values (both keyed by the bound name / ``self.attr``)."""
    out: t.Dict[str, t.Tuple[int, ...]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        donated: t.Tuple[int, ...] = ()
        if _is_jit_maker(value):
            positions, static = _donations_of_call(t.cast(ast.Call, value))
            if positions and not static:
                donated = positions
        elif isinstance(value, ast.Dict) and value.values and all(
            _is_jit_maker(v) for v in value.values
        ):
            acc: t.Set[int] = set()
            for v in value.values:
                positions, _ = _donations_of_call(t.cast(ast.Call, v))
                acc.update(positions)
            donated = tuple(sorted(acc))
        if not donated:
            continue
        for target in node.targets:
            key = tracked_key(target)
            if key is not None:
                out[key] = donated
    return out


def _donated_call_sites(
    ctx: FileContext, bindings: t.Dict[str, t.Tuple[int, ...]]
) -> t.Iterator[t.Tuple[ast.Call, t.Tuple[int, ...], str]]:
    """(call, donated positions, why) for every donating call site in
    the file: table-matched dispatch methods, local conditional/dict
    jit bindings (incl. subscripted dict-jit calls)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        # Local bindings: direct call or dict-jit subscript call.
        base = func.value if isinstance(func, ast.Subscript) else func
        key = tracked_key(base)
        if key is not None and key in bindings:
            yield node, bindings[key], f"jitted callable {key!r}"
            continue
        if isinstance(func, ast.Attribute):
            positions = _METHOD_DONATIONS.get(func.attr)
            if positions is not None:
                yield node, positions, (
                    f"donating entry point .{func.attr}() "
                    "(analysis/donation.py DONATING_ENTRY_POINTS)"
                )


# ----------------------------------------------------------- the checks


def _check_use_after_donation(
    ctx: FileContext, findings: t.List[Finding]
) -> None:
    bindings = _collect_donating_bindings(ctx)
    scopes: t.Dict[ast.AST, FlowScope] = {}
    for info in ctx.functions:
        fn = info.node
        scope = scopes.setdefault(fn, FlowScope(ctx, fn))
        for call, positions, why in _donated_call_sites(ctx, bindings):
            if ctx.enclosing_function(call) is not fn:
                continue
            stmt = scope.statement_of(call)
            if stmt is None:
                continue
            for pos in positions:
                if pos >= len(call.args):
                    continue
                key = tracked_key(call.args[pos])
                if key is None or key == "self":
                    continue
                _check_one_donation(
                    ctx, scope, call, stmt, pos, key, why, findings
                )


def _check_one_donation(
    ctx: FileContext,
    scope: FlowScope,
    call: ast.Call,
    stmt: ast.stmt,
    pos: int,
    key: str,
    why: str,
    findings: t.List[Finding],
) -> None:
    events = function_events(scope, {key})
    end = stmt.end_lineno or stmt.lineno
    # Rebound by the donating statement itself (`state, buf, m =
    # burst(state, buf, chunk)`) — the sound pattern: later reads see
    # the callee's fresh output buffer.
    rebound_same_stmt = any(
        e.kind == "store" and scope.statement_of(e.node) is stmt
        for e in events
    )
    loops = scope.loops_enclosing(call)
    if loops and not rebound_same_stmt:
        loop = loops[0]
        stored_in_loop = any(
            e.kind == "store"
            and any(l2 is loop for l2 in scope.loops_enclosing(e.node))
            for e in events
        )
        if not stored_in_loop:
            findings.append(Finding(
                "use-after-donation", ctx.path, call.lineno, call.col_offset,
                f"{key!r} is donated (arg {pos}) to {why} inside a loop "
                "without being rebound in the loop body: the second "
                "iteration passes an already-donated buffer (garbage or "
                "a deleted-buffer error on TPU; silently fine on CPU "
                "where donation is a no-op)",
                "rebind the donated value from the callee's return "
                "inside the loop, or move the value's construction into "
                "the loop body",
            ))
            return
    if rebound_same_stmt:
        return
    for e in events:
        if e.kind != "load":
            continue
        line = getattr(e.node, "lineno", 0)
        if line <= end:
            continue
        if not scope.reaches(call, e.node):
            continue
        # A store between the call and this read (on a compatible
        # path) kills the donated value first.
        killed = any(
            s.kind == "store"
            and end < getattr(s.node, "lineno", 0) <= line
            and scope.reaches(s.node, e.node)
            for s in events
        )
        if killed:
            continue
        what = "captured by a closure" if e.closure else "read"
        findings.append(Finding(
            "use-after-donation", ctx.path, line,
            getattr(e.node, "col_offset", 0),
            f"{key!r} is {what} after being donated (arg {pos}, line "
            f"{call.lineno}) to {why}: its buffer may already be "
            "aliased by the callee (garbage on TPU; silently fine on "
            "CPU where donation is a no-op)",
            "use the callee's returned value, rebind the name from it, "
            "or stop donating this argument",
        ))
        return  # one finding per donated arg per call site


def _resolves_to_replay_push(ctx: FileContext, idx, arg: ast.AST) -> bool:
    """Does a jit-wrapped target resolve to buffer/replay.py's push
    (unwrapping vmap/partial layers)?"""
    if isinstance(arg, ast.Call):
        name = dotted_name(arg.func)
        if name and (
            name in _UNWRAP or name.rsplit(".", 1)[-1] in ("partial",)
        ):
            return bool(arg.args) and _resolves_to_replay_push(
                ctx, idx, arg.args[0]
            )
        return False
    name = dotted_name(arg)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    if last != "push":
        return False
    if ctx.path.endswith("buffer/replay.py"):
        return True
    sym = idx.symbol_imports.get(name)
    if sym is not None:
        return sym[0].endswith("buffer.replay") and sym[1] == "push"
    if "." in name:
        head = name.split(".")[0]
        mod = idx.module_aliases.get(head)
        return mod is not None and mod.endswith("buffer.replay")
    return False


def _check_undonated_push(
    project: Project, ctx: FileContext, findings: t.List[Finding]
) -> None:
    idx = project.indexes[ctx.path]
    for node in ast.walk(ctx.tree):
        if not _is_jit_maker(node):
            continue
        call = t.cast(ast.Call, node)
        if not call.args:
            continue
        if not _resolves_to_replay_push(ctx, idx, call.args[0]):
            continue
        positions, _ = _donations_of_call(call)
        if 0 in positions:
            continue
        findings.append(Finding(
            "undonated-push", ctx.path, call.lineno, call.col_offset,
            "replay push jitted WITHOUT donating the ring argument: "
            "XLA copies the full ring every store (2x HBM residency "
            "on a 1e6-slot buffer) — buffer/replay.py's docstring "
            "makes donation the contract",
            "jit with donate_argnums=(0,) and rebind the buffer from "
            "the return value",
        ))


def _check_table(project: Project, findings: t.List[Finding]) -> None:
    """stale-donation-table: every row's builder still exists and
    still donates exactly what the row claims."""
    if not any(
        p.endswith("torch_actor_critic_tpu/__init__.py")
        for p in project.by_path
    ):
        return  # partial runs can't tell a moved builder from un-linted
    for cost_name, row in DONATING_ENTRY_POINTS.items():
        path = next(
            (p for p in project.by_path if p.endswith(row.file)), None
        )
        ctx = project.by_path.get(path) if path else None
        fn = None
        if ctx is not None:
            fn = next(
                (f for f in ctx.functions if f.qualname == row.builder),
                None,
            )
        if fn is None:
            findings.append(Finding(
                "stale-donation-table", row.file, 1, 0,
                f"donation table row {cost_name!r}: builder "
                f"{row.builder!r} not found in {row.file!r}",
                "update analysis/donation.py DONATING_ENTRY_POINTS to "
                "the moved/renamed builder",
            ))
            continue
        donated: t.Set[int] = set()
        for node in ast.walk(fn.node):
            if _is_jit_maker(node):
                positions, _ = _donations_of_call(t.cast(ast.Call, node))
                donated.update(positions)
        if tuple(sorted(donated)) != tuple(sorted(row.donated)):
            findings.append(Finding(
                "stale-donation-table", t.cast(str, path),
                fn.node.lineno, 0,
                f"donation table row {cost_name!r} claims donated "
                f"positions {tuple(sorted(row.donated))} but builder "
                f"{row.builder!r} constructs jits donating "
                f"{tuple(sorted(donated))}",
                "fix the builder's donate_argnums or update "
                "DONATING_ENTRY_POINTS (analysis/donation.py) — and "
                "re-audit every dispatch call site",
            ))


def check(project: Project) -> t.List[Finding]:
    findings: t.List[Finding] = []
    _check_table(project, findings)
    for ctx in project.files:
        _check_use_after_donation(ctx, findings)
        _check_undonated_push(project, ctx, findings)
    return findings
