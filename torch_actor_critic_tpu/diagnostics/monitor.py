"""Host-side early-warning monitor over the epoch diagnostic stream.

The divergence sentinel (resilience/sentinel.py) is a *lagging*
detector: it fires when the training state already holds a NaN. The
monitor watches the per-epoch diagnostic aggregates for the patterns
that PRECEDE that NaN — a gradient-norm spike, a policy-entropy
collapse, a drifting Q bias — and emits ``early_warning`` telemetry
events plus :meth:`DivergenceSentinel.note_warning` bookkeeping, so an
operator (or an alerting rule over ``telemetry.jsonl``) sees trouble
epochs before the sentinel has to roll anything back.

Detection is a robust deviation rule, not fixed thresholds: each
watched key keeps an EMA of its value and of its absolute deviation
(an online MAD analogue), and a warning fires when the new value
departs from the EMA by more than ``k`` deviations in the configured
direction. This adapts to any env's loss/reward scale — the same rule
works on Pendulum (rewards O(10)) and dm_control (rewards O(1)) — and
a fired value is clipped before it updates the baseline, so one spike
cannot poison the EMA into accepting the next one. Everything is plain
deterministic float arithmetic: unit-testable with scripted sequences
(tests/test_diagnostics.py).
"""

from __future__ import annotations

import math
import typing as t

__all__ = ["DEFAULT_RULES", "DriftDetector", "EarlyWarningMonitor"]

# (kind, metric key, direction): `high` fires on upward excursions,
# `low` on downward, `shift` on either. Keys absent from a run's
# metrics (e.g. `entropy` under TD3) simply never arm.
DEFAULT_RULES: t.Tuple[t.Tuple[str, str, str], ...] = (
    ("grad_spike", "diag/grad_norm_q", "high"),
    ("grad_spike", "diag/grad_norm_pi", "high"),
    ("entropy_collapse", "entropy", "low"),
    ("q_bias_drift", "diag/q_bias", "shift"),
    # Decoupled plane (decoupled/): mean per-transition generation lag
    # drifting upward is the leading indicator of a sick actor↔serving
    # link — degraded actors feed ever-staler data until the admission
    # gate starts dropping it. Key absent outside decoupled runs.
    ("actor_lag_drift", "decoupled/actor_lag_mean", "high"),
)


class DriftDetector:
    """One-key robust deviation detector (EMA + EMA-of-|dev|)."""

    def __init__(
        self,
        kind: str,
        key: str,
        direction: str,
        k: float = 6.0,
        warmup: int = 3,
        alpha: float = 0.3,
    ):
        if direction not in ("high", "low", "shift"):
            raise ValueError(f"direction must be high/low/shift, got {direction!r}")
        self.kind = kind
        self.key = key
        self.direction = direction
        self.k = float(k)
        self.warmup = int(warmup)
        self.alpha = float(alpha)
        self.n = 0
        self.ema: float | None = None
        self.dev = 0.0

    def update(self, value: float) -> t.Optional[dict]:
        """Feed one epoch aggregate; returns a warning dict when the
        value breaches the deviation envelope, else None."""
        value = float(value)
        if not math.isfinite(value):
            # Non-finite is the sentinel's jurisdiction; the detector
            # keeps its baseline untouched.
            return None
        self.n += 1
        if self.ema is None:
            self.ema = value
            return None
        # Deviation floor: 5% of the baseline magnitude, so a key that
        # has been perfectly flat (dev ~ 0) still needs a material move
        # to fire, and a zero-baseline key doesn't fire on noise.
        spread = max(self.dev, 0.05 * abs(self.ema) + 1e-6)
        delta = value - self.ema
        fired: t.Optional[dict] = None
        if self.n > self.warmup:
            breach = (
                delta > self.k * spread
                if self.direction == "high"
                else -delta > self.k * spread
                if self.direction == "low"
                else abs(delta) > self.k * spread
            )
            if breach:
                fired = {
                    "kind": self.kind,
                    "key": self.key,
                    "value": value,
                    "baseline": self.ema,
                    "spread": spread,
                }
        # A fired value updates the baseline clipped to the envelope —
        # adapting to a genuine regime change over a few epochs while
        # refusing to swallow a one-epoch spike whole.
        upd = value
        if fired is not None:
            upd = min(max(value, self.ema - 3 * spread), self.ema + 3 * spread)
        self.dev += self.alpha * (abs(upd - self.ema) - self.dev)
        self.ema += self.alpha * (upd - self.ema)
        return fired


class EarlyWarningMonitor:
    """Rule set of :class:`DriftDetector` over the epoch diagnostics."""

    def __init__(
        self,
        rules: t.Sequence[t.Tuple[str, str, str]] = DEFAULT_RULES,
        k: float = 6.0,
        warmup: int = 3,
    ):
        self.detectors = [
            DriftDetector(kind, key, direction, k=k, warmup=warmup)
            for kind, key, direction in rules
        ]
        self.fired_total = 0

    def update(self, metrics: t.Mapping[str, t.Any]) -> t.List[dict]:
        """Feed one epoch's reduced diagnostics; returns the warnings
        that fired this epoch (possibly empty)."""
        out = []
        for d in self.detectors:
            if d.key in metrics:
                w = d.update(float(metrics[d.key]))
                if w is not None:
                    out.append(w)
        self.fired_total += len(out)
        return out
