"""Process-wide XLA recompilation watchdog.

A steady-state jit recompilation is one of the costliest silent
failures on TPU: a stray weak-typed scalar, a changed donation
pattern, or a hot-reload that alters a dtype makes XLA recompile a
program that was supposed to be cached — multi-second stalls that look
like "the accelerator is slow" rather than "we are compiling on the
hot path". The reference (and most RL stacks) has no way to even see
this happening.

The watchdog hooks :mod:`jax.monitoring` — specifically the
``/jax/core/compile/backend_compile_duration`` event, which fires
exactly once per actual XLA backend compile and never on a jit-cache
hit — and attributes each compile to a **source label** via a
thread-local context stack:

    with watchdog.source("train/update_burst"):
        state, buf, m = dp.update_burst(...)     # compiles land here

Sources that have declared themselves **steady** (``mark_steady``
with their label prefix) flag any further compile as an anomaly —
logged, counted, and surfaced on the serving ``/metrics`` snapshot and
in ``telemetry.jsonl``. Warmup/compile phases inside a steady regime
(a new model slot registering mid-flight) wrap themselves in
:meth:`expected` to stay anomaly-free while still being counted.

One singleton per process (:func:`get_watchdog`); the listener is
registered once on :meth:`install` and afterwards costs one string
compare per monitoring event. Compile counts are *XLA program*
compiles, which can exceed user-visible jit sites (helper programs,
multi-computation lowerings) — honest accounting, documented in
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
import typing as t

logger = logging.getLogger(__name__)

__all__ = ["RecompilationWatchdog", "get_watchdog"]

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# Persistent-compilation-cache counters (aot/cache.py, docs/
# OBSERVABILITY.md "Cold start"): these fire as PLAIN monitoring
# events, one per cache probe. Crucially, a cache HIT still fires
# _COMPILE_EVENT (the retrieval runs through backend_compile), so
# compile counts alone cannot tell a warm-start from a cold one —
# the hit/miss pair is what distinguishes "loaded from the bundle's
# cache" from "paid a real XLA compile".
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_UNATTRIBUTED = "unattributed"
_MAX_ANOMALIES = 100  # bounded memory; the counter keeps the true total
_MAX_COMPILE_LOG = 256  # newest per-compile records kept for the trace
_MAX_REJECT_REASONS = 20  # newest bundle-rejection reasons kept


class _SourceCtx:
    """Reentrant, reusable context manager pushing a source label onto
    the owning watchdog's thread-local stack."""

    __slots__ = ("_wd", "_label")

    def __init__(self, wd: "RecompilationWatchdog", label: str):
        self._wd = wd
        self._label = label

    def __enter__(self):
        stack = getattr(self._wd._tls, "stack", None)
        if stack is None:
            stack = self._wd._tls.stack = []
        stack.append(self._label)
        return self

    def __exit__(self, *exc):
        self._wd._tls.stack.pop()
        return False


class _ExpectedCtx:
    __slots__ = ("_wd",)

    def __init__(self, wd: "RecompilationWatchdog"):
        self._wd = wd

    def __enter__(self):
        self._wd._tls.expected = getattr(self._wd._tls, "expected", 0) + 1
        return self

    def __exit__(self, *exc):
        self._wd._tls.expected -= 1
        return False


class _BundleLoadCtx:
    """Thread-local marker for warm-start bundle loading (aot/):
    compiles in this extent are executables arriving from the bundle's
    pre-populated compilation cache, NOT warmup work this process paid
    for. They get their own counter — classifying them as ``expected``
    (the warmup suppression) would make a broken bundle (every "load"
    actually a full compile) indistinguishable from a working one."""

    __slots__ = ("_wd",)

    def __init__(self, wd: "RecompilationWatchdog"):
        self._wd = wd

    def __enter__(self):
        self._wd._tls.bundle = getattr(self._wd._tls, "bundle", 0) + 1
        return self

    def __exit__(self, *exc):
        self._wd._tls.bundle -= 1
        return False


class RecompilationWatchdog:
    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.installed = False  # guarded-by: _lock
        self.compiles_total = 0  # guarded-by: _lock
        self.by_source: t.Dict[str, int] = {}  # guarded-by: _lock
        self.compile_time_s = 0.0  # guarded-by: _lock
        self.post_steady_total = 0  # guarded-by: _lock
        self.anomalies: t.List[dict] = []  # guarded-by: _lock
        # Three-way compile classification (docs/OBSERVABILITY.md
        # "Cold start & warm-start bundles"): every backend compile is
        # exactly one of live (a dispatch paid it — must be 0 in
        # steady state), warmup (inside expected(): deliberate
        # pre-compilation), or bundle-load (inside bundle_load():
        # served from a warm-start bundle's cache). Previously warmup
        # and bundle loads would both have landed in `expected`,
        # hiding a broken bundle behind the warmup suppression.
        self.live_compiles = 0  # guarded-by: _lock
        self.warmup_compiles = 0  # guarded-by: _lock
        self.bundle_load_compiles = 0  # guarded-by: _lock
        self._live_by_source: t.Dict[str, int] = {}  # guarded-by: _lock
        # Warm-start bundle accounting (aot/bundle.py): programs
        # successfully loaded from a bundle vs bundles rejected on a
        # fingerprint/aval mismatch (rejection falls back to live
        # compile — loudly, and counted here).
        self.bundle_hits = 0  # guarded-by: _lock
        self.bundle_rejected = 0  # guarded-by: _lock
        self._bundle_reject_reasons: collections.deque = (  # guarded-by: _lock
            collections.deque(maxlen=_MAX_REJECT_REASONS)
        )
        # Persistent compilation-cache probes (aot/cache.py).
        self.cache_hits_total = 0  # guarded-by: _lock
        self.cache_misses_total = 0  # guarded-by: _lock
        self._steady_prefixes: t.Set[str] = set()  # guarded-by: _lock
        # Bounded per-compile record ring (source, end wall time,
        # duration): the cross-plane trace export draws compile spans
        # from here (telemetry/traceview.py). Newest-wins, so a long
        # run keeps the recent window a trace would cover anyway.
        self._compile_log: collections.deque = (  # guarded-by: _lock
            collections.deque(maxlen=_MAX_COMPILE_LOG)
        )

    # ------------------------------------------------------------ install

    def install(self) -> "RecompilationWatchdog":
        """Register the jax.monitoring listener (idempotent)."""
        with self._lock:
            if self.installed:
                return self
            self.installed = True
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(self._on_event)
        # Plain-event listener for the persistent-cache hit/miss pair
        # (no duration payload; see _CACHE_HIT_EVENT above).
        jax.monitoring.register_event_listener(self._on_plain_event)
        return self

    # ------------------------------------------------------- attribution

    def source(self, label: str) -> _SourceCtx:
        """Context manager attributing compiles in the dynamic extent
        of the with-block (same thread) to ``label``. The returned
        object is reusable and reentrant — hot paths can construct it
        once and enter it per dispatch."""
        return _SourceCtx(self, label)

    def expected(self) -> _ExpectedCtx:
        """Context manager marking compiles as expected: counted, but
        never flagged as steady-state anomalies (warmup of a model slot
        registered after the serving plane went steady)."""
        return _ExpectedCtx(self)

    def bundle_load(self) -> _BundleLoadCtx:
        """Context manager marking compiles as warm-start bundle loads
        (aot/): counted under ``bundle_load_compiles`` — a THIRD class
        next to live and warmup, never a steady-state anomaly. Takes
        precedence over :meth:`expected` when nested (a bundle-armed
        warmup wraps both)."""
        return _BundleLoadCtx(self)

    # ---------------------------------------------------- bundle counters

    def note_bundle_hit(self, n: int = 1) -> None:
        """Count ``n`` programs successfully loaded from a warm-start
        bundle (aot/bundle.py calls this once per program it serves
        from the bundle's cache)."""
        with self._lock:
            self.bundle_hits += int(n)

    def note_bundle_rejected(self, reason: str) -> None:
        """Count one warm-start bundle rejection (fingerprint or aval
        mismatch). The caller falls back to live compilation; the
        rejection is logged loudly here and surfaced on /metrics."""
        with self._lock:
            self.bundle_rejected += 1
            self._bundle_reject_reasons.append(str(reason)[:300])
        logger.warning(
            "warm-start bundle REJECTED (falling back to live "
            "compile): %s — rebuild the bundle against this "
            "environment (docs/SERVING.md 'Cold start & warm-start "
            "bundles')",
            reason,
        )

    # ----------------------------------------------------- steady regime

    def mark_steady(self, prefix: str) -> None:
        """Declare sources starting with ``prefix`` steady: every later
        compile attributed to them is an anomaly. Scoped by prefix so
        the training and serving planes (and independent test cases in
        one process) manage their own regimes."""
        with self._lock:
            self._steady_prefixes.add(prefix)

    def clear_steady(self, prefix: str) -> None:
        with self._lock:
            self._steady_prefixes.discard(prefix)

    # ----------------------------------------------------------- listener

    def _on_plain_event(self, name: str, **kw) -> None:
        if name == _CACHE_HIT_EVENT:
            with self._lock:
                self.cache_hits_total += 1
        elif name == _CACHE_MISS_EVENT:
            with self._lock:
                self.cache_misses_total += 1

    def _on_event(self, name: str, secs: float, **kw) -> None:
        if name != _COMPILE_EVENT:
            return
        stack = getattr(self._tls, "stack", None)
        src = stack[-1] if stack else _UNATTRIBUTED
        bundle = getattr(self._tls, "bundle", 0) > 0
        expected = not bundle and getattr(self._tls, "expected", 0) > 0
        kind = "bundle" if bundle else ("warmup" if expected else "live")
        with self._lock:
            self.compiles_total += 1
            self.by_source[src] = self.by_source.get(src, 0) + 1
            self.compile_time_s += secs
            if bundle:
                self.bundle_load_compiles += 1
            elif expected:
                self.warmup_compiles += 1
            else:
                self.live_compiles += 1
                self._live_by_source[src] = (
                    self._live_by_source.get(src, 0) + 1
                )
            self._compile_log.append({
                "source": src,
                "time": time.time(),  # the event fires at compile END
                "duration_s": round(secs, 4),
                "expected": expected,
                "kind": kind,
            })
            steady = not (expected or bundle) and any(
                src.startswith(p) for p in self._steady_prefixes
            )
            if not steady:
                return
            self.post_steady_total += 1
            anomaly = {
                "source": src,
                "time": time.time(),
                "duration_s": round(secs, 3),
                "count_at": self.compiles_total,
            }
            if len(self.anomalies) < _MAX_ANOMALIES:
                self.anomalies.append(anomaly)
        logger.warning(
            "steady-state XLA recompilation from %s (%.2fs): a program "
            "that should be jit-cached was rebuilt on the hot path — "
            "check for varying shapes/dtypes/donation at this call site "
            "(docs/OBSERVABILITY.md recompile-watchdog runbook)",
            src, secs,
        )

    # ----------------------------------------------------------- reports

    def compile_log(self) -> t.List[dict]:
        """The newest per-compile records (bounded ring), each
        ``{source, time, duration_s, expected}`` — the trace export's
        compile-span source (``time`` is the compile's END on the wall
        clock)."""
        with self._lock:
            return [dict(r) for r in self._compile_log]

    def snapshot(self) -> dict:
        """``/metrics``-style view (also embedded in telemetry.jsonl
        epoch events by the Trainer)."""
        with self._lock:
            return {
                "compiles_total": self.compiles_total,
                "compile_time_s": round(self.compile_time_s, 3),
                "by_source": dict(self.by_source),
                "post_steady_compiles": self.post_steady_total,
                "anomalies": list(self.anomalies),
                # Cold-start accounting (aot/, docs/OBSERVABILITY.md):
                # live / warmup / bundle-load are DISJOINT classes of
                # compiles_total; hits/misses count persistent-cache
                # probes; bundle_* count warm-start bundle outcomes.
                "live_compiles": self.live_compiles,
                "warmup_compiles": self.warmup_compiles,
                "bundle_load_compiles": self.bundle_load_compiles,
                "live_by_source": dict(self._live_by_source),
                "bundle_hits": self.bundle_hits,
                "bundle_rejected": self.bundle_rejected,
                "bundle_reject_reasons": list(self._bundle_reject_reasons),
                "cache_hits_total": self.cache_hits_total,
                "cache_misses_total": self.cache_misses_total,
            }

    def live_compiles_for(self, prefix: str = "") -> int:
        """Live (neither warmup nor bundle-load) compiles attributed to
        sources starting with ``prefix`` ("" = every source)."""
        with self._lock:
            return sum(
                n for src, n in self._live_by_source.items()
                if src.startswith(prefix)
            )

    def assert_zero_live(self, prefix: str = "") -> None:
        """The steady-state cold-start assertion (aot/): raise if any
        live compile has been attributed to sources under ``prefix``.
        A warm-started worker must answer every request from warmup or
        bundle-loaded executables — the coldstart smoke and the serve
        plane's health checks call this after a flood."""
        live = self.live_compiles_for(prefix)
        if live:
            with self._lock:
                offenders = {
                    src: n for src, n in self._live_by_source.items()
                    if src.startswith(prefix)
                }
            raise AssertionError(
                f"live_compiles == 0 violated: {live} live compile(s) "
                f"under prefix {prefix!r} ({offenders}) — a request "
                "paid an XLA compile that warmup or the warm-start "
                "bundle should have covered (docs/SERVING.md 'Cold "
                "start & warm-start bundles')"
            )

    def reset(self) -> None:
        """Zero all counts and steady regimes (test isolation; the
        listener registration is left in place)."""
        with self._lock:
            self.compiles_total = 0
            self.by_source = {}
            self.compile_time_s = 0.0
            self.post_steady_total = 0
            self.anomalies = []
            self._steady_prefixes = set()
            self._compile_log.clear()
            self.live_compiles = 0
            self.warmup_compiles = 0
            self.bundle_load_compiles = 0
            self._live_by_source = {}
            self.bundle_hits = 0
            self.bundle_rejected = 0
            self._bundle_reject_reasons.clear()
            self.cache_hits_total = 0
            self.cache_misses_total = 0


_WATCHDOG: RecompilationWatchdog | None = None
_SINGLETON_LOCK = threading.Lock()


def get_watchdog() -> RecompilationWatchdog:
    """The process-wide watchdog (created lazily, never installed until
    someone calls :meth:`~RecompilationWatchdog.install`)."""
    global _WATCHDOG
    with _SINGLETON_LOCK:
        if _WATCHDOG is None:
            _WATCHDOG = RecompilationWatchdog()
        return _WATCHDOG
