"""On-device learning-health observability (docs/OBSERVABILITY.md).

PR 3's telemetry answers *where the wall-clock time goes*; this
subsystem answers *whether learning is healthy* — computed in-graph at
zero extra host<->device syncs (the Podracer keep-it-compiled
principle, arXiv:2104.06272) and surfaced through the same telemetry
sinks and /metrics schema (the monitoring-first platform posture of
TorchBeast, arXiv:1910.03552):

- :mod:`ingraph` — the device half: gradient global-norms and
  update-to-param ratios, Q-value stats (min/max/ensemble spread/
  target-vs-online bias), tanh action saturation, the fixed-bucket
  TD-error histogram, and the suffix-keyed reduction convention that
  carries them through scan, mesh collectives and epoch aggregation.
- :mod:`monitor` — host-side drift detectors turning the epoch stream
  into early-warning events (grad spike, entropy collapse, Q-bias
  drift) feeding telemetry and the divergence sentinel as leading
  indicators.
- :mod:`watchdog` — the process-wide XLA recompilation watchdog:
  counts every backend compile with a source label and flags
  steady-state recompiles as anomalies in both training and serving.

Tiering is ``SACConfig.diagnostics``: ``off`` (default — compiled
graph and metric keys bitwise identical to an uninstrumented build),
``light`` (scalar diagnostics), ``full`` (light + TD histogram + dp
skew). The tier is baked into the traced update at construction, so it
is part of the jit identity and flipping it never aliases a cache
entry.
"""

from torch_actor_critic_tpu.diagnostics.ingraph import (
    TD_HIST_GROWTH,
    TD_HIST_HI,
    TD_HIST_LO,
    bucket_counts,
    cross_replica_reduce,
    global_norm,
    make_td_histogram,
    norm_ratio,
    reduce_burst_metrics,
    reduce_metric_rows,
    reduction_for,
    replica_skew,
    saturation_fraction,
    split_member_metrics,
)
from torch_actor_critic_tpu.diagnostics.monitor import (
    DEFAULT_RULES,
    DriftDetector,
    EarlyWarningMonitor,
)
from torch_actor_critic_tpu.diagnostics.watchdog import (
    RecompilationWatchdog,
    get_watchdog,
)

__all__ = [
    "DEFAULT_RULES",
    "DriftDetector",
    "EarlyWarningMonitor",
    "RecompilationWatchdog",
    "TD_HIST_GROWTH",
    "TD_HIST_HI",
    "TD_HIST_LO",
    "bucket_counts",
    "cross_replica_reduce",
    "get_watchdog",
    "global_norm",
    "make_td_histogram",
    "norm_ratio",
    "reduce_burst_metrics",
    "reduce_metric_rows",
    "reduction_for",
    "replica_skew",
    "saturation_fraction",
    "split_member_metrics",
]
